"""Metric-vocabulary discipline.

Everything downstream of the registry — the merged multi-process
exposition, rsdl_top, the history ring, the health detectors, the run
report — addresses metrics BY NAME, across process and repo boundaries.
A metric created under an ad-hoc name still renders and still exports;
nothing fails until an operator's dashboard quietly shows no data, which
is the worst possible failure mode for an ops plane. ``runtime/
metric_names.py`` is the one catalog those consumers are written
against; ``unregistered-metric`` closes the loop from the producer side:
every literal ``rsdl_*`` name passed to ``metrics.counter`` / ``gauge``
/ ``histogram`` / ``get`` in library code must be a catalog entry, so
adding a metric forces the one-line catalog review that keeps dashboards
and detectors truthful.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from ray_shuffling_data_loader_tpu.analysis.core import (FileContext, Rule,
                                                         Violation,
                                                         dotted_name,
                                                         register)

_REGISTRY_METHODS = frozenset({"counter", "gauge", "histogram", "sketch",
                               "get"})
#: Registry methods that CREATE series (label kwargs are label keys);
#: ``get`` is a read and takes labels as a dict argument instead.
_CREATE_METHODS = frozenset({"counter", "gauge", "histogram", "sketch"})
#: Non-label keyword arguments of the create methods.
_CONFIG_KWARGS = frozenset({"buckets"})
#: Receivers that look like the metrics registry module/object
#: (``metrics``, ``rt_metrics``, ``rsdl_metrics``, ``self._metrics``).
_RECEIVER_RE = re.compile(r"(^|[._])metrics$")
#: Histogram/sketch families expose derived series names in the text
#: format; a ``get`` against one resolves through its base name.
_SERIES_SUFFIXES = ("_bucket", "_centroid", "_sum", "_count")


def _catalog_names() -> frozenset:
    from ray_shuffling_data_loader_tpu.runtime.metric_names import NAMES
    return NAMES


def _catalog_labels(name: str):
    from ray_shuffling_data_loader_tpu.runtime.metric_names import (
        METRIC_NAMES)
    entry = METRIC_NAMES.get(name)
    return None if entry is None else frozenset(entry[1])


@register
class UnregisteredMetricRule(Rule):
    id = "unregistered-metric"
    category = "metrics"
    description = ("literal `rsdl_*` metric name not present in "
                   "runtime/metric_names.py: dashboards, rsdl_top, the "
                   "health detectors and the run report address metrics "
                   "by catalog name — an uncataloged metric silently "
                   "drops out of every one of them")

    def check(self, tree: ast.Module,
              ctx: FileContext) -> Iterator[Violation]:
        if not ctx.path_matches(ctx.config.metric_catalog_globs):
            return
        names = _catalog_names()
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if not (isinstance(func, ast.Attribute)
                    and func.attr in _REGISTRY_METHODS):
                continue
            if not _RECEIVER_RE.search(dotted_name(func.value)):
                continue
            if not node.args:
                continue
            first = node.args[0]
            if not (isinstance(first, ast.Constant)
                    and isinstance(first.value, str)):
                continue
            name = first.value
            if not name.startswith("rsdl_"):
                continue  # test_*/probe metrics are out of scope
            base = name
            for suffix in _SERIES_SUFFIXES:
                if name.endswith(suffix) and name[:-len(suffix)] in names:
                    base = name[:-len(suffix)]
                    break
            if base not in names:
                yield ctx.violation(
                    self, first,
                    f"metric name {name!r} is not in "
                    "runtime/metric_names.py — add it to the catalog "
                    "(one reviewed line) so dashboards/detectors/"
                    "reports can address it")


@register
class MetricLabelCardinalityRule(Rule):
    id = "metric-label-cardinality"
    category = "metrics"
    description = ("`rsdl_*` metric labeled with a key outside the "
                   "catalog's allowed label set (runtime/"
                   "metric_names.py) — per-task/per-seq/per-pid labels "
                   "mint one child series per value, exploding the "
                   "registry, every federation shard and every "
                   "history-ring snapshot without bound; labels must be "
                   "fixed-cardinality identities (stage, hop, shard, "
                   "trainer rank) declared in the catalog")

    def check(self, tree: ast.Module,
              ctx: FileContext) -> Iterator[Violation]:
        if not ctx.path_matches(ctx.config.metric_catalog_globs):
            return
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if not (isinstance(func, ast.Attribute)
                    and func.attr in _CREATE_METHODS):
                continue
            if not _RECEIVER_RE.search(dotted_name(func.value)):
                continue
            if not node.args:
                continue
            first = node.args[0]
            if not (isinstance(first, ast.Constant)
                    and isinstance(first.value, str)):
                continue
            name = first.value
            if not name.startswith("rsdl_"):
                continue
            allowed = _catalog_labels(name)
            if allowed is None:
                continue  # unregistered-metric already flags the name
            for keyword in node.keywords:
                if (keyword.arg is None
                        or keyword.arg in _CONFIG_KWARGS
                        or keyword.arg in allowed):
                    continue
                yield ctx.violation(
                    self, keyword.value,
                    f"label {keyword.arg!r} on {name!r} is outside its "
                    f"catalog label set {sorted(allowed)} — an "
                    "undeclared label is how unbounded values (task "
                    "ids, seqs, pids) leak into the series space; "
                    "declare it in runtime/metric_names.py only if its "
                    "value set is provably bounded")
