"""Storage-plane discipline.

Every dataset byte the pipeline reads is supposed to flow through
``storage/`` — the tiered (shm -> disk -> remote) cache, the
``storage_read``/``storage_stall`` chaos sites and the retry policy all
live at that boundary. A raw ``pyarrow.parquet`` read somewhere else
still works against a local filesystem, so nothing fails until the
dataset moves to a remote backend and that one code path silently reads
cold, uncached, un-injectable and un-retried. ``raw-dataset-read``
closes the hole from the producer side: library code opens datasets via
``storage.read_table`` / ``storage.open_parquet`` (or the ``fileio``
primitive the storage plane itself is built on), never ``pq.*``
directly.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ray_shuffling_data_loader_tpu.analysis.core import (FileContext, Rule,
                                                         Violation,
                                                         dotted_name,
                                                         register)

#: ``pyarrow.parquet`` entry points that materialize dataset bytes.
_PQ_READERS = frozenset({"read_table", "read_pandas", "ParquetFile",
                         "ParquetDataset", "read_schema", "read_metadata"})
#: Receiver tails that name the pyarrow.parquet module (``pq``,
#: ``parquet``, ``pyarrow.parquet``, ``pa.parquet``).
_PQ_RECEIVERS = frozenset({"pq", "parquet", "pyarrow.parquet",
                           "pa.parquet"})


@register
class RawDatasetReadRule(Rule):
    id = "raw-dataset-read"
    category = "storage"
    description = ("dataset read bypasses storage/ — a raw "
                   "`pyarrow.parquet` call skips the tiered cache, the "
                   "`storage_read`/`storage_stall` chaos sites and the "
                   "storage retry policy, so it silently reads cold and "
                   "unprotected the day the dataset moves to a remote "
                   "backend; go through `storage.read_table` / "
                   "`storage.open_parquet` (or utils/fileio inside the "
                   "storage plane)")

    def check(self, tree: ast.Module,
              ctx: FileContext) -> Iterator[Violation]:
        if not ctx.path_matches(ctx.config.dataset_read_globs):
            return
        if ctx.path_matches(ctx.config.dataset_read_exempt_globs):
            return
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if not (isinstance(func, ast.Attribute)
                    and func.attr in _PQ_READERS):
                continue
            receiver = dotted_name(func.value)
            if receiver not in _PQ_RECEIVERS:
                continue
            yield ctx.violation(
                self, node,
                f"raw `{receiver}.{func.attr}` bypasses the storage "
                "plane — route dataset reads through storage."
                "read_table / storage.open_parquet so they hit the "
                "tiered cache, the chaos sites and the retry policy")
