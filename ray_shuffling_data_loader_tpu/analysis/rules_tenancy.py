"""Tenancy discipline.

The tenancy plane (tenancy/) only works if EVERY entry point that
accepts new work into a shared plane — queue serving, storage warming,
stream registration — knows whose work it is. An entry point that
takes neither a tenant parameter nor resolves the ambient
``tenancy.current_tenant()`` admits unattributable bytes: they land on
the ``default`` tenant's ledger, dodge the fair-share scheduler and the
admission quotas, and the whole QoS story silently regresses to
first-come-first-served. Nothing fails loudly — single-tenant tests
pass forever — so ``tenant-context-bypass`` closes the hole
mechanically: functions named like entry points
(``config.tenancy_entry_names``) inside the serving/storage planes
(``config.tenancy_entry_globs``) must mention a tenant somewhere — a
parameter, an attribute, a config key — or they flag.
"""

from __future__ import annotations

import ast
import fnmatch
from typing import Iterator

from ray_shuffling_data_loader_tpu.analysis.core import (FileContext, Rule,
                                                         Violation,
                                                         register)


def _mentions_tenant(node: ast.AST) -> bool:
    """Does the function take a tenant-ish parameter or reference a
    tenant-ish name/attribute/string anywhere in its body?"""
    args = node.args
    params = (list(getattr(args, "posonlyargs", ())) + list(args.args)
              + list(args.kwonlyargs) + [args.vararg, args.kwarg])
    for param in params:
        if param is not None and "tenant" in param.arg.lower():
            return True
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and "tenant" in sub.id.lower():
            return True
        if isinstance(sub, ast.Attribute) and "tenant" in sub.attr.lower():
            return True
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str) \
                and "tenant" in sub.value.lower():
            return True
    return False


@register
class TenantContextBypassRule(Rule):
    id = "tenant-context-bypass"
    category = "tenancy"
    description = ("queue/storage entry point accepts work without a "
                   "TenantContext — bytes admitted here are "
                   "unattributable, so they bypass the weighted-fair "
                   "scheduler, the admission quotas and the per-tenant "
                   "cache partitions; take a tenant/tenants parameter "
                   "or resolve tenancy.current_tenant()")

    def check(self, tree: ast.Module,
              ctx: FileContext) -> Iterator[Violation]:
        if not ctx.path_matches(ctx.config.tenancy_entry_globs):
            return
        patterns = ctx.config.tenancy_entry_names
        for node in ast.walk(tree):
            if not isinstance(node,
                              (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not any(fnmatch.fnmatchcase(node.name, p)
                       for p in patterns):
                continue
            if _mentions_tenant(node):
                continue
            yield ctx.violation(
                self, node,
                f"`{node.name}` accepts work into a shared plane "
                "without a TenantContext — add a tenant/tenants "
                "parameter or resolve tenancy.current_tenant() so the "
                "bytes stay attributable to a tenant ledger")
