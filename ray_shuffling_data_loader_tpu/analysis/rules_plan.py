"""Lineage-key discipline: derivation belongs to ``plan/``.

PR 9 reified the pipeline's determinism contract as an explicit epoch
plan (plan/ir.py): the route-key arithmetic
(``queue = epoch * num_trainers + rank`` and its ``//`` / ``%``
inverses) and the per-task lineage RNG streams live in exactly one
place, and every resume/recovery/chaos consumer queries the plan. The
historical failure mode was drift: five modules each re-deriving the
same keys with private arithmetic, where one edited formula silently
de-synchronizes replay from delivery. ``lineage-outside-plan`` pins the
invariant mechanically: fresh key-derivation arithmetic in library code
outside ``plan/`` (and the RNG primitive ``ops/partition.py``) is
flagged — call ``plan.ir.queue_index`` / ``queue_epoch`` /
``queue_rank`` / ``resume_from_watermarks`` instead.
"""

from __future__ import annotations

import ast
from typing import Iterator, Set

from ray_shuffling_data_loader_tpu.analysis.core import (FileContext, Rule,
                                                         Violation,
                                                         register)


def _name_words(node: ast.AST) -> Set[str]:
    """Lower-cased identifier words reachable in a subtree (Name ids and
    Attribute attrs) — ``self._num_trainers`` contributes
    ``_num_trainers``."""
    words: Set[str] = set()
    for child in ast.walk(node):
        if isinstance(child, ast.Name):
            words.add(child.id.lower())
        elif isinstance(child, ast.Attribute):
            words.add(child.attr.lower())
    return words


def _mentions(words: Set[str], stem: str) -> bool:
    return any(stem in w for w in words)


@register
class LineageOutsidePlanRule(Rule):
    id = "lineage-outside-plan"
    category = "plan"
    description = ("fresh (seed, epoch, task) key-derivation arithmetic "
                   "outside plan/ — resume/recovery must query the epoch "
                   "plan (plan.ir.queue_index/queue_epoch/queue_rank/"
                   "resume_from_watermarks), not re-derive keys that can "
                   "drift from the engine's")

    def check(self, tree: ast.Module,
              ctx: FileContext) -> Iterator[Violation]:
        if not ctx.path_matches(ctx.config.lineage_plan_globs):
            return
        if ctx.path_matches(ctx.config.lineage_plan_exempt_globs):
            return
        for node in ast.walk(tree):
            if isinstance(node, ast.BinOp):
                violation = self._check_binop(node, ctx)
                if violation is not None:
                    yield violation
            elif isinstance(node, ast.Call):
                violation = self._check_seedseq(node, ctx)
                if violation is not None:
                    yield violation

    def _check_binop(self, node: ast.BinOp,
                     ctx: FileContext):
        # Forward derivation: `epoch * num_trainers + rank` — an Add
        # whose subtree multiplies an epoch-ish name by a trainer-count
        # name and offsets by a rank-ish name.
        if isinstance(node.op, ast.Add):
            for mult, other in ((node.left, node.right),
                                (node.right, node.left)):
                if not (isinstance(mult, ast.BinOp)
                        and isinstance(mult.op, ast.Mult)):
                    continue
                mult_words = _name_words(mult)
                other_words = _name_words(other)
                if (_mentions(mult_words, "epoch")
                        and _mentions(mult_words, "trainer")
                        and _mentions(other_words, "rank")):
                    return ctx.violation(
                        self, node,
                        "queue-route key derived inline "
                        "(epoch * num_trainers + rank); use "
                        "plan.ir.queue_index(epoch, rank, num_trainers)")
        # Inverse derivation: `queue_idx // num_trainers` (epoch) and
        # `queue_idx % num_trainers` (rank). Keyed on the trainer-COUNT
        # name specifically: dividing by e.g. `trainers_per_host` is a
        # topology mapping, not a queue-route key.
        if isinstance(node.op, (ast.FloorDiv, ast.Mod)):
            right_words = _name_words(node.right)
            if _mentions(right_words, "num_trainers"):
                helper = ("queue_epoch" if isinstance(node.op, ast.FloorDiv)
                          else "queue_rank")
                return ctx.violation(
                    self, node,
                    "queue-route key inverted inline "
                    f"(queue {'//' if helper == 'queue_epoch' else '%'} "
                    "num_trainers); use "
                    f"plan.ir.{helper}(queue_idx, num_trainers)")
        return None

    def _check_seedseq(self, node: ast.Call, ctx: FileContext):
        # A fresh per-task lineage RNG stream: SeedSequence keyed by BOTH
        # a seed and an epoch. The only blessed homes are ops/partition.py
        # (the primitive) and plan/ — anything else is a private lineage
        # stream recovery cannot reproduce by querying the plan.
        func = node.func
        name = (func.attr if isinstance(func, ast.Attribute)
                else func.id if isinstance(func, ast.Name) else "")
        if name != "SeedSequence":
            return None
        words = _name_words(ast.Module(body=[ast.Expr(value=arg)
                                             for arg in node.args],
                                       type_ignores=[]))
        for kw in node.keywords:
            words |= _name_words(kw.value)
        if _mentions(words, "seed") and _mentions(words, "epoch"):
            return ctx.violation(
                self, node,
                "fresh (seed, epoch, ...) SeedSequence stream outside "
                "plan/ops — derive task RNG through the plan's lineage "
                "keys (ops.partition map_rng/reduce_rng)")
        return None


@register
class StaticEpochAssumptionRule(Rule):
    id = "static-epoch-assumption"
    category = "plan"
    description = ("library code counting epochs with range(num_epochs) "
                   "or indexing per-epoch state by a literal epoch — the "
                   "epoch sequence belongs to plan/ "
                   "(plan.ir.epoch_range / static_epoch_specs); a static "
                   "count silently breaks unbounded streaming input")

    def check(self, tree: ast.Module,
              ctx: FileContext) -> Iterator[Violation]:
        if not ctx.path_matches(ctx.config.static_epoch_globs):
            return
        if ctx.path_matches(ctx.config.static_epoch_exempt_globs):
            return
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                violation = self._check_range(node, ctx)
                if violation is not None:
                    yield violation
            elif isinstance(node, ast.Subscript):
                violation = self._check_subscript(node, ctx)
                if violation is not None:
                    yield violation

    def _check_range(self, node: ast.Call, ctx: FileContext):
        # `range(num_epochs)` / `range(start, self.num_epochs)`: a hard
        # assumption that the trial's epoch count is finite and known up
        # front. Streaming windows arrive as epochs with no count;
        # plan.ir.epoch_range handles both shapes (None = unbounded) and
        # plan.ir.static_epoch_specs IS the bounded schedule.
        func = node.func
        if not (isinstance(func, ast.Name) and func.id == "range"):
            return None
        for arg in node.args:
            if _mentions(_name_words(arg), "num_epochs"):
                return ctx.violation(
                    self, node,
                    "epochs counted with range(..num_epochs..); iterate "
                    "plan.ir.epoch_range(start, num_epochs) (None = "
                    "unbounded stream) or consume "
                    "plan.ir.static_epoch_specs")
        return None

    def _check_subscript(self, node: ast.Subscript, ctx: FileContext):
        # `epoch_refs[2]` / `per_epoch[0]`: per-epoch state indexed by a
        # literal epoch — code that can only be correct for one frozen
        # epoch numbering. Dynamic indices (loop variables, plan-derived
        # epochs) are fine.
        if not isinstance(node.slice, ast.Constant):
            return None
        if not isinstance(node.slice.value, int):
            return None
        words = _name_words(node.value)
        per_epoch = any(
            ("epoch" in w and ("ref" in w or "plan" in w or "queue" in w))
            or w in ("per_epoch", "epochs")
            for w in words)
        if per_epoch:
            return ctx.violation(
                self, node,
                "per-epoch state indexed by a literal epoch number — "
                "derive the index from the plan (plan.ir.queue_index / "
                "the EpochSpec being served), not a frozen count")
        return None


@register
class ShardAffinityAssumptionRule(Rule):
    id = "shard-affinity-assumption"
    category = "plan"
    description = ("library code deriving queue->shard placement with "
                   "literal num_shards arithmetic or resolving/caching a "
                   "shard's (host, port) by index — placement moves "
                   "under live rebalancing (rebalance/), so routing must "
                   "query ShardMap.shard_for_queue / address_for_queue "
                   "at call time")

    def check(self, tree: ast.Module,
              ctx: FileContext) -> Iterator[Violation]:
        if not ctx.path_matches(ctx.config.shard_affinity_globs):
            return
        if ctx.path_matches(ctx.config.shard_affinity_exempt_globs):
            return
        for node in ast.walk(tree):
            if isinstance(node, ast.BinOp):
                violation = self._check_binop(node, ctx)
                if violation is not None:
                    yield violation
            elif isinstance(node, ast.Subscript):
                violation = self._check_subscript(node, ctx)
                if violation is not None:
                    yield violation

    def _check_binop(self, node: ast.BinOp, ctx: FileContext):
        # `rank % num_shards` / `q // num_shards` / `x * num_shards`:
        # the STATIC placement formula. Correct on a fresh plan, stale
        # the moment a committed migration installs an override — the
        # consumer keeps dialing the pre-move shard and eats a failure
        # frame (or worse, a zombie's stream).
        if not isinstance(node.op, (ast.Mod, ast.FloorDiv, ast.Mult)):
            return None
        sides = ([node.left, node.right]
                 if isinstance(node.op, ast.Mult) else [node.right])
        for side in sides:
            if _mentions(_name_words(side), "num_shards"):
                return ctx.violation(
                    self, node,
                    "queue->shard placement derived with literal "
                    "num_shards arithmetic; query plan.ir.ShardMap."
                    "shard_for_queue/shard_for_rank — overrides from "
                    "live rebalancing make the static formula stale")
        return None

    def _check_subscript(self, node: ast.Subscript, ctx: FileContext):
        # `shard_map.addresses[shard]`: a shard address resolved by
        # index — the caller is about to cache a (host, port) that a
        # committed migration invalidates. `address_for_queue` (or the
        # MOVED-following ShardedRemoteQueue) re-resolves per call.
        words = _name_words(node.value)
        if not _mentions(words, "addresses"):
            return None
        if not _mentions(_name_words(node.slice), "shard"):
            return None
        return ctx.violation(
            self, node,
            "shard (host, port) resolved by address-table index; use "
            "plan.ir.ShardMap.address_for_queue (or route through "
            "ShardedRemoteQueue, which follows MOVED redirects) — "
            "cached shard addresses go stale under live rebalancing")


@register
class FixedWorldAssumptionRule(Rule):
    id = "fixed-world-assumption"
    category = "plan"
    description = ("library code fanning out over a frozen world size "
                   "(range(..world..) / len(addresses)) or scaling by "
                   "it — world composition is a membership view "
                   "(membership/), and placement over live ranks "
                   "belongs to plan.ir.rebalance_spans / "
                   "reduce_placement; frozen-world arithmetic silently "
                   "breaks elastic resize")

    #: Identifier stems that name a world/host count.
    _WORLD_STEMS = ("world", "num_hosts", "num_ranks")
    #: Identifier stems whose len() is a world size in disguise.
    _ROSTER_STEMS = ("addresses", "hosts", "peers")

    def check(self, tree: ast.Module,
              ctx: FileContext) -> Iterator[Violation]:
        if not ctx.path_matches(ctx.config.fixed_world_globs):
            return
        if ctx.path_matches(ctx.config.fixed_world_exempt_globs):
            return
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                violation = self._check_range(node, ctx)
                if violation is not None:
                    yield violation
            elif isinstance(node, ast.BinOp):
                violation = self._check_binop(node, ctx)
                if violation is not None:
                    yield violation

    def _world_sized(self, node: ast.AST) -> bool:
        # A world-count name (`self.world`, `num_hosts`) or the length
        # of a host roster (`len(self.addresses)`, `len(peers)`).
        for stem in self._WORLD_STEMS:
            if _mentions(_name_words(node), stem):
                return True
        for child in ast.walk(node):
            if (isinstance(child, ast.Call)
                    and isinstance(child.func, ast.Name)
                    and child.func.id == "len" and child.args):
                words = _name_words(child.args[0])
                if any(_mentions(words, s) for s in self._ROSTER_STEMS):
                    return True
        return False

    def _check_range(self, node: ast.Call, ctx: FileContext):
        # `range(world)` / `range(len(self.addresses))`: a fan-out that
        # hard-assumes every configured rank is alive. The live set is
        # a membership view; placement over it is
        # plan.ir.rebalance_spans / reduce_placement.
        func = node.func
        if not (isinstance(func, ast.Name) and func.id == "range"):
            return None
        if any(self._world_sized(arg) for arg in node.args):
            return ctx.violation(
                self, node,
                "fan-out over a frozen world size "
                "(range(..world../len(addresses)..)); iterate a "
                "membership view's live ranks and place with "
                "plan.ir.rebalance_spans / reduce_placement")
        return None

    def _check_binop(self, node: ast.BinOp, ctx: FileContext):
        # `x * world` / `q % world` / `n // world`: per-rank shares
        # computed from the configured size — wrong the moment the
        # world shrinks or grows. (Add/Sub are untouched: offsets over
        # a roster are topology math, not a share split.)
        if not isinstance(node.op, (ast.Mult, ast.Mod, ast.FloorDiv)):
            return None
        sides = [node.left, node.right] if isinstance(node.op, ast.Mult) \
            else [node.right]
        for side in sides:
            for stem in self._WORLD_STEMS:
                if _mentions(_name_words(side), stem):
                    return ctx.violation(
                        self, node,
                        "per-rank share scaled by a frozen world size; "
                        "derive shares from the live membership view "
                        "(plan.ir.rebalance_spans over view.ranks)")
        return None
