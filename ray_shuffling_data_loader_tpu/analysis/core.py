"""Core infrastructure for rsdl-lint, the project-invariant analyzer.

This repo reproduces the paper's pipelined shuffle as a lock-heavy,
multi-threaded host pipeline, and several of its correctness contracts
live in prose (executor.py's "one-shot consumers must use submit_once",
the (seed, epoch, task) determinism contract that makes task retries
safe, the Arrow >2GiB offset-promotion rules). Each of those contracts
is mechanically checkable, and this module is the frame that checks
them: an AST-walking rule registry, per-rule configuration, inline
``# rsdl-lint: disable=<rule>`` pragmas, a checked-in baseline file for
grandfathered findings, and human/JSON reporting with a stable
exit-code contract (0 clean, 1 violations, 2 usage/internal error).

Rules live in the sibling ``rules_*`` modules and register themselves
via :func:`register`; everything here is stdlib-only so the gate runs
on minimal images (format.sh).

Two registries coexist: per-file :class:`Rule` subclasses (the
original 22 checks, one parsed module at a time) and whole-program
:class:`ProgramRule` subclasses (``rules_concurrency``'s
``inconsistent-lock-order`` and ``unguarded-shared-mutation``, which
need the cross-module call graph from ``callgraph.py``/``locksets.py``
and only run under ``--concurrency``). Both share the same pragma,
baseline, and reporting machinery — a program-rule violation is still
anchored to one ``path:line`` and suppressible there.
"""

from __future__ import annotations

import ast
import dataclasses
import fnmatch
import io
import os
import re
import tokenize
from typing import (Dict, Iterable, Iterator, List, Optional, Sequence, Set,
                    Tuple)

#: Exit-code contract shared by the CLI and format.sh.
EXIT_CLEAN = 0
EXIT_VIOLATIONS = 1
EXIT_ERROR = 2


@dataclasses.dataclass
class Violation:
    """One finding: ``path:line:col: rule message``."""

    rule: str
    path: str  # repo-relative, forward slashes
    line: int
    col: int
    message: str
    snippet: str = ""  # stripped source line, used for baselining

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} " \
               f"{self.message}"

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class Config:
    """Per-rule knobs, overridable via ``--config <json>`` (keys are the
    field names; unknown keys are an error so typos fail loudly)."""

    # Attribute/variable names treated as locks for the lock rules.
    lock_name_regex: str = r"(?i)(lock|mutex)"
    # Attribute calls that block indefinitely when called with no
    # timeout while a lock is held.
    blocking_methods: Tuple[str, ...] = ("result", "join", "recv")
    # ``.get(...)`` blocks unless it passes ``timeout=`` or
    # ``block=False`` — queue.get / MultiQueue.get / BoundedFifo.get.
    blocking_get_methods: Tuple[str, ...] = ("get",)
    # ``.get`` is only treated as a BLOCKING get when its receiver looks
    # like a queue (otherwise every dict.get would flag) or the call
    # passes ``block=True`` explicitly.
    queue_name_regex: str = r"(?i)(queue|fifo|inbox)"
    # Function tails (``ex.wait``, ``time.sleep``) that block under a
    # lock when called without a timeout kwarg.
    blocking_functions: Tuple[str, ...] = ("wait", "sleep")
    # Method names whose call marks a function as a one-shot transport
    # consumer (it must be submitted via submit_once, never submit).
    oneshot_recv_methods: Tuple[str, ...] = ("recv",)
    # Extra function names to treat as one-shot consumers even without a
    # visible ``.recv`` call (cross-module consumers).
    oneshot_functions: Tuple[str, ...] = ()
    # fnmatch patterns of function names whose loops are prefetch/ingest
    # hot paths: host syncs inside their loops stall the pipeline.
    hot_loop_functions: Tuple[str, ...] = ("_persistent_producer",
                                           "_produce_epoch_tables",
                                           "*prefetch*", "producer",
                                           "*hot_loop*")
    # fnmatch patterns (against the repo-relative posix path) of files
    # whose device_put calls must carry an explicit sharding/device.
    sharded_path_globs: Tuple[str, ...] = ("*parallel/*",)
    # Module-level numpy.random draws (global, unseeded RNG state).
    unseeded_random_names: Tuple[str, ...] = (
        "rand", "randn", "randint", "random", "random_sample", "ranf",
        "sample", "choice", "shuffle", "permutation", "bytes", "normal",
        "uniform", "standard_normal", "exponential", "poisson", "binomial",
        "beta", "gamma", "seed")
    # stdlib ``random`` module draws (same hazard).
    stdlib_random_names: Tuple[str, ...] = (
        "random", "randint", "randrange", "choice", "choices", "shuffle",
        "sample", "uniform", "gauss", "normalvariate", "seed")
    # fnmatch patterns of files whose literal rsdl_* metric names must
    # come from runtime/metric_names.py (library code; tests may mint
    # throwaway test_* names, which the rule ignores by prefix anyway).
    metric_catalog_globs: Tuple[str, ...] = (
        "ray_shuffling_data_loader_tpu/*", "bench.py")
    # fnmatch patterns of library files where fresh (seed, epoch, task)
    # key-derivation arithmetic is a lineage-outside-plan violation —
    # resume/recovery must query plan/ir.py, not re-derive keys.
    lineage_plan_globs: Tuple[str, ...] = (
        "ray_shuffling_data_loader_tpu/*",)
    # Files exempt from lineage-outside-plan: the plan IR itself (the
    # one home of the arithmetic) and the RNG-stream primitive the plan
    # contract is defined in terms of.
    lineage_plan_exempt_globs: Tuple[str, ...] = (
        "*ray_shuffling_data_loader_tpu/plan/*",
        "*ray_shuffling_data_loader_tpu/ops/partition.py")
    # fnmatch patterns of library files where dataset bytes must flow
    # through storage/ (the tiered cache + chaos-site boundary), never
    # raw pyarrow.parquet reads.
    dataset_read_globs: Tuple[str, ...] = (
        "ray_shuffling_data_loader_tpu/*", "bench.py")
    # Files exempt from raw-dataset-read: the storage plane itself and
    # the low-level fileio primitive it is built on.
    dataset_read_exempt_globs: Tuple[str, ...] = (
        "*ray_shuffling_data_loader_tpu/storage/*",
        "*ray_shuffling_data_loader_tpu/utils/fileio.py")
    # fnmatch patterns of library files where counting epochs with
    # range(num_epochs) (or literal-epoch indexing of per-epoch state)
    # is a static-epoch-assumption violation — the epoch sequence
    # belongs to plan/ so unbounded streaming input keeps working.
    static_epoch_globs: Tuple[str, ...] = (
        "ray_shuffling_data_loader_tpu/*",)
    # Exempt: plan/ enumerates the schedule (epoch_range /
    # static_epoch_specs live there) and streaming/ derives epochs from
    # windows by construction.
    static_epoch_exempt_globs: Tuple[str, ...] = (
        "*ray_shuffling_data_loader_tpu/plan/*",
        "*ray_shuffling_data_loader_tpu/streaming/*")
    # fnmatch patterns of library files where arithmetic over a frozen
    # world size (range(..world..) / len(self.addresses) fan-outs) is a
    # fixed-world-assumption violation — world composition belongs to
    # membership/ (views) and plan/ (rebalance_spans /
    # reduce_placement), so an elastic resize keeps working.
    fixed_world_globs: Tuple[str, ...] = (
        "ray_shuffling_data_loader_tpu/*",)
    # Exempt: membership/ defines views, plan/ owns the rebalance
    # arithmetic, and the transport's address table is the dial list
    # membership layers liveness on top of.
    fixed_world_exempt_globs: Tuple[str, ...] = (
        "*ray_shuffling_data_loader_tpu/membership/*",
        "*ray_shuffling_data_loader_tpu/plan/*")
    # fnmatch patterns of library files where literal queue->shard
    # arithmetic (.. % num_shards) or indexed shard-address lookups
    # (shard_map.addresses[shard]) are a shard-affinity-assumption
    # violation — placement moves under live rebalancing (rebalance/),
    # so routing must query ShardMap.shard_for_queue /
    # address_for_queue at call time.
    shard_affinity_globs: Tuple[str, ...] = (
        "ray_shuffling_data_loader_tpu/*",)
    # Exempt: plan/ owns the placement arithmetic, rebalance/ journals
    # and rewrites it, and the serving plane implements the MOVED
    # redirect protocol itself (its cached routes are invalidated by
    # the redirect, by construction).
    shard_affinity_exempt_globs: Tuple[str, ...] = (
        "*ray_shuffling_data_loader_tpu/plan/*",
        "*ray_shuffling_data_loader_tpu/rebalance/*",
        "*ray_shuffling_data_loader_tpu/multiqueue_service.py")
    # fnmatch patterns of files included in the whole-program
    # concurrency pass (--concurrency). Library code only: tests spin
    # throwaway threads/locks with no cross-module ordering contract.
    concurrency_globs: Tuple[str, ...] = (
        "ray_shuffling_data_loader_tpu/*",)
    # ...minus these: the runtime lock sanitizer sits BELOW the lock
    # abstraction (its proxies wrap and forward acquire/release/wait),
    # so treating its classes as call-resolution targets invents
    # edges from every condition-wait in the package.
    concurrency_exclude_globs: Tuple[str, ...] = ("*locksan.py",)
    # unguarded-shared-mutation flags a bare write only when at least
    # this many OTHER sites write the same attribute under a lock.
    concurrency_min_guarded_sites: int = 1
    # fnmatch patterns of files whose serving/storage entry points must
    # be tenant-aware (tenancy/: every byte in flight attributable).
    tenancy_entry_globs: Tuple[str, ...] = (
        "ray_shuffling_data_loader_tpu/multiqueue_service.py",
        "ray_shuffling_data_loader_tpu/storage/*",
        "ray_shuffling_data_loader_tpu/streaming/runner.py",
        "ray_shuffling_data_loader_tpu/tenancy/*")
    # fnmatch patterns of function names that ARE tenancy entry points:
    # they accept new work into a shared plane, so they must take a
    # tenant-ish parameter or resolve tenancy.current_tenant().
    tenancy_entry_names: Tuple[str, ...] = (
        "serve_queue", "serve_pipeline", "server_config", "register",
        "make_prefetcher")
    # fnmatch patterns of files that assemble the bench JSON record —
    # their numeric emissions must be gated by a rsdl_bench_diff rule
    # or declared informational (rules_bench.py).
    bench_record_globs: Tuple[str, ...] = ("bench.py", "*/bench.py")

    @classmethod
    def from_dict(cls, data: dict) -> "Config":
        fields = {f.name for f in dataclasses.fields(cls)}
        unknown = set(data) - fields
        if unknown:
            raise ValueError(f"unknown config keys: {sorted(unknown)}")
        coerced = {
            k: tuple(v) if isinstance(v, list) else v
            for k, v in data.items()
        }
        return cls(**coerced)


class Rule:
    """One invariant checker. Subclasses set ``id``/``category``/
    ``description`` and implement :meth:`check` as a generator of
    :class:`Violation` over a parsed module."""

    id: str = ""
    category: str = ""
    description: str = ""

    def check(self, tree: ast.Module,
              ctx: "FileContext") -> Iterator[Violation]:
        raise NotImplementedError

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<Rule {self.id}>"


_REGISTRY: Dict[str, Rule] = {}


def register(cls):
    """Class decorator: instantiate and index the rule by id."""
    rule = cls()
    assert rule.id and rule.id not in _REGISTRY, rule.id
    _REGISTRY[rule.id] = rule
    return cls


def all_rules() -> Dict[str, Rule]:
    """The registry, with the built-in rule modules imported."""
    from ray_shuffling_data_loader_tpu.analysis import (  # noqa: F401
        rules_arrow, rules_bench, rules_executor, rules_hygiene, rules_jax,
        rules_lock, rules_metrics, rules_perf, rules_plan, rules_runtime,
        rules_storage, rules_telemetry, rules_tenancy)
    return dict(_REGISTRY)


class ProgramRule:
    """One whole-program invariant checker (``--concurrency`` pass).

    Unlike :class:`Rule`, ``check_program`` sees every module of the
    package at once (a ``callgraph.Program``) plus the finished
    ``locksets.LockAnalysis``; each yielded :class:`Violation` must
    still anchor to a single real ``path:line`` so pragmas and the
    baseline apply exactly as they do for per-file rules.
    """

    id: str = ""
    category: str = ""
    description: str = ""

    def check_program(self, program, analysis, config: "Config",
                      locksan_graph: Optional[dict] = None
                      ) -> Iterator[Violation]:
        raise NotImplementedError

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<ProgramRule {self.id}>"


_PROGRAM_REGISTRY: Dict[str, ProgramRule] = {}


def register_program(cls):
    """Class decorator: instantiate and index a whole-program rule."""
    rule = cls()
    assert rule.id and rule.id not in _PROGRAM_REGISTRY, rule.id
    _PROGRAM_REGISTRY[rule.id] = rule
    return cls


def program_rules() -> Dict[str, ProgramRule]:
    """The whole-program registry (kept separate from :func:`all_rules`
    so per-file tooling — fixture-coverage tests, --select over file
    rules — keeps its closed-world assumption)."""
    from ray_shuffling_data_loader_tpu.analysis import (  # noqa: F401
        rules_concurrency)
    return dict(_PROGRAM_REGISTRY)


class FileContext:
    """Everything a rule needs about the file under analysis."""

    def __init__(self, path: str, source: str, config: Config):
        self.path = path.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.config = config

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def violation(self, rule: Rule, node: ast.AST, message: str) -> Violation:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        return Violation(rule=rule.id, path=self.path, line=line, col=col,
                         message=message, snippet=self.line_text(line))

    def path_matches(self, globs: Sequence[str]) -> bool:
        return any(fnmatch.fnmatch(self.path, g) for g in globs)


# ---------------------------------------------------------------------------
# AST helpers shared by the rule modules
# ---------------------------------------------------------------------------


def dotted_name(node: ast.AST) -> str:
    """``a.b.c`` for an Attribute/Name chain; unknown bases become ``?``."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    else:
        parts.append("?")
    return ".".join(reversed(parts))


def keyword_names(call: ast.Call) -> Set[str]:
    return {kw.arg for kw in call.keywords if kw.arg is not None}


def get_keyword(call: ast.Call, name: str) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def is_constant(node: Optional[ast.expr], value) -> bool:
    return isinstance(node, ast.Constant) and node.value is value


# ---------------------------------------------------------------------------
# Pragmas
# ---------------------------------------------------------------------------

# Matched anywhere inside a COMMENT token (never in strings/docstrings),
# so a pragma can follow its justification prose on the same line.
PRAGMA_RE = re.compile(
    r"rsdl-lint\s*:\s*(disable-file|disable)\s*=\s*"
    r"([A-Za-z0-9_\-]+(?:\s*,\s*[A-Za-z0-9_\-]+)*|all)")


class Pragmas:
    """Inline suppressions.

    ``# rsdl-lint: disable=<rule>[,<rule>...]`` on a line suppresses
    those rules on that line; on a line of its own it also covers the
    next line (for statements whose flagged call starts one line down).
    ``# rsdl-lint: disable-file=<rule>`` suppresses for the whole file.
    ``all`` disables every rule.
    """

    def __init__(self, source: str):
        self.file_disables: Set[str] = set()
        self.line_disables: Dict[int, Set[str]] = {}
        self.standalone_lines: Set[int] = set()
        try:
            tokens = tokenize.generate_tokens(io.StringIO(source).readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                match = PRAGMA_RE.search(tok.string)
                if match is None:
                    continue
                rules = {r.strip() for r in match.group(2).split(",")}
                if match.group(1) == "disable-file":
                    self.file_disables |= rules
                else:
                    line = tok.start[0]
                    self.line_disables.setdefault(line, set()).update(rules)
                    if tok.line[:tok.start[1]].strip() == "":
                        self.standalone_lines.add(line)
        except (tokenize.TokenError, IndentationError, SyntaxError):
            pass  # the AST parse reports the real problem

    def _disabled_at(self, line: int) -> Set[str]:
        return self.line_disables.get(line, set())

    def suppresses(self, violation: Violation) -> bool:
        for rules in (self.file_disables,
                      self._disabled_at(violation.line)):
            if violation.rule in rules or "all" in rules:
                return True
        prev = violation.line - 1
        if prev in self.standalone_lines:
            rules = self._disabled_at(prev)
            return violation.rule in rules or "all" in rules
        return False


# ---------------------------------------------------------------------------
# Runner
# ---------------------------------------------------------------------------


def check_source(source: str, path: str, config: Optional[Config] = None,
                 rules: Optional[Iterable[Rule]] = None) -> List[Violation]:
    """Run rules over one source text; applies pragmas, not baselines."""
    config = config or Config()
    if rules is None:
        rules = all_rules().values()
    ctx = FileContext(path, source, config)
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Violation(rule="parse-error", path=ctx.path,
                          line=e.lineno or 1, col=(e.offset or 1) - 1,
                          message=f"could not parse: {e.msg}")]
    pragmas = Pragmas(source)
    out: List[Violation] = []
    for rule in rules:
        for violation in rule.check(tree, ctx):
            if not pragmas.suppresses(violation):
                out.append(violation)
    out.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    return out


def iter_python_files(paths: Sequence[str],
                      root: Optional[str] = None) -> Iterator[str]:
    """Expand files/dirs into .py files, skipping hidden and cache dirs."""
    for path in paths:
        full = os.path.join(root, path) if root else path
        if os.path.isfile(full):
            yield full
            continue
        for dirpath, dirnames, filenames in os.walk(full):
            dirnames[:] = sorted(d for d in dirnames
                                 if not d.startswith(".")
                                 and d != "__pycache__")
            for name in sorted(filenames):
                if name.endswith(".py"):
                    yield os.path.join(dirpath, name)


def check_paths(paths: Sequence[str], config: Optional[Config] = None,
                rules: Optional[Iterable[Rule]] = None,
                root: Optional[str] = None
                ) -> Tuple[List[Violation], int]:
    """Run the analyzer over files/directories.

    Returns ``(violations, files_checked)``. Paths inside ``root`` are
    reported relative to it so baselines are machine-independent.
    """
    base = os.path.abspath(root or os.getcwd())
    violations: List[Violation] = []
    count = 0
    for filename in iter_python_files(paths, root=root):
        count += 1
        rel = os.path.relpath(os.path.abspath(filename), base)
        if rel.startswith(".."):
            rel = filename  # outside root: report as given
        try:
            with open(filename, "r", encoding="utf-8") as f:
                source = f.read()
        except (OSError, UnicodeDecodeError) as e:
            violations.append(Violation(
                rule="read-error", path=rel.replace(os.sep, "/"), line=1,
                col=0, message=f"could not read file: {e}"))
            continue
        violations.extend(check_source(source, rel, config, rules))
    violations.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    return violations, count


def check_program_paths(paths: Sequence[str],
                        config: Optional[Config] = None,
                        rules: Optional[Iterable[ProgramRule]] = None,
                        root: Optional[str] = None,
                        locksan_graph: Optional[dict] = None
                        ) -> Tuple[List[Violation], "object"]:
    """Run the whole-program concurrency pass over the library files
    among ``paths`` (those matching ``config.concurrency_globs``).

    Returns ``(violations, analysis)`` — the ``LockAnalysis`` rides
    along so the CLI can emit the static order graph. Pragmas apply
    per anchored file/line exactly as in :func:`check_source`;
    baselines are the caller's job (the CLI applies one pass over the
    combined finding list).
    """
    from ray_shuffling_data_loader_tpu.analysis import callgraph, locksets
    config = config or Config()
    if rules is None:
        rules = program_rules().values()
    program = callgraph.Program.load(paths, root=root)
    for path in list(program.modules_by_path):
        if not any(fnmatch.fnmatch(path, g)
                   for g in config.concurrency_globs) or \
                any(fnmatch.fnmatch(path, g)
                    for g in config.concurrency_exclude_globs):
            mod = program.modules_by_path.pop(path)
            program.modules.pop(mod.name, None)
    program.index()
    analysis = locksets.analyze(program, config)
    pragmas = {mod.path: Pragmas(mod.source)
               for mod in program.modules.values()}
    out: List[Violation] = []
    for rule in rules:
        for violation in rule.check_program(program, analysis, config,
                                            locksan_graph=locksan_graph):
            file_pragmas = pragmas.get(violation.path)
            if file_pragmas is None or \
                    not file_pragmas.suppresses(violation):
                out.append(violation)
    out.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    return out, analysis
