"""Lightweight whole-program module/call-graph for rsdl-lint.

The per-file rules in ``rules_*`` see one ``ast.Module`` at a time,
which is exactly the wrong granularity for concurrency contracts: a
method that mutates ``self._states`` without ``self._states_lock`` is
fine when every caller already holds the lock, and a lock-order
inversion by definition spans at least two acquisition sites that may
live in different modules. This module gives the concurrency pass
(:mod:`.locksets`, :mod:`.rules_concurrency`) the minimum
interprocedural substrate: every module of the package parsed once, a
function index keyed by ``module:Class.method`` qualnames, per-module
import tables, and best-effort resolution of call expressions to those
qualnames.

Resolution is deliberately conservative — ``self.m()`` within the
defining class, bare names within the defining module, and
``alias.attr()`` through the import table. Anything dynamic (bound
methods passed around, getattr, duck-typed receivers) resolves to
``None`` and the downstream analyses treat the call as opaque. A
linter that under-resolves misses edges; one that over-resolves
invents deadlocks. Stdlib-only, like the rest of ``analysis/``.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

from ray_shuffling_data_loader_tpu.analysis import core


class ModuleInfo:
    """One parsed module of the program under analysis."""

    __slots__ = ("name", "path", "source", "tree", "imports",
                 "global_names")

    def __init__(self, name: str, path: str, source: str, tree: ast.Module):
        self.name = name          # dotted module name ("pkg.sub.mod")
        self.path = path          # repo-relative posix path
        self.source = source
        self.tree = tree
        #: local alias -> dotted module name (``import x.y as z``,
        #: ``from pkg import mod``).
        self.imports: Dict[str, str] = {}
        #: names bound at module level (globals candidates).
        self.global_names: "set[str]" = set()


class FunctionInfo:
    """One function/method definition, addressable by qualname."""

    __slots__ = ("qualname", "module", "cls", "name", "node")

    def __init__(self, qualname: str, module: ModuleInfo,
                 cls: Optional[str], node: ast.AST):
        self.qualname = qualname  # "mod:Class.method" or "mod:func"
        self.module = module
        self.cls = cls            # class name or None
        self.name = node.name
        self.node = node


#: Method names shared with builtin containers / threading primitives /
#: sockets / futures. The unique-method fallback must never fire on
#: these: a program class happening to define ``append`` would swallow
#: every ``list.append`` in the package and invent call edges (a real
#: incident: ``FaultInjector.check``'s list append resolving to
#: ``StreamJournal.append`` manufactured lock-order edges out of thin
#: air).
_GENERIC_METHODS = frozenset({
    "append", "extend", "insert", "remove", "pop", "popitem", "clear",
    "update", "setdefault", "add", "discard", "sort", "reverse", "copy",
    "count", "index", "get", "put", "keys", "values", "items",
    "acquire", "release", "locked", "wait", "wait_for", "notify",
    "notify_all", "read", "write", "close", "flush", "send", "recv",
    "sendall", "connect", "accept", "join", "start", "run", "stop",
    "result", "done", "cancel", "submit", "split", "strip",
})


def module_name_for(path: str) -> str:
    """Dotted module name for a repo-relative ``.py`` path."""
    name = path[:-3] if path.endswith(".py") else path
    name = name.replace("/", ".")
    if name.endswith(".__init__"):
        name = name[:-len(".__init__")]
    return name


def _record_imports(mod: ModuleInfo) -> None:
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                local = alias.asname or alias.name.split(".")[0]
                # ``import a.b.c`` binds ``a``; ``import a.b.c as m``
                # binds ``m`` to the full dotted path.
                target = alias.name if alias.asname else \
                    alias.name.split(".")[0]
                mod.imports[local] = target
        elif isinstance(node, ast.ImportFrom):
            if node.level:  # relative import: resolve against our package
                base_parts = mod.name.split(".")
                # level 1 == "from . import x" relative to the package,
                # which for a module "pkg.mod" is "pkg".
                base_parts = base_parts[:len(base_parts) - node.level]
                base = ".".join(base_parts)
            else:
                base = ""
            src = node.module or ""
            prefix = ".".join(p for p in (base, src) if p)
            for alias in node.names:
                local = alias.asname or alias.name
                mod.imports[local] = f"{prefix}.{alias.name}" if prefix \
                    else alias.name


def _record_globals(mod: ModuleInfo) -> None:
    for node in mod.tree.body:
        targets: List[ast.expr] = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            targets = [node.target]
        for target in targets:
            if isinstance(target, ast.Name):
                mod.global_names.add(target.id)
            elif isinstance(target, ast.Tuple):
                for elt in target.elts:
                    if isinstance(elt, ast.Name):
                        mod.global_names.add(elt.id)


class Program:
    """Every module of the package, parsed, with a function index."""

    def __init__(self) -> None:
        self.modules: Dict[str, ModuleInfo] = {}      # by dotted name
        self.modules_by_path: Dict[str, ModuleInfo] = {}
        self.functions: Dict[str, FunctionInfo] = {}  # by qualname
        #: class qualname ("mod:Class") -> method name -> FunctionInfo
        self.classes: Dict[str, Dict[str, FunctionInfo]] = {}
        #: method name -> every FunctionInfo defining it (for the
        #: unique-name fallback on untyped receivers).
        self._methods_by_name: Dict[str, List[FunctionInfo]] = {}

    @classmethod
    def load(cls, paths: Sequence[str],
             root: Optional[str] = None) -> "Program":
        """Parse every ``.py`` under ``paths`` (files or directories).

        Unparseable files are skipped — the per-file pass already
        reports ``parse-error`` for them.
        """
        program = cls()
        base = os.path.abspath(root or os.getcwd())
        for filename in core.iter_python_files(paths, root=root):
            rel = os.path.relpath(os.path.abspath(filename), base)
            if rel.startswith(".."):
                rel = filename
            rel = rel.replace(os.sep, "/")
            if rel in program.modules_by_path:
                continue
            try:
                with open(filename, "r", encoding="utf-8") as f:
                    source = f.read()
                tree = ast.parse(source, filename=filename)
            except (OSError, UnicodeDecodeError, SyntaxError):
                continue
            program.add_module(rel, source, tree)
        program.index()
        return program

    def add_module(self, rel_path: str, source: str,
                   tree: ast.Module) -> ModuleInfo:
        mod = ModuleInfo(module_name_for(rel_path), rel_path, source, tree)
        self.modules[mod.name] = mod
        self.modules_by_path[mod.path] = mod
        return mod

    def index(self) -> None:
        """(Re)build import tables and the function/class index."""
        self.functions.clear()
        self.classes.clear()
        self._methods_by_name.clear()
        for mod in self.modules.values():
            mod.imports.clear()
            mod.global_names.clear()
            _record_imports(mod)
            _record_globals(mod)
            for node in mod.tree.body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    info = FunctionInfo(f"{mod.name}:{node.name}", mod,
                                        None, node)
                    self.functions[info.qualname] = info
                elif isinstance(node, ast.ClassDef):
                    cls_q = f"{mod.name}:{node.name}"
                    methods = self.classes.setdefault(cls_q, {})
                    for item in node.body:
                        if isinstance(item, (ast.FunctionDef,
                                             ast.AsyncFunctionDef)):
                            info = FunctionInfo(
                                f"{cls_q}.{item.name}", mod, node.name,
                                item)
                            self.functions[info.qualname] = info
                            methods[item.name] = info
                            self._methods_by_name.setdefault(
                                item.name, []).append(info)

    # -- call resolution --------------------------------------------------

    def resolve_call(self, caller: FunctionInfo,
                     call: ast.Call) -> Optional[str]:
        """Best-effort qualname of the called function, else ``None``."""
        func = call.func
        mod = caller.module
        # self.m(...) -> method of the caller's own class.
        if (isinstance(func, ast.Attribute)
                and isinstance(func.value, ast.Name)
                and func.value.id == "self" and caller.cls is not None):
            cls_q = f"{mod.name}:{caller.cls}"
            info = self.classes.get(cls_q, {}).get(func.attr)
            return info.qualname if info else None
        # f(...) -> module-level function of the caller's module, or an
        # imported name (``from mod import f``). A constructor call
        # resolves to the class's __init__ — acquiring a lock while
        # building an object (a client that dials on construction) is
        # a lock-order edge like any other.
        if isinstance(func, ast.Name):
            qual = f"{mod.name}:{func.id}"
            if qual in self.functions:
                return qual
            init = self.classes.get(qual, {}).get("__init__")
            if init is not None:
                return init.qualname
            imported = mod.imports.get(func.id)
            if imported and "." in imported:
                target_mod, _, leaf = imported.rpartition(".")
                qual = f"{target_mod}:{leaf}"
                if qual in self.functions:
                    return qual
                init = self.classes.get(qual, {}).get("__init__")
                if init is not None:
                    return init.qualname
            return None
        # alias.f(...) / pkg.mod.f(...) through the import table.
        if isinstance(func, ast.Attribute):
            dotted = core.dotted_name(func.value)
            if dotted and not dotted.startswith("?"):
                head, _, rest = dotted.partition(".")
                imported = mod.imports.get(head)
                if imported is not None:
                    target = f"{imported}.{rest}" if rest else imported
                    if target in self.modules:
                        qual = f"{target}:{func.attr}"
                        if qual in self.functions:
                            return qual
                        init = self.classes.get(qual, {}).get("__init__")
                        return init.qualname if init is not None else None
            # Untyped receiver (``self._journal.record(...)``,
            # ``handle.beat()``): resolve only when exactly ONE class
            # in the program defines a method of that name — ambiguity
            # must stay opaque or the analysis invents edges — and the
            # name is not one a builtin container/primitive also has
            # (an accidentally-unique ``append`` would capture every
            # list in the package).
            if func.attr in _GENERIC_METHODS:
                return None
            candidates = self._methods_by_name.get(func.attr, [])
            if len(candidates) == 1:
                return candidates[0].qualname
        return None

    def resolve_class(self, mod: ModuleInfo,
                      call: ast.Call) -> Optional[str]:
        """Class qualname when ``call`` constructs a program class."""
        func = call.func
        if isinstance(func, ast.Name):
            qual = f"{mod.name}:{func.id}"
            if qual in self.classes:
                return qual
            imported = mod.imports.get(func.id)
            if imported and "." in imported:
                owner_mod, _, leaf = imported.rpartition(".")
                qual = f"{owner_mod}:{leaf}"
                if qual in self.classes:
                    return qual
            return None
        if isinstance(func, ast.Attribute):
            dotted = core.dotted_name(func.value)
            if not dotted or dotted.startswith("?"):
                return None
            head, _, rest = dotted.partition(".")
            imported = mod.imports.get(head)
            if imported is None:
                return None
            target = f"{imported}.{rest}" if rest else imported
            if target in self.modules:
                qual = f"{target}:{func.attr}"
                return qual if qual in self.classes else None
        return None

    def iter_calls(self, info: FunctionInfo
                   ) -> Iterator[Tuple[ast.Call, Optional[str]]]:
        """Every Call in ``info``'s body with its resolved qualname."""
        for node in ast.walk(info.node):
            if isinstance(node, ast.Call):
                yield node, self.resolve_call(info, node)
