"""Bench-record gating discipline.

Every numeric key ``bench.py`` emits into its JSON record is a claim
about performance — and a claim nobody thresholds is a regression
channel nobody watches: the r09->r10 stream collapse sat in plain sight
across two committed records because ``stream_duration_s`` doubling
fails nothing. ``ungated-bench-metric`` closes the loop from the
producer side: a numeric record emission must either be covered by a
``tools/rsdl_bench_diff.py`` ``DEFAULT_RULES`` entry (exact key, or a
``_``-separated refinement of one — ``train_fill_s`` under ``fill_s``,
``train_rows_per_sec_median`` under ``train_rows_per_sec``) or be
listed in ``bench.py``'s own ``BENCH_INFORMATIONAL_KEYS`` allowlist —
an explicit, reviewable declaration that the number is forensic
context, not a gated contract. Adding a metric therefore forces the
one-line review that decides which it is.

The rule inspects the emission idiom, not runtime values: subscript
assignments ``record["k"] = <numeric expr>`` and dict-literal keys in
``record.update({...})`` whose value expression is numeric-shaped
(literals, ``round``/``int``/``float``/``len``/``min``/``max``/``sum``
calls, arithmetic over them, conditional numerics). Non-numeric values
(strings, dicts, plain name references) are out of scope by design.
"""

from __future__ import annotations

import ast
import os
from typing import Iterator, Optional

from ray_shuffling_data_loader_tpu.analysis.core import (FileContext, Rule,
                                                         Violation,
                                                         register)

#: Builtins whose call result is numeric for gating purposes.
_NUMERIC_CALLS = frozenset({"round", "int", "float", "len", "min", "max",
                            "sum", "abs"})

_gate_keys_cache: Optional[frozenset] = None


def _gate_keys() -> frozenset:
    """DEFAULT_RULES keys from tools/rsdl_bench_diff.py, loaded by file
    path (tools/ is not a package). Empty on hosts without the tools
    tree — the rule then stays silent rather than inventing findings
    against an unknowable gate."""
    global _gate_keys_cache
    if _gate_keys_cache is not None:
        return _gate_keys_cache
    import importlib.util
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    path = os.path.join(root, "tools", "rsdl_bench_diff.py")
    try:
        spec = importlib.util.spec_from_file_location(
            "_rsdl_bench_diff_rules", path)
        module = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(module)
        _gate_keys_cache = frozenset(
            rule["key"] for rule in module.DEFAULT_RULES)
    except (OSError, AttributeError, KeyError, TypeError, SyntaxError):
        _gate_keys_cache = frozenset()
    return _gate_keys_cache


def _allowlisted(tree: ast.Module) -> frozenset:
    """String elements of the linted module's own
    ``BENCH_INFORMATIONAL_KEYS = frozenset({...})`` declaration."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        if not any(isinstance(t, ast.Name)
                   and t.id == "BENCH_INFORMATIONAL_KEYS"
                   for t in node.targets):
            continue
        value = node.value
        if isinstance(value, ast.Call) and \
                isinstance(value.func, ast.Name) and \
                value.func.id == "frozenset" and value.args and \
                isinstance(value.args[0], ast.Set):
            return frozenset(
                e.value for e in value.args[0].elts
                if isinstance(e, ast.Constant)
                and isinstance(e.value, str))
    return frozenset()


def _numeric_expr(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant):
        return (isinstance(node.value, (int, float))
                and not isinstance(node.value, bool))
    if isinstance(node, ast.BinOp):
        return _numeric_expr(node.left) or _numeric_expr(node.right)
    if isinstance(node, ast.UnaryOp):
        return _numeric_expr(node.operand)
    if isinstance(node, ast.IfExp):
        return _numeric_expr(node.body) or _numeric_expr(node.orelse)
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
        return node.func.id in _NUMERIC_CALLS
    return False


def _gated(key: str, gate: frozenset) -> bool:
    if key in gate:
        return True
    # A refinement of a gated family counts: spread stats and per-phase
    # variants of a thresholded quantity (train_rows_per_sec_median,
    # train_fill_s) are watched through their family's rule.
    for rule_key in gate:
        if key.startswith(rule_key + "_") or key.endswith("_" + rule_key):
            return True
    return False


@register
class UngatedBenchMetricRule(Rule):
    id = "ungated-bench-metric"
    category = "bench"
    description = ("numeric bench-record key has no tools/"
                   "rsdl_bench_diff.py rule and no "
                   "BENCH_INFORMATIONAL_KEYS entry: an unthresholded "
                   "number is a regression channel nobody watches — "
                   "gate it or declare it informational")

    def check(self, tree: ast.Module,
              ctx: FileContext) -> Iterator[Violation]:
        if not ctx.path_matches(ctx.config.bench_record_globs):
            return
        gate = _gate_keys()
        if not gate:
            return
        allow = _allowlisted(tree)

        def judge(key_node: ast.AST, value: ast.AST, anchor: ast.AST):
            if not (isinstance(key_node, ast.Constant)
                    and isinstance(key_node.value, str)):
                return None
            key = key_node.value
            if not _numeric_expr(value):
                return None
            if key in allow or _gated(key, gate):
                return None
            return ctx.violation(
                self, anchor,
                f"record key {key!r} is numeric but has no "
                "rsdl_bench_diff rule and no BENCH_INFORMATIONAL_KEYS "
                "entry")

        for node in ast.walk(tree):
            if isinstance(node, ast.Assign):
                for target in node.targets:
                    if isinstance(target, ast.Subscript) and \
                            isinstance(target.value, ast.Name) and \
                            target.value.id == "record":
                        v = judge(target.slice, node.value, node)
                        if v is not None:
                            yield v
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "update" and \
                    isinstance(node.func.value, ast.Name) and \
                    node.func.value.id == "record":
                for arg in node.args:
                    if not isinstance(arg, ast.Dict):
                        continue
                    for key_node, value in zip(arg.keys, arg.values):
                        if key_node is None:
                            continue
                        v = judge(key_node, value, key_node)
                        if v is not None:
                            yield v
