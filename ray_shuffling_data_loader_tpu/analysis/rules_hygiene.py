"""Worker-thread hygiene rules.

Every stage of this pipeline runs on background threads feeding bounded
queues. An `except: pass` (or ``except Exception: pass``) in that
topology does not just lose a traceback — it silently drops the
sentinel/batch the consumer is blocked on, stranding it forever (the
exact failure mode ShuffleFailure/poison-pill machinery exists to
prevent). Narrow handlers (``except OSError: pass`` around best-effort
cleanup) are fine and are not flagged.

``wallclock-interval`` guards the clock discipline the telemetry spine
depends on: ``time.time()`` is WALL clock — NTP steps/slew move it
backwards or by seconds at a time — so any duration, deadline, or
interval computed from it is wrong exactly when the host is unhealthy
(the moment observability matters). Durations use ``time.monotonic()``
/ ``perf_counter``; ``time.time()`` stays only where a real calendar
timestamp is serialized.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Set

from ray_shuffling_data_loader_tpu.analysis.core import (FileContext, Rule,
                                                         Violation,
                                                         dotted_name,
                                                         register)

_BROAD = {"Exception", "BaseException"}


def _is_broad(type_node) -> bool:
    if type_node is None:
        return True  # bare except
    if isinstance(type_node, ast.Name):
        return type_node.id in _BROAD
    if isinstance(type_node, ast.Tuple):
        return any(_is_broad(t) for t in type_node.elts)
    return False


def _is_noop(body) -> bool:
    for stmt in body:
        if isinstance(stmt, ast.Pass):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
            continue  # docstring / `...`
        return False
    return True


@register
class SwallowedExceptionRule(Rule):
    id = "swallowed-exception"
    category = "hygiene"
    description = ("broad `except:`/`except Exception:` with a pass-only "
                   "body swallows worker failures and strands queue "
                   "consumers")

    def check(self, tree: ast.Module,
              ctx: FileContext) -> Iterator[Violation]:
        for node in ast.walk(tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if _is_broad(node.type) and _is_noop(node.body):
                yield ctx.violation(
                    self, node,
                    "a swallowed broad exception in a worker thread drops "
                    "the batch/sentinel its consumer is blocked on; catch "
                    "the specific exception, or log and forward the "
                    "failure (ShuffleFailure / on_failure hook)")


def _wallclock_names(tree: ast.Module) -> Set[str]:
    """Names resolving to ``time.time`` in this module: the dotted form
    for ``import time [as t]``, bare names for ``from time import time
    [as now]``."""
    names: Set[str] = {"time.time"}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "time":
                    names.add(f"{alias.asname or alias.name}.time")
        elif isinstance(node, ast.ImportFrom) and node.module == "time":
            for alias in node.names:
                if alias.name == "time":
                    names.add(alias.asname or alias.name)
    return names


def _scopes(tree: ast.Module):
    """Module body + each function body, walked without descending into
    nested function scopes (each gets its own pass)."""
    yield tree
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _scope_nodes(scope) -> List[ast.AST]:
    out: List[ast.AST] = []
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        node = stack.pop()
        out.append(node)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue  # nested scope: analyzed separately
        stack.extend(ast.iter_child_nodes(node))
    return out


@register
class WallclockIntervalRule(Rule):
    id = "wallclock-interval"
    category = "hygiene"
    description = ("`time.time()` used in a duration/interval/deadline "
                   "computation — wall clock steps under NTP; durations "
                   "must use time.monotonic()/perf_counter()")

    def check(self, tree: ast.Module,
              ctx: FileContext) -> Iterator[Violation]:
        wallclock = _wallclock_names(tree)

        def is_wallclock_call(node: ast.AST) -> bool:
            return (isinstance(node, ast.Call)
                    and dotted_name(node.func) in wallclock)

        for scope in _scopes(tree):
            nodes = _scope_nodes(scope)
            # Variables assigned directly from a wall-clock read in this
            # scope: `start = time.time()`.
            assigned: Set[str] = set()
            for node in nodes:
                if isinstance(node, ast.Assign) \
                        and is_wallclock_call(node.value):
                    for target in node.targets:
                        if isinstance(target, ast.Name):
                            assigned.add(target.id)
            for node in nodes:
                if not isinstance(node, ast.BinOp) \
                        or not isinstance(node.op, (ast.Sub, ast.Add)):
                    continue
                operands = (node.left, node.right)
                direct = any(is_wallclock_call(op) for op in operands)
                via_name = isinstance(node.op, ast.Sub) and any(
                    isinstance(op, ast.Name) and op.id in assigned
                    for op in operands)
                if direct or via_name:
                    yield ctx.violation(
                        self, node,
                        "interval arithmetic on time.time(): wall clock "
                        "jumps under NTP steps/slew, so this duration or "
                        "deadline is wrong exactly when the host is "
                        "unhealthy; use time.monotonic() (or "
                        "perf_counter) and keep time.time() only for "
                        "serialized timestamps")
