"""Worker-thread hygiene rules.

Every stage of this pipeline runs on background threads feeding bounded
queues. An `except: pass` (or ``except Exception: pass``) in that
topology does not just lose a traceback — it silently drops the
sentinel/batch the consumer is blocked on, stranding it forever (the
exact failure mode ShuffleFailure/poison-pill machinery exists to
prevent). Narrow handlers (``except OSError: pass`` around best-effort
cleanup) are fine and are not flagged.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ray_shuffling_data_loader_tpu.analysis.core import (FileContext, Rule,
                                                         Violation, register)

_BROAD = {"Exception", "BaseException"}


def _is_broad(type_node) -> bool:
    if type_node is None:
        return True  # bare except
    if isinstance(type_node, ast.Name):
        return type_node.id in _BROAD
    if isinstance(type_node, ast.Tuple):
        return any(_is_broad(t) for t in type_node.elts)
    return False


def _is_noop(body) -> bool:
    for stmt in body:
        if isinstance(stmt, ast.Pass):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
            continue  # docstring / `...`
        return False
    return True


@register
class SwallowedExceptionRule(Rule):
    id = "swallowed-exception"
    category = "hygiene"
    description = ("broad `except:`/`except Exception:` with a pass-only "
                   "body swallows worker failures and strands queue "
                   "consumers")

    def check(self, tree: ast.Module,
              ctx: FileContext) -> Iterator[Violation]:
        for node in ast.walk(tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if _is_broad(node.type) and _is_noop(node.body):
                yield ctx.violation(
                    self, node,
                    "a swallowed broad exception in a worker thread drops "
                    "the batch/sentinel its consumer is blocked on; catch "
                    "the specific exception, or log and forward the "
                    "failure (ShuffleFailure / on_failure hook)")
