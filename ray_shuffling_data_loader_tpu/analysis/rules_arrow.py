"""Arrow schema-discipline rules.

The >2GiB regime promotes variable-width columns to 64-bit-offset
``large_*`` types per reducer output (shuffle.py), so one trainer's
epoch stream can legally mix ``large_*`` and 32-bit-offset schemas.
Any ``pa.concat_tables`` on that stream without schema promotion
raises ``ArrowInvalid`` exactly in the huge-corpus regime the
promotion targets (the ADVICE round-5 crash in slice_batches' carry
buffer). Likewise ``to_numpy(zero_copy_only=True)`` raises on chunked
or nullable columns — both hazards are one kwarg away from safe.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ray_shuffling_data_loader_tpu.analysis.core import (FileContext, Rule,
                                                         Violation,
                                                         dotted_name,
                                                         get_keyword,
                                                         is_constant,
                                                         keyword_names,
                                                         register)


@register
class ConcatPromoteRule(Rule):
    id = "arrow-concat-promote"
    category = "arrow-schema"
    description = ("`pa.concat_tables` without `promote_options=` crashes "
                   "on mixed large_*/32-bit-offset schemas (the >2GiB "
                   "promotion regime)")

    def check(self, tree: ast.Module,
              ctx: FileContext) -> Iterator[Violation]:
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            if dotted_name(node.func).rsplit(".", 1)[-1] != "concat_tables":
                continue
            kwargs = keyword_names(node)
            if "promote_options" in kwargs or "promote" in kwargs:
                continue
            yield ctx.violation(
                self, node,
                "pass `promote_options=\"permissive\"`: reducer outputs "
                "may mix large_* and 32-bit-offset schemas once the "
                ">2GiB offset promotion engages, and an unpromoted "
                "concat raises ArrowInvalid in exactly that regime")


@register
class ZeroCopyChunkedRule(Rule):
    id = "arrow-zero-copy"
    category = "arrow-schema"
    description = ("`.to_numpy(zero_copy_only=True)` raises on chunked or "
                   "nullable columns")

    def check(self, tree: ast.Module,
              ctx: FileContext) -> Iterator[Violation]:
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "to_numpy"):
                continue
            if is_constant(get_keyword(node, "zero_copy_only"), True):
                yield ctx.violation(
                    self, node,
                    "`zero_copy_only=True` raises ArrowInvalid on chunked "
                    "or nullable columns; combine_chunks() first and prove "
                    "null_count == 0, or pass zero_copy_only=False and "
                    "accept the copy")
