"""JAX/TPU hot-path hygiene rules.

The ingest pipeline's throughput rests on keeping the host out of the
device path: the prefetch producer must never synchronize with the
device (a ``.block_until_ready()`` / ``device_get`` inside its loop
serializes transfer against compute and shows up directly as trainer
stall %), a jitted function must never force a trace-time host sync
(``float(x)`` / ``np.asarray(x)`` on a traced value aborts tracing or
silently constant-folds), and ``jax.device_put`` in the SPMD layers
must carry an explicit sharding — an unsharded put materializes the
whole array on device 0 and the next collective pays a full reshard.
"""

from __future__ import annotations

import ast
import fnmatch
from typing import Dict, Iterator, List, Optional, Set

from ray_shuffling_data_loader_tpu.analysis.core import (FileContext, Rule,
                                                         Violation,
                                                         dotted_name,
                                                         keyword_names,
                                                         register)

#: Builtin conversions that force a host sync on a traced/device value.
_SYNC_BUILTINS = {"float", "int", "bool"}
#: Dotted tails that copy device values to host.
_SYNC_FUNCTIONS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array",
                   "jax.device_get", "device_get"}
#: Method calls that synchronize with the device.
_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
#: Device syncs worth flagging inside prefetch/ingest hot loops (host
#: numpy work is normal there, so the builtin/np.* set does not apply).
_LOOP_SYNC_METHODS = {"block_until_ready", "item"}
_LOOP_SYNC_FUNCTIONS = {"jax.block_until_ready", "jax.device_get",
                        "device_get"}


def _is_jit_expr(node: ast.expr) -> bool:
    """``jax.jit`` / ``jit`` / ``partial(jax.jit, ...)`` / ``jax.jit(...)``
    (a configured jit used as a decorator factory)."""
    if isinstance(node, ast.Call):
        name = dotted_name(node.func)
        if name.rsplit(".", 1)[-1] == "partial" and node.args:
            return _is_jit_expr(node.args[0])
        return name.rsplit(".", 1)[-1] == "jit"
    return dotted_name(node).rsplit(".", 1)[-1] == "jit"


class _JitIndex:
    """Which function bodies in a module execute under jax.jit."""

    def __init__(self, tree: ast.Module):
        self.defs: Dict[str, List[ast.AST]] = {}
        self.jitted_names: Set[str] = set()
        self.jitted_lambdas: List[ast.Lambda] = []
        self.decorated: List[ast.AST] = []
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.defs.setdefault(node.name, []).append(node)
                if any(_is_jit_expr(d) for d in node.decorator_list):
                    self.decorated.append(node)
            elif isinstance(node, ast.Call) and _is_jit_expr(node.func) \
                    and node.args:
                target = node.args[0]
                if isinstance(target, ast.Name):
                    self.jitted_names.add(target.id)
                elif isinstance(target, ast.Lambda):
                    self.jitted_lambdas.append(target)

    def jitted_bodies(self) -> Iterator[ast.AST]:
        seen: Set[int] = set()
        for node in self.decorated:
            seen.add(id(node))
            yield node
        for name in self.jitted_names:
            for node in self.defs.get(name, []):
                if id(node) not in seen:
                    seen.add(id(node))
                    yield node
        yield from self.jitted_lambdas


@register
class JaxHostSyncRule(Rule):
    id = "jax-host-sync"
    category = "jax-hygiene"
    description = ("host synchronization (float()/np.asarray/.item()/"
                   ".block_until_ready()) inside a jitted function or a "
                   "prefetch hot loop")

    def check(self, tree: ast.Module,
              ctx: FileContext) -> Iterator[Violation]:
        index = _JitIndex(tree)
        for body in index.jitted_bodies():
            yield from self._check_jitted(body, ctx)
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and any(fnmatch.fnmatch(node.name, pat)
                            for pat in ctx.config.hot_loop_functions):
                yield from self._check_hot_loops(node, ctx)

    def _check_jitted(self, fn: ast.AST,
                      ctx: FileContext) -> Iterator[Violation]:
        for node in ast.walk(fn):
            if node is not fn and isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # nested defs are visited as their own entries
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            reason = None
            if isinstance(node.func, ast.Name) \
                    and node.func.id in _SYNC_BUILTINS:
                reason = f"`{node.func.id}()` forces a host sync"
            elif name in _SYNC_FUNCTIONS:
                reason = f"`{name}` copies the value to host"
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _SYNC_METHODS:
                reason = f"`.{node.func.attr}()` synchronizes with the " \
                         "device"
            if reason is not None:
                yield ctx.violation(
                    self, node,
                    f"{reason} inside a jit-compiled function; trace-time "
                    "sync either fails on tracers or silently "
                    "constant-folds — keep host conversions outside jit")

    def _check_hot_loops(self, fn: ast.AST,
                         ctx: FileContext) -> Iterator[Violation]:
        loops = [n for n in ast.walk(fn)
                 if isinstance(n, (ast.For, ast.While, ast.AsyncFor))]
        seen: Set[int] = set()
        for loop in loops:
            for node in ast.walk(loop):
                if not isinstance(node, ast.Call) or id(node) in seen:
                    continue
                name = dotted_name(node.func)
                hit = (name in _LOOP_SYNC_FUNCTIONS
                       or (isinstance(node.func, ast.Attribute)
                           and node.func.attr in _LOOP_SYNC_METHODS))
                if hit:
                    seen.add(id(node))
                    yield ctx.violation(
                        self, node,
                        f"`{name}` inside the `{fn.name}` hot loop "
                        "serializes host against device; prefetch loops "
                        "must stay async (device_put returns before the "
                        "copy lands)")


@register
class DevicePutUnshardedRule(Rule):
    id = "device-put-unsharded"
    category = "jax-hygiene"
    description = ("`jax.device_put` without an explicit sharding/device "
                   "in SPMD (parallel/) code paths")

    def check(self, tree: ast.Module,
              ctx: FileContext) -> Iterator[Violation]:
        if not ctx.path_matches(ctx.config.sharded_path_globs):
            return
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            if dotted_name(node.func).rsplit(".", 1)[-1] != "device_put":
                continue
            if len(node.args) >= 2 or "device" in keyword_names(node):
                continue
            yield ctx.violation(
                self, node,
                "`jax.device_put` without a sharding in an SPMD path "
                "lands the whole array on the default device; pass a "
                "`NamedSharding` (second argument) so the batch axis is "
                "laid out over the mesh")
