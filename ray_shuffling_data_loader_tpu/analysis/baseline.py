"""Checked-in baseline ("known findings") support for rsdl-lint.

A baseline entry fingerprints a violation by ``(path, rule, snippet)``
— deliberately NOT by line number, so unrelated edits that shift code
do not invalidate the baseline. Identical snippets in one file share a
fingerprint; the baseline then suppresses up to as many occurrences as
it recorded, so a *new* copy of a grandfathered violation still fails
the gate.

The project keeps the baseline empty by policy (every deliberate
exception carries an inline ``# rsdl-lint: disable=`` pragma with a
justification comment); the mechanism exists so a future sweep that
lands a new rule with many pre-existing findings can gate new code
immediately and burn the backlog down separately.
"""

from __future__ import annotations

import collections
import hashlib
import json
from typing import Dict, List, Tuple

from ray_shuffling_data_loader_tpu.analysis.core import Violation

FORMAT_VERSION = 1


def fingerprint(violation: Violation) -> str:
    key = f"{violation.path}::{violation.rule}::{violation.snippet}"
    return hashlib.sha1(key.encode("utf-8")).hexdigest()[:16]


def write_baseline(path: str, violations: List[Violation]) -> None:
    entries = [{
        "rule": v.rule,
        "path": v.path,
        "line": v.line,  # informational only; matching uses the fingerprint
        "fingerprint": fingerprint(v),
    } for v in violations]
    payload = {"version": FORMAT_VERSION, "entries": entries}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")


def load_baseline(path: str) -> Dict[str, int]:
    """``fingerprint -> allowed occurrence count``."""
    with open(path, "r", encoding="utf-8") as f:
        payload = json.load(f)
    if payload.get("version") != FORMAT_VERSION:
        raise ValueError(
            f"unsupported baseline version {payload.get('version')!r} "
            f"in {path} (expected {FORMAT_VERSION})")
    counts: Dict[str, int] = collections.Counter(
        entry["fingerprint"] for entry in payload.get("entries", []))
    return dict(counts)


def apply_baseline(violations: List[Violation],
                   allowed: Dict[str, int]
                   ) -> Tuple[List[Violation], int]:
    """Drop baselined occurrences; returns ``(remaining, suppressed)``."""
    budget = dict(allowed)
    remaining: List[Violation] = []
    suppressed = 0
    for violation in violations:
        fp = fingerprint(violation)
        if budget.get(fp, 0) > 0:
            budget[fp] -= 1
            suppressed += 1
        else:
            remaining.append(violation)
    return remaining, suppressed
