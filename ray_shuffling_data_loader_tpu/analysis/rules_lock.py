"""Lock-discipline rules.

Nine modules of this pipeline guard shared state with ``threading.Lock``
(shuffle caches, the spill manager, queue internals, the JAX prefetch
wrapper). The two hazard classes a reviewer keeps re-catching by hand:

- a class that protects an attribute with ``with self._lock:`` in one
  method but mutates the same attribute bare in another (a data race
  that only bites under producer/consumer overlap), and
- blocking while holding a lock (``Future.result()`` / ``queue.get``
  with no timeout / ``Executor.wait``), which turns one slow task into
  a pipeline-wide stall or deadlock.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from ray_shuffling_data_loader_tpu.analysis.core import (FileContext, Rule,
                                                         Violation,
                                                         dotted_name,
                                                         get_keyword,
                                                         is_constant,
                                                         keyword_names,
                                                         register)

#: Methods where self-attribute writes are exempt: the object is not
#: yet (or no longer) shared with other threads.
_SETUP_METHODS = ("__init__", "__new__", "__del__", "__init_subclass__")


def _lockish(name: str, ctx: FileContext) -> bool:
    return re.search(ctx.config.lock_name_regex, name) is not None


def _withitem_lock_name(item: ast.withitem,
                        ctx: FileContext) -> Optional[str]:
    """The lock-ish name a ``with`` item acquires, if any.

    Recognizes ``with self._lock:``, ``with lock:``, and container
    lookups like ``with self._peer_locks[dest]:``.
    """
    expr = item.context_expr
    if isinstance(expr, ast.Subscript):
        expr = expr.value
    if isinstance(expr, ast.Attribute) and _lockish(expr.attr, ctx):
        return expr.attr
    if isinstance(expr, ast.Name) and _lockish(expr.id, ctx):
        return expr.id
    return None


def _self_attr_writes(stmt: ast.stmt) -> List[Tuple[str, ast.AST]]:
    """Attribute names of ``self`` written by one statement: direct
    assignment, augmented assignment, subscript stores
    (``self._paths[k] = v``) and deletes."""
    writes: List[Tuple[str, ast.AST]] = []

    def target_attr(node: ast.expr) -> Optional[str]:
        if isinstance(node, ast.Subscript):
            node = node.value
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"):
            return node.attr
        return None

    if isinstance(stmt, ast.Assign):
        targets = stmt.targets
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    elif isinstance(stmt, ast.Delete):
        targets = stmt.targets
    else:
        return writes
    for target in targets:
        elements = (target.elts if isinstance(target, (ast.Tuple, ast.List))
                    else [target])
        for element in elements:
            attr = target_attr(element)
            if attr is not None:
                writes.append((attr, element))
    return writes


@register
class LockMutationRule(Rule):
    id = "lock-mutation"
    category = "lock-discipline"
    description = ("attribute guarded by `with self.<lock>:` elsewhere in "
                   "the class is mutated without holding the lock")

    def check(self, tree: ast.Module,
              ctx: FileContext) -> Iterator[Violation]:
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                yield from self._check_class(node, ctx)

    def _check_class(self, cls: ast.ClassDef,
                     ctx: FileContext) -> Iterator[Violation]:
        guarded: Set[str] = set()
        unguarded: List[Tuple[str, ast.AST]] = []

        def scan(stmts, in_lock: bool, exempt: bool) -> None:
            for stmt in stmts:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.Lambda)):
                    # A nested def's body runs whenever it is CALLED,
                    # not where it is defined — never under this lock.
                    body = getattr(stmt, "body", [])
                    scan(body if isinstance(body, list) else [], False,
                         exempt)
                    continue
                for attr, target in _self_attr_writes(stmt):
                    if in_lock:
                        guarded.add(attr)
                    elif not exempt:
                        unguarded.append((attr, target))
                if isinstance(stmt, ast.With):
                    locked = in_lock or any(
                        _withitem_lock_name(i, ctx) is not None
                        for i in stmt.items)
                    scan(stmt.body, locked, exempt)
                else:
                    for field in ("body", "orelse", "finalbody", "handlers"):
                        children = getattr(stmt, field, None)
                        if not children:
                            continue
                        for child in children:
                            if isinstance(child, ast.ExceptHandler):
                                scan(child.body, in_lock, exempt)
                            elif isinstance(child, ast.stmt):
                                scan([child], in_lock, exempt)

        for method in cls.body:
            if not isinstance(method, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                continue
            scan(method.body, False, method.name in _SETUP_METHODS)
        for attr, target in unguarded:
            if attr in guarded:
                yield ctx.violation(
                    self, target,
                    f"`self.{attr}` is written under a lock elsewhere in "
                    f"`{cls.name}` but mutated here without holding it; "
                    "take the lock (or move all access out from under it "
                    "if the attribute is single-thread-owned)")


@register
class LockBlockingCallRule(Rule):
    id = "lock-blocking-call"
    category = "lock-discipline"
    description = ("potentially-unbounded blocking call (Future.result, "
                   "timeout-less queue.get/join/recv, Executor.wait, "
                   "sleep) while holding a lock")

    def check(self, tree: ast.Module,
              ctx: FileContext) -> Iterator[Violation]:
        out: List[Violation] = []

        def visit(node: ast.AST, held: Optional[str]) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                held = None  # a nested def runs outside this lock scope
            if isinstance(node, ast.With):
                for item in node.items:
                    name = _withitem_lock_name(item, ctx)
                    if name is not None:
                        held = name
            if held is not None and isinstance(node, ast.Call):
                message = self._blocking_reason(node, ctx)
                if message is not None:
                    out.append(ctx.violation(
                        self, node,
                        f"{message} while holding `{held}` can stall every "
                        "thread contending for it; release the lock first "
                        "or pass a timeout"))
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        visit(tree, None)
        yield from out

    def _blocking_reason(self, call: ast.Call,
                         ctx: FileContext) -> Optional[str]:
        config = ctx.config
        kwargs = keyword_names(call)
        name = dotted_name(call.func)
        if isinstance(call.func, ast.Attribute):
            method = call.func.attr
            base = dotted_name(call.func.value)
            if _lockish(base.rsplit(".", 1)[-1], ctx):
                return None  # the lock object's own API (acquire etc.)
            if method in config.blocking_get_methods:
                block = get_keyword(call, "block")
                queueish = re.search(config.queue_name_regex,
                                     base.rsplit(".", 1)[-1])
                if queueish is None and not is_constant(block, True):
                    return None  # a dict/env .get, not a queue get
                if "timeout" in kwargs:
                    return None
                if block is not None and is_constant(block, False):
                    return None
                # Positional block=False: get(idx, False)
                if any(is_constant(a, False) for a in call.args):
                    return None
                return f"timeout-less blocking `{name}()`"
            if method in config.blocking_methods:
                if "timeout" in kwargs or call.args:
                    # result(timeout)/join(timeout)/recv(n) style args
                    # bound or qualify the wait.
                    return None
                return f"`{name}()` with no timeout"
        tail = name.rsplit(".", 1)[-1]
        if tail in config.blocking_functions and "timeout" in kwargs:
            return None
        if tail in config.blocking_functions:
            return f"blocking `{name}(...)`"
        return None
