"""rsdl-lint: project-invariant static analyzer for this pipeline.

Run ``python -m ray_shuffling_data_loader_tpu.analysis <paths>`` (or
``tools/rsdl_lint.py``); see ``--list-rules`` for the rule set and
``examples/static_analysis.md`` for the invariants each rule encodes
and the ``# rsdl-lint: disable=<rule>`` pragma syntax. Stdlib-only by
design so the format.sh gate runs on minimal TPU-VM images.
"""

from ray_shuffling_data_loader_tpu.analysis.core import (Config, Rule,
                                                         Violation,
                                                         all_rules,
                                                         check_paths,
                                                         check_source)

__all__ = [
    "Config", "Rule", "Violation", "all_rules", "check_paths",
    "check_source",
]
