"""Admission control: accept / queue / reject registrations against
cluster-wide quota ledgers, with a journaled, bit-identically
replayable decision log.

Fair-share scheduling (tenancy/fairshare.py) divides capacity among
work ALREADY admitted; this module decides whether new work gets in
at all. A registration is one dataset or stream a tenant wants served
(its estimated working-set bytes are the ask). The controller holds a
:class:`QuotaLedger` of cluster capacity and per-tenant usage and
makes a three-way decision:

``reject``  the ask can NEVER fit (exceeds the tenant's own byte
            quota or the whole cluster capacity, or duplicates a
            registration already charged/queued) — telling the tenant
            now beats queueing it forever;
``queue``   the ask fits in principle but not right now — it waits
            FIFO and is admitted automatically as releases free bytes;
``accept``  charged to the ledger immediately.

Determinism is the design constraint, not an afterthought: decisions
are pure functions of (journal history, request), with no wall clock,
no randomness, no dict-order dependence — so the journal REPLAYS:
:func:`replay` feeds the journaled requests through a fresh
controller and must re-derive byte-identical journal lines. That is
the recovery story (a restarted controller rebuilds its ledger from
the journal alone) and the audit story (any disagreement between a
journal and its replay is evidence of corruption or version skew, and
raises).

Journal format: one canonical JSON object per line (sorted keys,
compact separators, ``\\n`` terminator), append-only, fsync'd per
record — the same discipline as the queue journal's watermarks.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

from ray_shuffling_data_loader_tpu.runtime import metrics as rt_metrics
from ray_shuffling_data_loader_tpu.tenancy import (TenantContext,
                                                   validate_tenant_id)

_ACTIONS = ("accept", "queue", "reject", "admit", "release")


@dataclasses.dataclass(frozen=True)
class AdmissionDecision:
    """One journaled decision. ``seq`` is the journal position (the
    total order); ``action`` is one of accept/queue/reject for
    register events, admit for a queued request promoted by a release,
    release for freed capacity."""

    seq: int
    action: str
    tenant_id: str
    kind: str  # "dataset" | "stream"
    name: str
    nbytes: int
    reason: str = ""

    def to_line(self) -> bytes:
        d = dict(sorted(dataclasses.asdict(self).items()))
        return (json.dumps(d, sort_keys=True,
                           separators=(",", ":")) + "\n").encode("utf-8")

    @classmethod
    def from_line(cls, line: bytes) -> "AdmissionDecision":
        return cls(**json.loads(line.decode("utf-8")))


class QuotaLedger:
    """Cluster capacity and per-tenant charges, in bytes and
    registration slots. Pure bookkeeping — policy lives in the
    controller."""

    def __init__(self, capacity_bytes: int,
                 max_registrations: Optional[int] = None):
        if capacity_bytes <= 0:
            raise ValueError("capacity_bytes must be > 0")
        self.capacity_bytes = capacity_bytes
        self.max_registrations = max_registrations
        self._used_bytes = 0
        self._charges: Dict[Tuple[str, str], int] = {}  # (tenant, name)
        self._tenant_bytes: Dict[str, int] = {}

    @property
    def used_bytes(self) -> int:
        return self._used_bytes

    @property
    def free_bytes(self) -> int:
        return self.capacity_bytes - self._used_bytes

    @property
    def registrations(self) -> int:
        return len(self._charges)

    def tenant_bytes(self, tenant_id: str) -> int:
        return self._tenant_bytes.get(tenant_id, 0)

    def charged(self, tenant_id: str, name: str) -> bool:
        return (tenant_id, name) in self._charges

    def fits(self, nbytes: int) -> bool:
        if self.max_registrations is not None \
                and len(self._charges) >= self.max_registrations:
            return False
        return self._used_bytes + nbytes <= self.capacity_bytes

    def charge(self, tenant_id: str, name: str, nbytes: int) -> None:
        key = (tenant_id, name)
        if key in self._charges:
            raise ValueError(f"{tenant_id!r}/{name!r} already charged")
        self._charges[key] = nbytes
        self._used_bytes += nbytes
        self._tenant_bytes[tenant_id] = \
            self._tenant_bytes.get(tenant_id, 0) + nbytes

    def release(self, tenant_id: str, name: str) -> int:
        nbytes = self._charges.pop((tenant_id, name), 0)
        self._used_bytes -= nbytes
        if nbytes:
            self._tenant_bytes[tenant_id] = \
                self._tenant_bytes.get(tenant_id, 0) - nbytes
        return nbytes

    def snapshot(self) -> dict:
        return {
            "capacity_bytes": self.capacity_bytes,
            "used_bytes": self._used_bytes,
            "registrations": len(self._charges),
            "per_tenant_bytes": dict(sorted(self._tenant_bytes.items())),
        }


class AdmissionController:
    """Journaled admission over one :class:`QuotaLedger`.

    ``journal_path=None`` keeps the journal in memory only (unit tests,
    ephemeral servers); with a path every decision line is appended and
    fsync'd before the decision is returned, so an accepted tenant is
    accepted across a crash.
    """

    def __init__(self, capacity_bytes: int,
                 max_registrations: Optional[int] = None,
                 journal_path: Optional[str] = None):
        self.ledger = QuotaLedger(capacity_bytes, max_registrations)
        self.journal_path = journal_path
        self._lock = threading.Lock()
        self._seq = 0
        self._lines: List[bytes] = []
        # FIFO of queued asks: (tenant_ctx_dict, kind, name, nbytes)
        self._waiting: Deque[Tuple[dict, str, str, int]] = deque()
        self._fh = None
        if journal_path is not None:
            os.makedirs(os.path.dirname(journal_path) or ".",
                        exist_ok=True)
            self._fh = open(journal_path, "ab")

    # -- journal -------------------------------------------------------

    def _journal(self, decision: AdmissionDecision) -> AdmissionDecision:
        line = decision.to_line()
        self._lines.append(line)
        if self._fh is not None:
            self._fh.write(line)
            self._fh.flush()
            os.fsync(self._fh.fileno())
        rt_metrics.counter(
            "rsdl_admission_decisions_total",
            "admission decisions by action",
            action=decision.action).inc()
        rt_metrics.gauge(
            "rsdl_admission_waiting",
            "registrations queued behind the quota ledger").set(
            len(self._waiting))
        rt_metrics.gauge(
            "rsdl_admission_used_bytes",
            "bytes charged to the admission quota ledger").set(
            self.ledger.used_bytes)
        return decision

    def journal_bytes(self) -> bytes:
        """The full journal as emitted (the replay-comparison target)."""
        with self._lock:
            return b"".join(self._lines)

    # -- decisions -----------------------------------------------------

    def _decide_locked(self, tenant: TenantContext, kind: str, name: str,
                       nbytes: int) -> AdmissionDecision:
        # Caller holds _lock (the _locked suffix is the contract).
        # rsdl-lint: disable=lock-mutation
        self._seq += 1
        seq = self._seq
        tid = tenant.tenant_id
        if nbytes < 0:
            return AdmissionDecision(seq, "reject", tid, kind, name,
                                     nbytes, "negative byte ask")
        if self.ledger.charged(tid, name) or any(
                w[0]["tenant_id"] == tid and w[2] == name
                for w in self._waiting):
            # A retry of an already-accepted (or already-queued) ask is
            # the crash-recovery scenario the journal must survive: it
            # MUST become a journaled, deterministic decision here. If
            # it instead escaped to ledger.charge (which raises), the
            # seq this call already consumed would never be journaled,
            # and every subsequent replay() of an otherwise-valid
            # journal would diverge on the gap.
            return AdmissionDecision(
                seq, "reject", tid, kind, name, nbytes,
                "duplicate registration (already charged or queued)")
        if tenant.byte_quota is not None and \
                self.ledger.tenant_bytes(tid) + nbytes > tenant.byte_quota:
            return AdmissionDecision(
                seq, "reject", tid, kind, name, nbytes,
                f"tenant byte quota exceeded "
                f"({self.ledger.tenant_bytes(tid)}+{nbytes}"
                f">{tenant.byte_quota})")
        if nbytes > self.ledger.capacity_bytes:
            return AdmissionDecision(
                seq, "reject", tid, kind, name, nbytes,
                f"ask exceeds cluster capacity "
                f"({nbytes}>{self.ledger.capacity_bytes})")
        if not self.ledger.fits(nbytes):
            return AdmissionDecision(
                seq, "queue", tid, kind, name, nbytes,
                f"waiting for {nbytes - self.ledger.free_bytes} bytes")
        return AdmissionDecision(seq, "accept", tid, kind, name, nbytes)

    def register(self, tenant: TenantContext, kind: str, name: str,
                 nbytes: int) -> AdmissionDecision:
        """Ask to serve one dataset/stream of ``nbytes`` working set."""
        validate_tenant_id(tenant.tenant_id)
        if kind not in ("dataset", "stream"):
            raise ValueError(f"kind must be dataset|stream, got {kind!r}")
        with self._lock:
            decision = self._decide_locked(tenant, kind, name, nbytes)
            if decision.action == "accept":
                self.ledger.charge(tenant.tenant_id, name, nbytes)
            elif decision.action == "queue":
                self._waiting.append(
                    (tenant.to_dict(), kind, name, nbytes))
            return self._journal(decision)

    def release(self, tenant_id: str, name: str) -> List[AdmissionDecision]:
        """Free a registration's bytes and admit waiting asks FIFO.
        Returns the journaled decisions (the release plus any
        admits)."""
        with self._lock:
            freed = self.ledger.release(tenant_id, name)
            self._seq += 1
            out = [self._journal(AdmissionDecision(
                self._seq, "release", tenant_id, "dataset", name, freed))]
            # FIFO admit: head-of-line blocking is deliberate — skipping
            # over a large queued ask to admit a small one behind it
            # would starve the large tenant forever.
            while self._waiting:
                ctx_dict, kind, wname, wbytes = self._waiting[0]
                if not self.ledger.fits(wbytes):
                    break
                self._waiting.popleft()
                wtid = ctx_dict["tenant_id"]
                self.ledger.charge(wtid, wname, wbytes)
                self._seq += 1
                out.append(self._journal(AdmissionDecision(
                    self._seq, "admit", wtid, kind, wname, wbytes)))
            return out

    def waiting(self) -> int:
        with self._lock:
            return len(self._waiting)

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


def replay(journal_path: str, capacity_bytes: int,
           max_registrations: Optional[int] = None,
           tenants: Optional[Dict[str, TenantContext]] = None
           ) -> AdmissionController:
    """Rebuild a controller from its journal and PROVE the rebuild: the
    journaled register/release events are re-fed through a fresh
    controller, and the re-derived journal must be byte-identical to
    the file — any divergence raises ``ValueError`` (corruption or
    version skew). Returns the rebuilt controller (in-memory journal;
    callers re-attach a path for new decisions)."""
    with open(journal_path, "rb") as f:
        original = f.read()
    decisions = [AdmissionDecision.from_line(line)
                 for line in original.splitlines(keepends=False) if line]
    fresh = AdmissionController(capacity_bytes, max_registrations)
    tenants = tenants or {}
    for d in decisions:
        if d.action in ("accept", "queue", "reject"):
            ctx = tenants.get(d.tenant_id)
            if ctx is None:
                ctx = TenantContext(d.tenant_id)
            fresh.register(ctx, d.kind, d.name, d.nbytes)
        elif d.action == "release":
            fresh.release(d.tenant_id, d.name)
        # "admit" lines are DERIVED (a release replays them), never fed
    rederived = fresh.journal_bytes()
    if rederived != original:
        raise ValueError(
            "admission journal replay diverged: re-derived "
            f"{len(rederived)} bytes != journaled {len(original)} bytes "
            "(corruption, version skew, or a tenant context whose "
            "quotas changed since the journal was written)")
    return fresh


__all__ = ["AdmissionController", "AdmissionDecision", "QuotaLedger",
           "replay"]
