"""Weighted-fair sharing of one in-flight byte budget (deficit round
robin).

The queue service's flow control is a single number: a shard stops
popping frames for a consumer once its unacked (replay) bytes reach
``queue_replay_bytes``. That budget is the congestion window of the
whole serving plane — and before this module it was first-come-first-
served: a batch tenant replaying cold epochs could pin the entire
budget and starve an interactive stream's watermark.

:class:`FairShare` partitions that budget by tenant weight, two ways
at once:

- **window partition** (:meth:`budget`) — each ACTIVE tenant's unacked
  bytes may grow to ``total * weight / sum(active weights)``. With
  window-limited consumers (slow acks — exactly the contention case),
  per-RTT delivered bytes track the window, so throughput converges to
  the weight ratio. Work-conserving: tenants that stop asking leave
  the active set after ``active_window_s`` and their share is
  redistributed on the next call.
- **deficit round robin** (:meth:`grant` / :meth:`charge`) — classic
  DRR over byte quanta for the fast-ack regime, where the window never
  binds: every delivered frame charges the tenant's deficit; a GET may
  pop frames past the first only while the deficit is positive; when
  every active tenant is exhausted, all deficits replenish by
  ``quantum * weight``. Over any contention interval the delivered
  byte ratio converges to the weight ratio.

Both checks preserve the one-frame-per-GET floor (the server only
consults FairShare for frames past the first), so a starved tenant
still progresses — fairness here shapes rates, it never deadlocks a
consumer.

Thread-safety: all methods take the internal lock; the queue service
calls them under its own per-queue state lock, which is fine — this
lock is leaf-level and never calls out.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Iterable, Optional

from ray_shuffling_data_loader_tpu.tenancy import DEFAULT_TENANT_ID

#: Deficit replenish quantum multiplier — one round hands each tenant
#: ``quantum * weight`` bytes of pop credit.
DEFAULT_QUANTUM_BYTES = 1 << 20


class FairShare:
    """Deficit-round-robin weighted shares of ``total_budget`` bytes.

    ``weights`` maps tenant id -> weight; unknown tenants fall back to
    ``default_weight`` so an unconfigured tenant degrades to a normal
    (weight-1) participant instead of crashing the serving path.
    """

    def __init__(self, weights: Dict[str, float], total_budget: int,
                 quantum_bytes: int = DEFAULT_QUANTUM_BYTES,
                 active_window_s: float = 1.0,
                 default_weight: float = 1.0,
                 clock: Callable[[], float] = time.monotonic):
        if total_budget <= 0:
            raise ValueError("total_budget must be > 0")
        for tenant_id, weight in weights.items():
            if not weight > 0:
                raise ValueError(
                    f"tenant {tenant_id!r}: weight must be > 0")
        self.total_budget = total_budget
        self.quantum_bytes = max(1, int(quantum_bytes))
        self.active_window_s = active_window_s
        self.default_weight = default_weight
        self._weights = dict(weights)
        self._clock = clock
        self._lock = threading.Lock()
        self._last_active: Dict[str, float] = {}
        self._deficit: Dict[str, float] = {}

    # -- identity ------------------------------------------------------

    def weight(self, tenant_id: str) -> float:
        return self._weights.get(tenant_id, self.default_weight)

    def set_weight(self, tenant_id: str, weight: float) -> None:
        """Register/adjust a tenant's weight (a wire-announced tenant
        joining a live server)."""
        if not weight > 0:
            raise ValueError(f"weight must be > 0, got {weight}")
        with self._lock:
            self._weights[tenant_id] = weight

    def touch(self, tenant_id: str) -> None:
        """Mark ``tenant_id`` active (called on every GET it issues)."""
        now = self._clock()
        with self._lock:
            if tenant_id not in self._deficit:
                # Join mid-round with one quantum of credit, like a DRR
                # flow arriving at a busy link.
                self._deficit[tenant_id] = \
                    self.quantum_bytes * self.weight(tenant_id)
            self._last_active[tenant_id] = now

    def idle(self, tenant_id: str) -> None:
        """Drop ``tenant_id``'s active claim and unspent credit NOW (a
        GET found its queue empty). A tenant with no queued work must
        not gate tenants that do have work — without this, a slow live
        stream blocked waiting for its next frame would hold positive
        deficit for up to ``active_window_s`` and pin every competing
        batch tenant to the paced liveness floor. It rejoins with a
        fresh quantum on its next :meth:`touch`, like any arriving
        flow.

        Only POSITIVE credit is dropped; a negative deficit (debt) is
        kept, and :meth:`touch` does not re-grant over it. A tenant
        with one empty stream and one busy replay rank would otherwise
        zero its debt on every empty-queue GET and rejoin with a fresh
        quantum on the busy rank's next GET — resetting the round
        robin each cycle and out-delivering its weight share."""
        with self._lock:
            self._last_active.pop(tenant_id, None)
            if self._deficit.get(tenant_id, 0.0) >= 0:
                self._deficit.pop(tenant_id, None)

    def active(self) -> Iterable[str]:
        """Tenants seen within the activity window (expired ones are
        dropped so their share redistributes — work conservation)."""
        now = self._clock()
        with self._lock:
            expired = [t for t, ts in self._last_active.items()
                       if now - ts > self.active_window_s]
            for tenant_id in expired:
                del self._last_active[tenant_id]
                self._deficit.pop(tenant_id, None)
            return list(self._last_active)

    # -- window partition ----------------------------------------------

    def budget(self, tenant_id: str) -> int:
        """``tenant_id``'s share of the in-flight byte budget among
        currently-active tenants. A lone tenant gets the whole budget
        (bit-for-bit the pre-tenancy behavior)."""
        active = self.active()
        if tenant_id not in active:
            self.touch(tenant_id)
            active = list(active) + [tenant_id]
        total_weight = sum(self.weight(t) for t in active)
        if total_weight <= 0:
            return self.total_budget
        return max(1, int(self.total_budget
                          * self.weight(tenant_id) / total_weight))

    # -- deficit round robin ---------------------------------------------

    def grant(self, tenant_id: str) -> bool:
        """May ``tenant_id`` pop another frame this round? True while
        its deficit is positive; when EVERY active tenant is exhausted
        the round ends and all deficits replenish by
        ``quantum * weight`` (the DRR service round)."""
        active = self.active()
        with self._lock:
            if self._deficit.get(tenant_id, 0.0) > 0:
                return True
            if any(self._deficit.get(t, 0.0) > 0 for t in active
                   if t != tenant_id):
                return False  # others still hold credit: wait your turn
            for t in active:
                self._deficit[t] = (self._deficit.get(t, 0.0)
                                    + self.quantum_bytes * self.weight(t))
            return self._deficit.get(tenant_id, 0.0) > 0

    def charge(self, tenant_id: str, nbytes: int) -> None:
        """Record ``nbytes`` delivered to ``tenant_id``."""
        with self._lock:
            self._deficit[tenant_id] = \
                self._deficit.get(tenant_id, 0.0) - nbytes

    def deficit(self, tenant_id: str) -> float:
        with self._lock:
            return self._deficit.get(tenant_id, 0.0)

    def snapshot(self) -> Dict[str, dict]:
        """Per-tenant {weight, deficit, budget} for metrics/debugging."""
        active = set(self.active())
        out = {}
        for tenant_id in sorted(set(self._weights) | active):
            out[tenant_id] = {
                "weight": self.weight(tenant_id),
                "deficit": self.deficit(tenant_id),
                "active": tenant_id in active,
                "budget": self.budget(tenant_id)
                if tenant_id in active else 0,
            }
        return out


def simulate_rounds(fair: FairShare, demands: Dict[str, int],
                    frame_bytes: int, rounds: int,
                    advance: Optional[Callable[[], None]] = None
                    ) -> Dict[str, int]:
    """Deterministic DRR simulation used by the fairness-convergence
    tests and the bench's sanity path: every round, each tenant with
    remaining demand is offered pops while ``grant`` allows; returns
    delivered bytes per tenant. No wall clock involved (callers pass a
    fake clock into ``fair``; ``advance``, if given, steps that clock
    once per round so exhausted tenants age out of the active set).

    All demanding tenants are touched BEFORE anyone pops — GETs
    interleave in the real server, so contention is established first;
    touching lazily would let the round's first tenant replenish
    against an empty active set and drain its whole demand alone.
    """
    delivered = {t: 0 for t in demands}
    remaining = dict(demands)
    for _ in range(rounds):
        if not any(v > 0 for v in remaining.values()):
            break
        for tenant_id in sorted(remaining):
            if remaining[tenant_id] > 0:
                fair.touch(tenant_id)
        for tenant_id in sorted(remaining):
            if remaining[tenant_id] <= 0:
                continue
            # one-frame floor: the first frame of a GET never consults
            # the scheduler (matching _collect_frames)
            take = min(frame_bytes, remaining[tenant_id])
            fair.charge(tenant_id, take)
            delivered[tenant_id] += take
            remaining[tenant_id] -= take
            while remaining[tenant_id] > 0 and fair.grant(tenant_id):
                take = min(frame_bytes, remaining[tenant_id])
                fair.charge(tenant_id, take)
                delivered[tenant_id] += take
                remaining[tenant_id] -= take
        if advance is not None:
            advance()
    return delivered


__all__ = ["DEFAULT_QUANTUM_BYTES", "DEFAULT_TENANT_ID", "FairShare",
           "simulate_rounds"]
