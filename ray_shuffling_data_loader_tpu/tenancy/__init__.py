"""Multi-tenant identity: who is asking for bytes, and on what terms.

Every plane in this repo — the sharded queue service, the tiered
storage cache, the plan-driven prefetcher, the streaming runner —
was built assuming ONE job reading ONE dataset. Nothing stops a
lagging trainer's replay from starving a live stream's watermark, or
one tenant's cold scan from thrashing another tenant's hot cache
tier. This package is the missing policy layer: a
:class:`TenantContext` names the principal and carries its priority
class, quotas and SLO targets; the context is threaded from dataset /
stream construction through the plan IR (``EpochSpec.tenant_id``),
queue leases and the wire protocol, so every byte in flight is
attributable — and therefore schedulable (:mod:`tenancy.fairshare`),
admittable (:mod:`tenancy.admission`) and cacheable under per-tenant
quotas (storage/cache.py).

Identity propagation is deliberately two-channel:

- **structural** — plan specs and server config carry ``tenant_id`` /
  a ``tenants`` table, so the server can attribute work even for
  legacy clients that never heard of tenancy;
- **ambient** — a contextvar (:func:`tenant_scope` /
  :func:`current_tenant`) so deep call sites (cache ``put``, prefetch
  ``warm``) can attribute bytes without threading a parameter through
  every signature. The default tenant makes single-tenant
  deployments behave exactly as before this package existed.
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
import json
import re
from typing import Dict, Iterator, Optional

#: Priority classes and the weight each implies when the context does
#: not pin one explicitly. Weights are RATIOS (3:1 interactive:batch
#: means 3x the shared byte budget under contention), not absolutes.
PRIORITY_WEIGHTS: Dict[str, float] = {
    "batch": 1.0,
    "standard": 2.0,
    "interactive": 4.0,
}

#: Tenant ids are label values (metrics) and journal keys: lowercase,
#: bounded, no whitespace — the same shape every other bounded label
#: in runtime/metric_names.py keeps.
_TENANT_ID_RE = re.compile(r"^[a-z0-9][a-z0-9_.-]{0,63}$")


def validate_tenant_id(tenant_id: str) -> str:
    """Return ``tenant_id`` or raise ``ValueError`` — ids become metric
    labels and journal keys, so the vocabulary must stay bounded and
    shell/JSON-safe."""
    if not isinstance(tenant_id, str) or not _TENANT_ID_RE.match(tenant_id):
        raise ValueError(
            f"invalid tenant id {tenant_id!r}: want ^[a-z0-9][a-z0-9_.-]"
            "{0,63}$ (it becomes a metric label and a journal key)")
    return tenant_id


@dataclasses.dataclass(frozen=True)
class TenantContext:
    """One tenant's identity + service terms, immutable and serializable.

    ``weight`` is the fair-share ratio the queue scheduler honors under
    contention; when ``None`` it derives from ``priority`` via
    :data:`PRIORITY_WEIGHTS`. Quotas are ``None`` = unlimited, so a
    default-constructed context changes nothing for existing callers.
    """

    tenant_id: str
    priority: str = "standard"
    weight: Optional[float] = None
    #: Storage-plane quotas: resident cache bytes / prefetch bytes this
    #: tenant may pin (None = share the global budget unpartitioned).
    cache_quota_bytes: Optional[int] = None
    prefetch_quota_bytes: Optional[int] = None
    #: Admission-time byte ask (dataset/stream working set estimate).
    byte_quota: Optional[int] = None
    #: SLO targets the health plane evaluates per tenant.
    slo_p99_ms: Optional[float] = None
    slo_freshness_s: Optional[float] = None

    def __post_init__(self):
        validate_tenant_id(self.tenant_id)
        if self.priority not in PRIORITY_WEIGHTS:
            raise ValueError(
                f"unknown priority {self.priority!r}: "
                f"want one of {sorted(PRIORITY_WEIGHTS)}")
        if self.weight is not None and not self.weight > 0:
            raise ValueError(f"weight must be > 0, got {self.weight}")

    @property
    def effective_weight(self) -> float:
        return (self.weight if self.weight is not None
                else PRIORITY_WEIGHTS[self.priority])

    def to_dict(self) -> dict:
        """Canonical dict: sorted keys, ``None`` fields omitted — the
        journal/wire form, stable across processes and releases."""
        d = {"tenant_id": self.tenant_id, "priority": self.priority}
        for field in ("weight", "cache_quota_bytes",
                      "prefetch_quota_bytes", "byte_quota",
                      "slo_p99_ms", "slo_freshness_s"):
            value = getattr(self, field)
            if value is not None:
                d[field] = value
        return dict(sorted(d.items()))

    def to_json(self) -> bytes:
        """Wire blob (OP_TENANT payload): canonical compact JSON."""
        return json.dumps(self.to_dict(), sort_keys=True,
                          separators=(",", ":")).encode("utf-8")

    @classmethod
    def from_dict(cls, data: dict) -> "TenantContext":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in data.items() if k in known})

    @classmethod
    def from_json(cls, blob: bytes) -> "TenantContext":
        return cls.from_dict(json.loads(blob.decode("utf-8")))


#: The tenant every pre-tenancy caller implicitly is. Single-tenant
#: deployments never see quotas, fair-share math or per-tenant metrics
#: beyond this one label.
DEFAULT_TENANT_ID = "default"
DEFAULT_TENANT = TenantContext(DEFAULT_TENANT_ID)

_current: "contextvars.ContextVar[TenantContext]" = contextvars.ContextVar(
    "rsdl_current_tenant", default=DEFAULT_TENANT)


def current_tenant() -> TenantContext:
    """The ambient tenant for this (thread/task) context."""
    return _current.get()


@contextlib.contextmanager
def tenant_scope(ctx: TenantContext) -> Iterator[TenantContext]:
    """Run a block as ``ctx``: deep call sites (cache put, prefetch
    warm) attribute their bytes to it via :func:`current_tenant`."""
    token = _current.set(ctx)
    try:
        yield ctx
    finally:
        _current.reset(token)


def resolve(tenant=None) -> TenantContext:
    """Coerce ``tenant`` (context, id string, dict or None) into a
    :class:`TenantContext`; ``None`` means the ambient tenant."""
    if tenant is None:
        return current_tenant()
    if isinstance(tenant, TenantContext):
        return tenant
    if isinstance(tenant, str):
        return TenantContext(tenant)
    if isinstance(tenant, dict):
        return TenantContext.from_dict(tenant)
    raise TypeError(f"cannot resolve tenant from {type(tenant).__name__}")


def tenants_from_config(tenants: Optional[dict]) -> Dict[str, dict]:
    """Normalize a server-config ``tenants`` table
    (``{tenant_id: {"weight": w, "ranks": [...], ...}}``) — validates
    ids, fills weights from priority, leaves extra keys alone."""
    normalized: Dict[str, dict] = {}
    for tenant_id, spec in (tenants or {}).items():
        validate_tenant_id(tenant_id)
        spec = dict(spec or {})
        if spec.get("weight") is None:
            spec["weight"] = PRIORITY_WEIGHTS[
                spec.get("priority", "standard")]
        if not spec["weight"] > 0:
            raise ValueError(
                f"tenant {tenant_id!r}: weight must be > 0")
        normalized[tenant_id] = spec
    return normalized


__all__ = [
    "DEFAULT_TENANT", "DEFAULT_TENANT_ID", "PRIORITY_WEIGHTS",
    "TenantContext", "current_tenant", "resolve", "tenant_scope",
    "tenants_from_config", "validate_tenant_id",
]
