"""Epoch-plan subsystem: the declarative IR (plan/ir.py — stdlib-only,
loadable standalone by tools) and the speculative, work-stealing
execution engine (plan/scheduler.py)."""

from ray_shuffling_data_loader_tpu.plan.ir import (EpochPlan, LineageKey,
                                                   PlanError, PlanNode,
                                                   build_epoch_plan,
                                                   from_json, node_id,
                                                   queue_epoch, queue_index,
                                                   queue_rank,
                                                   resume_from_watermarks,
                                                   route_slices)
from ray_shuffling_data_loader_tpu.plan.scheduler import (PlanScheduler,
                                                          SchedulerPolicy,
                                                          speculation_totals)

__all__ = [
    "EpochPlan", "LineageKey", "PlanError", "PlanNode", "PlanScheduler",
    "SchedulerPolicy", "build_epoch_plan", "from_json", "node_id",
    "queue_epoch", "queue_index", "queue_rank", "resume_from_watermarks",
    "route_slices", "speculation_totals",
]
