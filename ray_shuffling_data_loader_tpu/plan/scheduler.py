"""Plan-driven execution engine: dependency-ordered dispatch, speculative
re-execution of stragglers, and work-stealing placement.

Replaces the inline epoch loops (``shuffle.shuffle_epoch``'s submit-all
fan-out and ``procpool.process_epoch``'s await-then-submit sequence) with
one engine that executes an :class:`plan.ir.EpochPlan` on any pool
satisfying the ``executor.Executor`` contract:

- **Dependency-ordered dispatch**: a node is submitted only when every
  dependency has *resolved* (completed — successfully or not; failure
  semantics stay with the consumer, e.g. the reduce task's
  ``EpochLineage`` recovery observes a failed map ref exactly as
  before). No worker is ever parked blocking on an unfinished input.

- **Speculative re-execution** (``RSDL_PLAN_SPECULATION``, default off):
  when a running task's elapsed time exceeds a policy-gated multiple of
  the rolling per-stage median (``RSDL_PLAN_SPECULATION_MULTIPLIER``,
  floored by ``RSDL_PLAN_SPECULATION_MIN_S``) and an idle lane exists, a
  backup attempt of the SAME node is launched — the classic MapReduce
  answer to stragglers, provably safe here because every task is a pure
  function of its ``(seed, epoch, task)`` lineage key, so duplicate
  executions are bit-identical. First completion wins; the loser is
  cancelled if still queued, otherwise its result is discarded
  (``rsdl_plan_speculative_wasted_total``). Backup attempts run under
  ``telemetry.speculative()`` so their recorder events carry a ``spec``
  attr and never double-count in trace merge or bottleneck attribution.

- **Work stealing / locality-aware placement**
  (``RSDL_PLAN_STEALING``, default on): nodes are assigned to logical
  lanes (one per pool worker, ``task % lanes`` — the static round-robin
  the inline loops effectively had). An idle lane whose own queue is
  empty pulls the oldest ready node from the longest sibling queue
  (``rsdl_plan_steals_total``) instead of idling; with stealing off,
  placement is strictly static (the A/B baseline the equivalence tests
  pin — outputs are identical either way, only idle time differs).

- **Idle-lane prefetch** (``prefetcher=``, storage/prefetch.py): a lane
  with no real work, nothing to steal, and no speculation candidate
  pulls a cache-warming task from the prefetcher instead of idling —
  the lowest rung of the priority ladder (ready nodes > steals >
  speculation > prefetch). A prefetch does NOT mark its lane busy: the
  lane stays claimable, and the moment real work lands on it the
  prefetch is canceled (best effort — a transfer already in flight
  finishes and still warms the cache). Warms run on dedicated daemon
  threads, never on pool workers, so an in-flight remote fetch cannot
  occupy a worker slot a real task would queue behind. Prefetches
  still in flight when the plan resolves are left to complete: they
  are warming the files the NEXT epoch's plan reads.

The engine runs on one named driver thread per plan (no polling when
speculation is off: dispatch is woken by completion events). Stage
barrier hooks (``barriers={stage: fn}``) run on the driver thread after
a stage fully resolves and before dependents dispatch — the process
backend uses one to collect map segment results (including its
driver-side lineage re-run) without ever blocking a pool dispatcher
thread.
"""

from __future__ import annotations

import collections
import concurrent.futures as cf
import queue as queue_mod
import statistics
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ray_shuffling_data_loader_tpu import executor as ex
from ray_shuffling_data_loader_tpu.plan import ir
from ray_shuffling_data_loader_tpu.runtime import metrics as rt_metrics
from ray_shuffling_data_loader_tpu.runtime import policy as rt_policy
from ray_shuffling_data_loader_tpu.runtime import telemetry as rt_telemetry
from ray_shuffling_data_loader_tpu.utils.logger import setup_custom_logger

logger = setup_custom_logger(__name__)

#: Dispatcher signature: submit one attempt of a node to the pool.
Dispatcher = Callable[[ir.PlanNode, int], ex.TaskRef]

#: Rolling window of completed durations per stage for the speculation
#: median (bounded memory; stragglers are judged against recent peers).
_MEDIAN_WINDOW = 64

# Process-wide speculation/steal totals (the bench record's
# ``speculation`` block reads deltas of these; the registry counters
# carry the same numbers per stage for the exposition/rsdl_top view).
_totals_lock = threading.Lock()
_totals = {"speculative_launched": 0, "speculative_won": 0,
           "speculative_wasted": 0, "steals": 0}


def speculation_totals() -> Dict[str, int]:
    """Process-wide ``{speculative_launched, speculative_won,
    speculative_wasted, steals}`` counters across all schedulers."""
    with _totals_lock:
        return dict(_totals)


def _bump(name: str, n: int = 1) -> None:
    with _totals_lock:
        _totals[name] += n


class SchedulerPolicy:
    """Resolved ``plan`` component policy knobs (kwarg > RSDL_PLAN_* env
    > default; see runtime/policy.py for the precedence contract)."""

    def __init__(self, speculation: Optional[bool] = None,
                 stealing: Optional[bool] = None,
                 multiplier: Optional[float] = None,
                 min_task_s: Optional[float] = None,
                 check_interval_s: Optional[float] = None):
        self.speculation = rt_policy.resolve("plan", "plan_speculation",
                                             override=speculation)
        self.stealing = rt_policy.resolve("plan", "plan_stealing",
                                          override=stealing)
        self.multiplier = rt_policy.resolve(
            "plan", "plan_speculation_multiplier", override=multiplier)
        self.min_task_s = rt_policy.resolve(
            "plan", "plan_speculation_min_s", override=min_task_s)
        self.check_interval_s = rt_policy.resolve(
            "plan", "plan_speculation_check_s", override=check_interval_s)


class _NodeState:
    __slots__ = ("node", "future", "lane", "indegree", "attempts",
                 "started_at", "backup_launched")

    def __init__(self, node: ir.PlanNode, lane: int, indegree: int):
        self.node = node
        self.future: cf.Future = cf.Future()
        self.lane = lane
        self.indegree = indegree
        #: attempt -> (ref, start monotonic) for in-flight attempts.
        self.attempts: Dict[int, Tuple[ex.TaskRef, float]] = {}
        self.started_at: Optional[float] = None
        self.backup_launched = False


class PlanScheduler:
    """Execute the scheduled stages of one :class:`ir.EpochPlan`.

    ``dispatchers`` maps stage name -> callable submitting one attempt
    to the pool; stages without a dispatcher (``route``) are not
    scheduled — they are the driver's consumption plan. ``barriers``
    maps stage name -> hook run once on the driver thread when that
    stage fully resolves, before dependents dispatch.

    :meth:`start` returns immediately; per-node results are exposed as
    ``executor.TaskRef``s (:meth:`ref_for` / :meth:`refs`) the existing
    drain/consume machinery accepts unchanged.
    """

    def __init__(self, plan: ir.EpochPlan, pool,
                 dispatchers: Dict[str, Dispatcher],
                 barriers: Optional[Dict[str, Callable[[], None]]] = None,
                 policy: Optional[SchedulerPolicy] = None,
                 speculative_stages: Sequence[str] = ("map", "reduce"),
                 lanes: Optional[int] = None,
                 name: Optional[str] = None,
                 prefetcher=None):
        plan.validate()
        self.plan = plan
        self.pool = pool
        self.policy = policy if policy is not None else SchedulerPolicy()
        self._dispatchers = dict(dispatchers)
        self._barriers = dict(barriers or {})
        #: storage.prefetch.PrefetchManager (duck-typed: ``next()`` ->
        #: task with ``run``/``cancel``) feeding idle lanes, or None.
        self._prefetcher = prefetcher
        self._lane_prefetch: Dict[int, object] = {}
        self._speculative_stages = frozenset(speculative_stages)
        self._lanes = max(1, lanes if lanes is not None
                          else getattr(pool, "num_workers", 1))
        self._name = name or f"rsdl-plan-e{plan.epoch}"
        self._events: "queue_mod.Queue[tuple]" = queue_mod.Queue()
        # No instance lock on purpose: every field below is owned by
        # the driver thread running the event loop (callbacks talk to
        # it through self._events); a lock here would only disguise
        # that confinement contract from the concurrency pass.
        self._lane_busy = [False] * self._lanes
        self._lane_queues: List["collections.deque[_NodeState]"] = [
            collections.deque() for _ in range(self._lanes)]
        self._durations: Dict[str, "collections.deque[float]"] = {}
        self._stage_outstanding: Dict[str, int] = {}
        self._barrier_done: set = set()
        self._states: Dict[str, _NodeState] = {}
        self._unresolved = 0
        self._started = False
        self._driver: Optional[threading.Thread] = None
        dependents = plan.dependents()
        scheduled = set(self._dispatchers)
        for node in plan.nodes.values():
            if node.stage not in scheduled:
                continue
            indegree = sum(1 for dep in node.deps
                           if plan.nodes[dep].stage in scheduled)
            state = _NodeState(node, node.key.task % self._lanes, indegree)
            self._states[node.id] = state
            self._stage_outstanding[node.stage] = \
                self._stage_outstanding.get(node.stage, 0) + 1
        self._dependents = {
            nid: [d for d in dependents.get(nid, ()) if d in self._states]
            for nid in self._states}
        self._unresolved = len(self._states)
        #: stages (in dependency order) whose nodes this run schedules.
        self._scheduled_stages = [s for s in ir.STAGES if s in scheduled
                                  and self._stage_outstanding.get(s)]

    # -- public surface -------------------------------------------------

    def start(self) -> "PlanScheduler":
        assert not self._started, "scheduler already started"
        self._started = True
        for state in self._states.values():
            if state.indegree == 0 and self._deps_barriers_done(state.node):
                self._lane_queues[state.lane].append(state)
        self._driver = threading.Thread(target=self._drive,
                                        name=self._name, daemon=True)
        self._driver.start()
        return self

    def ref_for(self, nid: str) -> ex.TaskRef:
        return ex.TaskRef(self._states[nid].future)

    def refs(self, stage: str) -> List[ex.TaskRef]:
        """Stage refs in task order (the contract the drain/consume
        loops expect: ``refs[i]`` is task ``i``)."""
        nodes = sorted((s.node for s in self._states.values()
                        if s.node.stage == stage), key=lambda n: n.key.task)
        return [self.ref_for(n.id) for n in nodes]

    def futures(self, stage: str) -> List[cf.Future]:
        nodes = sorted((s.node for s in self._states.values()
                        if s.node.stage == stage), key=lambda n: n.key.task)
        return [self._states[n.id].future for n in nodes]

    def join(self, timeout: Optional[float] = None) -> bool:
        """Wait for the driver thread (every scheduled node resolved)."""
        assert self._driver is not None
        self._driver.join(timeout)
        return not self._driver.is_alive()

    # -- driver loop -----------------------------------------------------

    def _drive(self) -> None:
        try:
            self._fill_lanes()
            while self._unresolved:
                timeout = (self.policy.check_interval_s
                           if self.policy.speculation else None)
                try:
                    event = self._events.get(timeout=timeout)
                except queue_mod.Empty:
                    self._maybe_speculate()
                    continue
                self._handle_done(*event)
                # Drain whatever else arrived without re-blocking.
                while True:
                    try:
                        event = self._events.get_nowait()
                    except queue_mod.Empty:
                        break
                    self._handle_done(*event)
                self._fill_lanes()
                if self.policy.speculation:
                    self._maybe_speculate()
        except BaseException as e:  # noqa: BLE001 - surfaced via futures
            logger.exception("%s: plan driver failed", self._name)
            for state in self._states.values():
                if not state.future.done():
                    state.future.set_exception(e)

    def _deps_barriers_done(self, node: ir.PlanNode) -> bool:
        for dep in node.deps:
            stage = self.plan.nodes[dep].stage
            if stage in self._barriers and stage not in self._barrier_done:
                return False
        return True

    def _fill_lanes(self) -> None:
        for lane in range(self._lanes):
            while not self._lane_busy[lane]:
                state = self._take_work(lane)
                if state is None:
                    break
                # Real work outranks a warming fetch: reclaim the lane.
                self._cancel_prefetch(lane)
                self._dispatch(state, attempt=0, lane=lane)
        if self._prefetcher is not None:
            self._fill_prefetch()

    def _cancel_prefetch(self, lane: int) -> None:
        task = self._lane_prefetch.pop(lane, None)
        if task is not None:
            task.cancel()

    def _fill_prefetch(self) -> None:
        """Bottom of the priority ladder: lanes with no real work, and
        nothing stealable, pull cache-warming tasks. The lane is NOT
        marked busy — and the warm runs on its own daemon thread, NOT
        the executor pool: a submitted pool task would occupy a real
        worker slot for the whole remote fetch, so the next epoch's map
        (or this epoch's reduce) would queue behind a cache warm —
        exactly the priority inversion the ladder forbids. A warm is
        mostly remote-latency sleep; a thread per in-flight warm
        (bounded by the lane count) costs nothing the pool would not."""
        for lane in range(self._lanes):
            if (self._lane_busy[lane] or lane in self._lane_prefetch
                    or self._lane_queues[lane]):
                continue
            task = self._prefetcher.next()
            if task is None:
                return
            def _warm(task=task, lane=lane):
                try:
                    task.run()
                finally:
                    self._events.put(("__prefetch__", lane))
            self._lane_prefetch[lane] = task
            threading.Thread(target=_warm, daemon=True,
                             name=f"{self._name}-prefetch-l{lane}").start()

    def _take_work(self, lane: int) -> Optional[_NodeState]:
        own = self._lane_queues[lane]
        if own:
            return own.popleft()
        if not self.policy.stealing:
            return None
        victim = max(self._lane_queues, key=len)
        if not victim:
            return None
        state = victim.popleft()
        _bump("steals")
        rt_metrics.counter(
            "rsdl_plan_steals_total",
            "ready plan nodes pulled by an idle lane instead of waiting "
            "on static placement", stage=state.node.stage).inc()
        rt_telemetry.record("plan_steal", epoch=state.node.key.epoch,
                            task=state.node.key.task,
                            stage=state.node.stage, lane=lane,
                            home=state.lane)
        return state

    def _dispatch(self, state: _NodeState, attempt: int, lane: int) -> None:
        node = state.node
        dispatcher = self._dispatchers[node.stage]
        try:
            ref = dispatcher(node, attempt)
        except BaseException as e:  # noqa: BLE001 - surfaced via future
            if attempt > 0:
                # A failed BACKUP submission must never poison a node
                # whose original attempt is still running.
                logger.warning("%s: speculative dispatch of %s failed "
                               "(%s); original attempt continues",
                               self._name, node.id, e)
            elif not state.future.done():
                state.future.set_exception(e)
                self._on_resolved(state)
            return
        now = time.monotonic()
        if attempt == 0:
            self._lane_busy[lane] = True
            state.lane = lane
            state.started_at = now
        state.attempts[attempt] = (ref, now)
        nid, aid = node.id, attempt
        ref.add_done_callback(
            lambda _f: self._events.put((nid, aid)))

    def _handle_done(self, nid: str, attempt: int) -> None:
        if nid == "__prefetch__":
            # A warming task finished (or was canceled): free its lane's
            # prefetch slot so _fill_lanes can issue the next one.
            self._lane_prefetch.pop(attempt, None)
            return
        state = self._states.get(nid)
        if state is None:
            return
        entry = state.attempts.pop(attempt, None)
        if entry is None:
            return
        ref, started = entry
        node = state.node
        if state.future.done():
            # A sibling attempt already won; this completion is waste.
            _bump("speculative_wasted")
            rt_metrics.counter(
                "rsdl_plan_speculative_wasted_total",
                "completed attempts whose result was discarded "
                "(first-completion-wins)", stage=node.stage).inc()
            return
        dur = time.monotonic() - started
        try:
            result = ref.result()
        except BaseException as e:  # noqa: BLE001 - consumer semantics
            state.future.set_exception(e)
        else:
            state.future.set_result(result)
        window = self._durations.setdefault(
            node.stage, collections.deque(maxlen=_MEDIAN_WINDOW))
        window.append(dur)
        if attempt > 0:
            _bump("speculative_won")
            rt_metrics.counter(
                "rsdl_plan_speculative_won_total",
                "speculative backup attempts that finished first",
                stage=node.stage).inc()
            rt_telemetry.record("plan_speculate_win",
                                epoch=node.key.epoch, task=node.key.task,
                                stage=node.stage, dur_s=dur)
        for other_attempt, (other_ref, _) in list(state.attempts.items()):
            other_ref.cancel()
        self._on_resolved(state)

    def _on_resolved(self, state: _NodeState) -> None:
        node = state.node
        self._unresolved -= 1
        self._lane_busy[state.lane] = False
        self._stage_outstanding[node.stage] -= 1
        if self._stage_outstanding[node.stage] == 0:
            hook = self._barriers.get(node.stage)
            if hook is not None:
                hook()
            self._barrier_done.add(node.stage)
        for child_id in self._dependents[node.id]:
            child = self._states[child_id]
            child.indegree -= 1
            if child.indegree == 0 and \
                    self._deps_barriers_done(child.node):
                self._lane_queues[child.lane].append(child)
        # A stage barrier may have unblocked nodes whose indegree hit 0
        # earlier in the stage (they were held back only by the hook).
        if node.stage in self._barrier_done:
            for child in self._states.values():
                if (child.indegree == 0 and not child.future.done()
                        and not child.attempts
                        and child not in self._lane_queues[child.lane]
                        and self._deps_barriers_done(child.node)):
                    self._lane_queues[child.lane].append(child)

    # -- speculation ----------------------------------------------------

    def _threshold(self, stage: str) -> Optional[float]:
        window = self._durations.get(stage)
        if not window:
            return None
        median = statistics.median(window)
        return max(self.policy.min_task_s,
                   self.policy.multiplier * median)

    def _maybe_speculate(self) -> None:
        idle = [lane for lane in range(self._lanes)
                if not self._lane_busy[lane]
                and not self._lane_queues[lane]]
        if not idle:
            return
        now = time.monotonic()
        for state in self._states.values():
            if not idle:
                return
            node = state.node
            if (state.backup_launched or state.future.done()
                    or 0 not in state.attempts
                    or node.stage not in self._speculative_stages):
                continue
            threshold = self._threshold(node.stage)
            if threshold is None:
                continue
            elapsed = now - state.attempts[0][1]
            if elapsed <= threshold:
                continue
            state.backup_launched = True
            # Speculation outranks prefetch for the lane's capacity.
            self._cancel_prefetch(idle.pop())
            logger.warning(
                "%s: task %s running %.3fs (> %.3fs threshold); "
                "launching speculative backup", self._name, node.id,
                elapsed, threshold)
            _bump("speculative_launched")
            rt_metrics.counter(
                "rsdl_plan_speculative_launched_total",
                "speculative backup attempts launched for straggling "
                "plan nodes", stage=node.stage).inc()
            rt_telemetry.record("plan_speculate", epoch=node.key.epoch,
                                task=node.key.task, stage=node.stage,
                                elapsed_s=elapsed, threshold_s=threshold)
            self._dispatch(state, attempt=1, lane=-1)


# ---------------------------------------------------------------------------
# Membership-aware plan rewrite (membership/)
# ---------------------------------------------------------------------------


def rewrite_for_view(plan: ir.EpochPlan,
                     live_ranks: Sequence[int]) -> int:
    """Resize-as-plan-rewrite: re-place the plan's reduce and route
    nodes over the LIVE membership rank set.

    A ``member_down`` mid-epoch does not change *what* the plan
    computes — every node keeps its ``(seed, epoch, task)`` lineage key,
    so outputs stay bit-identical — it changes *where*: the dead rank's
    reduce nodes are handed to survivors via
    :func:`plan.ir.reduce_placement` (``route_slices`` arithmetic over
    the shrunken rank set) and each route node follows the trainer-span
    rebalance the same way. The placement lands in ``node.meta["host"]``
    (advisory, like ``cost_s`` — excluded from plan equality), which is
    how the dryrun scene and ``tools/rsdl_plan.py`` show the resized
    world. Returns the number of nodes whose host changed.
    """
    placement = ir.reduce_placement(plan.num_reducers, live_ranks)
    trainer_host: Dict[int, int] = {}
    for host, (start, stop) in ir.rebalance_spans(
            plan.num_trainers, live_ranks).items():
        for trainer in range(start, stop):
            trainer_host[trainer] = host
    moved = 0
    for node in plan.reduces():
        host = placement[node.key.task]
        if node.meta.get("host") not in (None, host):
            moved += 1
        node.meta["host"] = host
    for node in plan.routes():
        host = trainer_host[int(node.meta.get("rank", node.key.task))]
        if node.meta.get("host") not in (None, host):
            moved += 1
        node.meta["host"] = host
    if moved:
        rt_telemetry.record("plan_rewrite", epoch=plan.epoch,
                            moved=moved, live=sorted(
                                int(r) for r in live_ranks))
        logger.warning("plan epoch %d: rewrote %d node placement(s) "
                       "onto live ranks %s", plan.epoch, moved,
                       sorted(int(r) for r in live_ranks))
    return moved


def rebalance_queues(shard_map: ir.ShardMap,
                     moves: Dict[int, int]) -> ir.ShardMap:
    """Rebalance-as-plan-rewrite: re-home trainer ranks' queues onto
    other shards of the serving fabric.

    The ``rewrite_for_view`` sibling for the serving plane: ``moves``
    maps trainer rank -> target shard, and the result is a NEW
    :class:`plan.ir.ShardMap` whose ``overrides`` carry the merged
    placement and whose ``generation`` is bumped by one — the fence the
    wire protocol stamps into every frame so post-move frames from the
    old home are droppable. Pure data-in/data-out (the input map is
    never mutated); no-op moves (rank already on the target) are
    dropped, and if every move is a no-op the INPUT map is returned
    unchanged so callers can cheaply detect "nothing to do" by
    identity. Raises :class:`plan.ir.PlanError` on out-of-range ranks
    or shards (``ShardMap.validate``).
    """
    overrides = dict(shard_map.overrides)
    applied: Dict[int, int] = {}
    for rank, shard in sorted(moves.items()):
        rank, shard = int(rank), int(shard)
        if shard_map.shard_for_rank(rank) == shard:
            continue
        overrides[rank] = shard
        applied[rank] = shard
    if not applied:
        return shard_map
    # An override that lands a rank back on its static home is pure
    # noise — drop it so maps stay canonical (and serialize minimally).
    overrides = {rank: shard for rank, shard in overrides.items()
                 if shard != rank % shard_map.num_shards}
    rebalanced = ir.ShardMap(
        num_trainers=shard_map.num_trainers,
        addresses=[tuple(addr) for addr in shard_map.addresses],
        version=shard_map.version,
        overrides=overrides,
        generation=shard_map.generation + 1)
    rebalanced.validate()
    rt_telemetry.record("plan_rebalance",
                        generation=rebalanced.generation,
                        moves={str(r): s for r, s in applied.items()})
    logger.warning("shard map generation %d: rebalanced %d rank(s) %s",
                   rebalanced.generation, len(applied),
                   {r: s for r, s in applied.items()})
    return rebalanced
