"""THE epoch-plan IR: one declarative object per shuffle epoch.

The pipeline's determinism contract — every task is a pure function of
``(seed, epoch, task)`` — used to be *implicit*, smeared across
``shuffle.EpochLineage``, the queue server's resume arithmetic
(``queue_id = epoch * num_trainers + rank``), checkpoint skip math, the
procpool's kill-recovery resubmission and the chaos harness's rule keys.
Each consumer re-derived the same keys with its own private arithmetic,
and nothing could *look at* an epoch's task graph as data.

This module reifies that knowledge as an explicit, serializable plan:

- :class:`PlanNode` — one task (``map`` / ``reduce`` / ``route``) with
  its lineage key, dependency edges, and an optional cost annotation fed
  back from telemetry.
- :class:`EpochPlan` — the per-epoch DAG ``files -> map partitions ->
  reduce slices -> queue routes``, built by :func:`build_epoch_plan`,
  validated by :meth:`EpochPlan.validate`, round-tripped by
  :meth:`EpochPlan.to_json` / :func:`from_json` (stable key order, so
  tools and the checkpoint journal can diff two serializations).
- The **plan queries** every resume/recovery path must use instead of
  re-deriving keys: :func:`queue_index` / :func:`queue_epoch` /
  :func:`queue_rank` (the route-key arithmetic, in exactly one place),
  :func:`route_slices` (the contiguous reducer->trainer split,
  remainder-first like ``np.array_split``), and
  :func:`resume_from_watermarks` (the PR 5 journal-resume math the
  restarted queue server runs).

The ``lineage-outside-plan`` rsdl-lint rule closes the loop from the
other side: fresh ``(seed, epoch, task)`` key-derivation arithmetic in
library code outside ``plan/`` is flagged — resume and recovery must
query the plan, not re-derive.

Execution of a plan lives in :mod:`plan.scheduler`. This module is
stdlib-only and import-free on purpose (the ``runtime/`` contract):
``tools/rsdl_plan.py`` loads it by file path on images without numpy or
pyarrow.
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import re
from typing import (Any, Dict, Iterable, List, Mapping, Optional,
                    Sequence, Tuple)

#: Serialization format version (bumped on breaking shape changes).
PLAN_VERSION = 1

#: Mirrors tenancy/__init__.py's id shape (this module stays import-free
#: so tools can load it by file path without the package).
_TENANT_ID_RE = re.compile(r"^[a-z0-9][a-z0-9_.-]{0,63}$")

#: Shard-map serialization version (the serving-plane config, PR 10).
SHARD_MAP_VERSION = 1

#: Stage names, in dependency order.
STAGES = ("map", "reduce", "route")


class PlanError(ValueError):
    """A plan failed validation (or deserialization)."""


# ---------------------------------------------------------------------------
# Lineage / route key derivation — THE one place for this arithmetic.
# ---------------------------------------------------------------------------


def queue_index(epoch: int, rank: int, num_trainers: int) -> int:
    """The multiqueue index carrying ``rank``'s tables for ``epoch``
    (the wire contract of multiqueue.py / multiqueue_service.py)."""
    return epoch * num_trainers + rank


def queue_epoch(queue_idx: int, num_trainers: int) -> int:
    """Inverse of :func:`queue_index`: the epoch a queue belongs to."""
    return queue_idx // num_trainers


def queue_rank(queue_idx: int, num_trainers: int) -> int:
    """Inverse of :func:`queue_index`: the trainer rank a queue feeds."""
    return queue_idx % num_trainers


def queue_shard(queue_idx: int, num_trainers: int, num_shards: int) -> int:
    """The serving-plane shard responsible for ``queue_idx``.

    Placement is BY RANK (``queue_rank % num_shards``), so every epoch of
    one trainer's stream lands on the same shard — a consumer holds one
    connection per shard for its whole run, and a shard's watermark
    journal covers complete per-rank histories (the per-shard recovery
    matrix needs no cross-shard coordination)."""
    return queue_rank(queue_idx, num_trainers) % max(1, num_shards)


def shard_ranks(shard: int, num_trainers: int, num_shards: int) -> List[int]:
    """The trainer ranks (hence queues, across every epoch) shard
    ``shard`` owns under the :func:`queue_shard` placement."""
    num_shards = max(1, num_shards)
    return [r for r in range(num_trainers) if r % num_shards == shard]


def split_sizes(total: int, num_parts: int) -> List[int]:
    """Sizes of the contiguous reducer->trainer split: remainder-first,
    exactly ``np.array_split(range(total), num_parts)`` (the reference's
    routing arithmetic, reference: shuffle.py:188-189; mirrored from
    ``ops.partition.split_sizes`` so this module stays stdlib-only —
    equality is pinned by a test)."""
    base, rem = divmod(total, num_parts)
    return [base + 1 if i < rem else base for i in range(num_parts)]


def route_slices(num_reducers: int, num_trainers: int
                 ) -> List[Tuple[int, int]]:
    """Per-trainer ``(start, stop)`` reducer-index spans (contiguous,
    remainder-first)."""
    out: List[Tuple[int, int]] = []
    start = 0
    for size in split_sizes(num_reducers, num_trainers):
        out.append((start, start + size))
        start += size
    return out


def rebalance_spans(num_items: int, live_ranks: Sequence[int]
                    ) -> Dict[int, Tuple[int, int]]:
    """Contiguous ``(start, stop)`` item spans re-placed over an
    ELASTIC rank set: :func:`route_slices` arithmetic, but keyed by the
    live ranks themselves (sorted) instead of ``range(world)`` — THE
    membership-resize placement query. A shrunken world hands the dead
    rank's span to survivors (remainder-first, so the split is uneven
    but deterministic); a grown world spreads the same items thinner.
    Placement moves, content never does: the items are still the same
    global indices, so every task's ``(seed, epoch, task)`` lineage key
    — and therefore its output — is unchanged by any resize."""
    ranks = sorted(int(r) for r in live_ranks)
    if not ranks:
        raise PlanError("rebalance_spans needs at least one live rank")
    spans = route_slices(num_items, len(ranks))
    return {rank: spans[i] for i, rank in enumerate(ranks)}


def reduce_placement(num_reducers: int, live_ranks: Sequence[int]
                     ) -> Dict[int, int]:
    """``reducer_index -> owning live rank`` under the
    :func:`rebalance_spans` placement — the inverse view the elastic
    runner's per-reducer loop wants."""
    placement: Dict[int, int] = {}
    for rank, (start, stop) in rebalance_spans(num_reducers,
                                               live_ranks).items():
        for reducer in range(start, stop):
            placement[reducer] = rank
    return placement


def node_id(stage: str, epoch: int, task: int) -> str:
    """Stable node id: ``stage:eE:tT``."""
    return f"{stage}:e{epoch}:t{task}"


# ---------------------------------------------------------------------------
# IR data model
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class LineageKey:
    """The ``(seed, epoch, task)`` triple that makes a task pure: the
    same key always reproduces the same output, which is what makes
    recomputation, replay, checkpoint resume and speculative duplicate
    execution all provably safe."""

    seed: int
    epoch: int
    task: int

    def as_tuple(self) -> Tuple[int, int, int]:
        return (self.seed, self.epoch, self.task)

    def __str__(self) -> str:
        return f"{self.seed}:{self.epoch}:{self.task}"


@dataclasses.dataclass
class PlanNode:
    """One task of an epoch plan.

    ``meta`` carries the stage-specific payload (map: ``file`` path and
    ``file_index``; reduce: nothing extra; route: ``rank``, ``queue``
    and the contiguous ``reducers`` span it consumes). ``cost_s`` is an
    advisory duration annotation fed back from telemetry — schedulers
    may use it for placement, tools render it; it never affects
    correctness (it is excluded from plan equality on purpose)."""

    id: str
    stage: str
    key: LineageKey
    deps: Tuple[str, ...] = ()
    cost_s: Optional[float] = None
    meta: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def as_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "id": self.id,
            "stage": self.stage,
            "key": list(self.key.as_tuple()),
            "deps": list(self.deps),
        }
        if self.cost_s is not None:
            d["cost_s"] = round(float(self.cost_s), 6)
        if self.meta:
            d["meta"] = dict(sorted(self.meta.items()))
        return d

    @classmethod
    def from_dict(cls, data: Mapping[str, Any]) -> "PlanNode":
        try:
            seed, epoch, task = data["key"]
            return cls(id=str(data["id"]), stage=str(data["stage"]),
                       key=LineageKey(int(seed), int(epoch), int(task)),
                       deps=tuple(str(d) for d in data.get("deps", ())),
                       cost_s=(None if data.get("cost_s") is None
                               else float(data["cost_s"])),
                       meta=dict(data.get("meta", {})))
        except (KeyError, TypeError, ValueError) as e:
            raise PlanError(f"malformed plan node {data!r}: {e}") from e


@dataclasses.dataclass
class EpochPlan:
    """The declarative task graph of ONE shuffle epoch.

    Node order is deterministic (maps by file index, reduces by reducer
    index, routes by rank), so two plans built from the same inputs
    serialize byte-identically — the property the checkpoint journal and
    ``tools/rsdl_plan.py`` diffing rely on.

    ``window`` is the streaming provenance block (``streaming/window.py``):
    a closed window compiles to a normal epoch plan and stamps
    ``{"index", "policy", "ingest_watermark", "late_events"}`` here so
    recovery and tools can see which stream window an epoch came from.
    ``None`` (the static-file-list case) serializes to nothing — plans
    from the pre-streaming world stay byte-identical.

    ``tenant_id`` names the tenant the epoch is served FOR
    (tenancy/__init__.py): the serving plane attributes queue bytes
    and the storage plane attributes cache residency to it. Like
    ``window``, ``None`` serializes to nothing so single-tenant plans
    stay byte-identical with every pre-tenancy journal."""

    seed: int
    epoch: int
    num_reducers: int
    num_trainers: int
    filenames: List[str]
    nodes: Dict[str, PlanNode] = dataclasses.field(default_factory=dict)
    version: int = PLAN_VERSION
    window: Optional[Dict[str, Any]] = None
    tenant_id: Optional[str] = None

    # -- queries --------------------------------------------------------

    def stage_nodes(self, stage: str) -> List[PlanNode]:
        return [n for n in self.nodes.values() if n.stage == stage]

    def maps(self) -> List[PlanNode]:
        return self.stage_nodes("map")

    def reduces(self) -> List[PlanNode]:
        return self.stage_nodes("reduce")

    def routes(self) -> List[PlanNode]:
        return self.stage_nodes("route")

    def node(self, nid: str) -> PlanNode:
        try:
            return self.nodes[nid]
        except KeyError:
            raise PlanError(f"unknown plan node {nid!r}") from None

    def map_key(self, file_index: int) -> LineageKey:
        return self.node(node_id("map", self.epoch, file_index)).key

    def reduce_key(self, reduce_index: int) -> LineageKey:
        return self.node(node_id("reduce", self.epoch, reduce_index)).key

    def dependents(self) -> Dict[str, List[str]]:
        """Reverse edges: node id -> ids depending on it."""
        out: Dict[str, List[str]] = {nid: [] for nid in self.nodes}
        for node in self.nodes.values():
            for dep in node.deps:
                if dep in out:
                    out[dep].append(node.id)
        return out

    def annotate_costs(self, stage_costs: Mapping[str, float]) -> None:
        """Stamp advisory per-stage cost annotations (seconds) onto every
        node of each stage — the telemetry feedback hook (bench and the
        scheduler pass stage p50s from ``telemetry.attribution()``)."""
        for node in self.nodes.values():
            cost = stage_costs.get(node.stage)
            if cost is not None:
                node.cost_s = float(cost)

    # -- validation -----------------------------------------------------

    def validate(self) -> None:
        """Raise :class:`PlanError` unless the plan is well-formed:
        unique stage/epoch/task-consistent ids, closed acyclic dependency
        edges, reduces depending on every map, and route nodes covering
        the reducer range contiguously exactly once."""
        if self.version != PLAN_VERSION:
            raise PlanError(
                f"plan version {self.version} != {PLAN_VERSION}")
        if self.num_reducers < 1 or self.num_trainers < 1:
            raise PlanError("num_reducers and num_trainers must be >= 1")
        if self.window is not None:
            if not isinstance(self.window, dict):
                raise PlanError("window metadata must be a dict")
            try:
                if int(self.window["index"]) < 0:
                    raise PlanError("window index must be >= 0")
            except (KeyError, TypeError, ValueError) as e:
                raise PlanError(
                    f"malformed window metadata {self.window!r}: {e}") from e
        if self.tenant_id is not None:
            if not isinstance(self.tenant_id, str) \
                    or not _TENANT_ID_RE.match(self.tenant_id):
                raise PlanError(
                    f"invalid tenant_id {self.tenant_id!r}: want "
                    "^[a-z0-9][a-z0-9_.-]{0,63}$")
        maps, reduces, routes = [], [], []
        for nid, node in self.nodes.items():
            if node.id != nid:
                raise PlanError(f"node indexed as {nid!r} carries id "
                                f"{node.id!r}")
            if node.stage not in STAGES:
                raise PlanError(f"{nid}: unknown stage {node.stage!r}")
            if node.id != node_id(node.stage, node.key.epoch, node.key.task):
                raise PlanError(f"{nid}: id does not encode its stage/"
                                f"lineage key {node.key}")
            if node.key.seed != self.seed or node.key.epoch != self.epoch:
                raise PlanError(
                    f"{nid}: lineage key {node.key} disagrees with plan "
                    f"(seed={self.seed}, epoch={self.epoch})")
            for dep in node.deps:
                if dep not in self.nodes:
                    raise PlanError(f"{nid}: unknown dep {dep!r}")
            {"map": maps, "reduce": reduces,
             "route": routes}[node.stage].append(node)
        if {n.key.task for n in maps} != set(range(len(self.filenames))):
            raise PlanError("map tasks do not cover the file list "
                            f"(files={len(self.filenames)})")
        if {n.key.task for n in reduces} != set(range(self.num_reducers)):
            raise PlanError("reduce tasks do not cover "
                            f"range({self.num_reducers})")
        if {n.key.task for n in routes} != set(range(self.num_trainers)):
            raise PlanError("route tasks do not cover "
                            f"range({self.num_trainers})")
        map_ids = {n.id for n in maps}
        for node in reduces:
            if set(node.deps) != map_ids:
                raise PlanError(
                    f"{node.id}: a reduce must depend on every map "
                    "(its permutation gathers one chunk per file)")
        covered: List[int] = []
        for node in sorted(routes, key=lambda n: n.key.task):
            span = node.meta.get("reducers")
            expect_queue = queue_index(self.epoch, node.key.task,
                                       self.num_trainers)
            if node.meta.get("queue") != expect_queue:
                raise PlanError(f"{node.id}: queue {node.meta.get('queue')}"
                                f" != queue_index() {expect_queue}")
            if span is None:
                raise PlanError(f"{node.id}: route without a reducers span")
            covered.extend(span)
            want_deps = {node_id("reduce", self.epoch, r) for r in span}
            if set(node.deps) != want_deps:
                raise PlanError(f"{node.id}: deps do not match its "
                                "reducers span")
        if covered != list(range(self.num_reducers)):
            raise PlanError("route nodes do not cover the reducer range "
                            "contiguously exactly once")
        self._check_acyclic()

    def _check_acyclic(self) -> None:
        indegree = {nid: len(n.deps) for nid, n in self.nodes.items()}
        ready = [nid for nid, d in indegree.items() if d == 0]
        dependents = self.dependents()
        seen = 0
        while ready:
            nid = ready.pop()
            seen += 1
            for child in dependents[nid]:
                indegree[child] -= 1
                if indegree[child] == 0:
                    ready.append(child)
        if seen != len(self.nodes):
            raise PlanError("dependency cycle detected")

    # -- serialization --------------------------------------------------

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "version": self.version,
            "seed": self.seed,
            "epoch": self.epoch,
            "num_reducers": self.num_reducers,
            "num_trainers": self.num_trainers,
            "filenames": list(self.filenames),
            "nodes": [n.as_dict() for n in self.nodes.values()],
        }
        if self.window is not None:
            # After "nodes" on purpose: absent for static plans, so the
            # pre-streaming serialization stays byte-identical.
            d["window"] = dict(sorted(self.window.items()))
        if self.tenant_id is not None:
            # Same back-compat contract as "window": single-tenant
            # plans serialize byte-identically to pre-tenancy ones.
            d["tenant_id"] = self.tenant_id
        return d

    def to_json(self, indent: Optional[int] = None) -> str:
        """Stable serialization: fixed top-level key order, nodes in
        build order, node dicts with sorted meta — byte-identical for
        equal plans."""
        return json.dumps(self.to_dict(), indent=indent, sort_keys=False)

    @classmethod
    def from_dict(cls, data: Mapping[str, Any]) -> "EpochPlan":
        try:
            window = data.get("window")
            plan = cls(seed=int(data["seed"]), epoch=int(data["epoch"]),
                       num_reducers=int(data["num_reducers"]),
                       num_trainers=int(data["num_trainers"]),
                       filenames=[str(f) for f in data["filenames"]],
                       version=int(data.get("version", PLAN_VERSION)),
                       window=dict(window) if window is not None else None,
                       tenant_id=data.get("tenant_id"))
        except (KeyError, TypeError, ValueError) as e:
            raise PlanError(f"malformed plan: {e}") from e
        for node_data in data.get("nodes", ()):
            node = PlanNode.from_dict(node_data)
            if node.id in plan.nodes:
                raise PlanError(f"duplicate node id {node.id!r}")
            plan.nodes[node.id] = node
        return plan


def from_json(text: str) -> EpochPlan:
    try:
        data = json.loads(text)
    except json.JSONDecodeError as e:
        raise PlanError(f"plan is not valid JSON: {e}") from e
    if not isinstance(data, dict):
        raise PlanError("plan JSON must be an object")
    return EpochPlan.from_dict(data)


def build_epoch_plan(filenames: Iterable[str], num_reducers: int,
                     num_trainers: int, seed: int, epoch: int,
                     window: Optional[Dict[str, Any]] = None,
                     tenant_id: Optional[str] = None) -> EpochPlan:
    """Build (and validate) the canonical plan of one epoch:
    one map node per file, one reduce node per reducer (depending on
    every map), one route node per trainer rank consuming its contiguous
    reducer span and naming its queue index. ``window`` stamps streaming
    provenance onto the plan (closed-window epochs); ``tenant_id``
    stamps the owning tenant (tenancy plans)."""
    plan = EpochPlan(seed=seed, epoch=epoch, num_reducers=num_reducers,
                     num_trainers=num_trainers,
                     filenames=[str(f) for f in filenames],
                     window=dict(window) if window is not None else None,
                     tenant_id=tenant_id)
    map_ids = []
    for file_index, filename in enumerate(plan.filenames):
        nid = node_id("map", epoch, file_index)
        plan.nodes[nid] = PlanNode(
            id=nid, stage="map", key=LineageKey(seed, epoch, file_index),
            meta={"file": filename, "file_index": file_index})
        map_ids.append(nid)
    reduce_ids = []
    for reduce_index in range(num_reducers):
        nid = node_id("reduce", epoch, reduce_index)
        plan.nodes[nid] = PlanNode(
            id=nid, stage="reduce",
            key=LineageKey(seed, epoch, reduce_index),
            deps=tuple(map_ids))
        reduce_ids.append(nid)
    for rank, (start, stop) in enumerate(route_slices(num_reducers,
                                                      num_trainers)):
        nid = node_id("route", epoch, rank)
        plan.nodes[nid] = PlanNode(
            id=nid, stage="route", key=LineageKey(seed, epoch, rank),
            deps=tuple(reduce_ids[start:stop]),
            meta={"rank": rank,
                  "queue": queue_index(epoch, rank, num_trainers),
                  "reducers": list(range(start, stop))})
    plan.validate()
    return plan


# ---------------------------------------------------------------------------
# Epoch specs: what the generalized shuffle driver iterates
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class EpochSpec:
    """One epoch's worth of work, as the shuffle driver sees it BEFORE a
    plan is built: the epoch index, the files it shuffles, and optional
    streaming window provenance (stamped onto the built plan).

    The driver loop in ``shuffle.py`` consumes an *iterator* of these —
    the static file list compiles to :func:`static_epoch_specs`, a
    stream's window assembler yields them unboundedly as windows close.
    The ``static-epoch-assumption`` rsdl-lint rule pins the inversion:
    library code no longer counts epochs with ``range(num_epochs)``;
    epochs arrive from here.

    ``num_reducers`` overrides the driver-wide reducer count for THIS
    epoch (None = the driver default): the elastic-membership hook that
    lets a streaming run retopologize at a window seal — window N built
    on the old view's count, window N+1 on the new one — with zero
    replay, because each epoch's plan always carried its own reducer
    count."""

    epoch: int
    filenames: Tuple[str, ...]
    window: Optional[Dict[str, Any]] = None
    tenant_id: Optional[str] = None
    num_reducers: Optional[int] = None


def static_epoch_specs(filenames: Iterable[str], num_epochs: int,
                       start_epoch: int = 0,
                       tenant_id: Optional[str] = None
                       ) -> Iterable[EpochSpec]:
    """The classic epochs-over-a-fixed-file-list schedule as an epoch-spec
    iterator: every epoch reshuffles the same files, ``start_epoch``
    resumes mid-trial. THE one place the per-trial epoch range is
    enumerated (shuffle.py consumes the iterator, never the count)."""
    files = tuple(str(f) for f in filenames)
    for epoch in range(start_epoch, num_epochs):
        yield EpochSpec(epoch=epoch, filenames=files,
                        tenant_id=tenant_id)


def epoch_range(start_epoch: int, num_epochs: Optional[int]):
    """Epoch indices for a consumer: ``range`` for a bounded trial,
    ``itertools.count`` when ``num_epochs`` is None (an unbounded stream
    — epochs keep arriving as windows close). Consumers iterate this
    instead of hand-rolling ``range(num_epochs)``; the
    ``static-epoch-assumption`` lint rule enforces it."""
    if num_epochs is None:
        return itertools.count(start_epoch)
    return range(start_epoch, num_epochs)


# ---------------------------------------------------------------------------
# Serving-plane shard map (the PR 10 queue fabric config)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ShardMap:
    """The serving plane's one config object: which shard serves which
    (trainer, epoch) queue, and where each shard listens.

    Replaces the single ``(host, port)`` of the pre-sharded topology.
    Placement is the :func:`queue_shard` plan query (by rank), so the
    map is pure data — ``addresses[i]`` is shard ``i``'s ``(host,
    port)``. Stdlib-only and JSON round-trippable (stable key order)
    like :class:`EpochPlan`, so tools and child-process configs can
    carry it verbatim.

    ``overrides`` (rank -> shard) layers the rebalancer's live moves on
    top of the static ``rank % num_shards`` arithmetic, and
    ``generation`` counts committed placement changes — it is the fence
    stamped into every wire frame so a zombie source shard's post-move
    frames are loudly droppable. Both serialize only when non-default,
    so pre-rebalance maps round-trip byte-identically.
    """

    num_trainers: int
    addresses: List[Tuple[str, int]]
    version: int = SHARD_MAP_VERSION
    overrides: Dict[int, int] = dataclasses.field(default_factory=dict)
    generation: int = 0

    @property
    def num_shards(self) -> int:
        return len(self.addresses)

    def validate(self) -> None:
        if self.version != SHARD_MAP_VERSION:
            raise PlanError(
                f"shard map version {self.version} != {SHARD_MAP_VERSION}")
        if self.num_trainers < 1:
            raise PlanError("shard map needs num_trainers >= 1")
        if not self.addresses:
            raise PlanError("shard map needs at least one shard address")
        for addr in self.addresses:
            if len(tuple(addr)) != 2 or not isinstance(addr[0], str):
                raise PlanError(f"malformed shard address {addr!r}")
        if self.generation < 0:
            raise PlanError("shard map generation must be >= 0")
        for rank, shard in self.overrides.items():
            if not 0 <= int(rank) < self.num_trainers:
                raise PlanError(f"override for unknown rank {rank}")
            if not 0 <= int(shard) < self.num_shards:
                raise PlanError(
                    f"override routes rank {rank} to unknown shard {shard}")

    def shard_for_queue(self, queue_idx: int) -> int:
        return self.shard_for_rank(
            queue_rank(queue_idx, self.num_trainers))

    def shard_for_rank(self, rank: int) -> int:
        return self.overrides.get(rank, rank % self.num_shards)

    def ranks_for_shard(self, shard: int) -> List[int]:
        return [rank for rank in range(self.num_trainers)
                if self.shard_for_rank(rank) == shard]

    def address_for_queue(self, queue_idx: int) -> Tuple[str, int]:
        return tuple(self.addresses[self.shard_for_queue(queue_idx)])

    def to_dict(self) -> Dict[str, Any]:
        data: Dict[str, Any] = {
            "version": self.version,
            "num_trainers": self.num_trainers,
            "addresses": [[host, int(port)]
                          for host, port in self.addresses],
        }
        if self.overrides:
            data["overrides"] = {str(rank): int(shard) for rank, shard
                                 in sorted(self.overrides.items())}
        if self.generation:
            data["generation"] = self.generation
        return data

    def to_json(self, indent: Optional[int] = None) -> str:
        return json.dumps(self.to_dict(), indent=indent, sort_keys=False)

    @classmethod
    def from_dict(cls, data: Mapping[str, Any]) -> "ShardMap":
        try:
            shard_map = cls(
                num_trainers=int(data["num_trainers"]),
                addresses=[(str(h), int(p)) for h, p in data["addresses"]],
                version=int(data.get("version", SHARD_MAP_VERSION)),
                overrides={int(rank): int(shard) for rank, shard
                           in dict(data.get("overrides", {})).items()},
                generation=int(data.get("generation", 0)))
        except (KeyError, TypeError, ValueError) as e:
            raise PlanError(f"malformed shard map: {e}") from e
        shard_map.validate()
        return shard_map

    @classmethod
    def from_json(cls, text: str) -> "ShardMap":
        try:
            data = json.loads(text)
        except json.JSONDecodeError as e:
            raise PlanError(f"shard map is not valid JSON: {e}") from e
        if not isinstance(data, dict):
            raise PlanError("shard map JSON must be an object")
        return cls.from_dict(data)


# ---------------------------------------------------------------------------
# Resume queries (the PR 5 journal math, now a plan query)
# ---------------------------------------------------------------------------


def _entry_fields(entry: Any) -> Tuple[int, bool]:
    """``(seq, done)`` from a WatermarkEntry-shaped object or dict."""
    if isinstance(entry, Mapping):
        return int(entry["seq"]), bool(entry.get("done", False))
    return int(entry.seq), bool(getattr(entry, "done", False))


def resume_from_watermarks(state: Mapping[int, Any], num_epochs: int,
                           num_trainers: int,
                           ranks: Optional[Iterable[int]] = None
                           ) -> Tuple[int, Dict[int, int]]:
    """``(start_epoch, skip_items)`` for a restarted producer: the first
    epoch any rank has not fully consumed, and — per queue at/after it —
    how many items (tables + sentinel) of the deterministic re-run are
    already journaled as delivered and must not be re-enqueued.

    ``state`` maps queue index -> a ``checkpoint.WatermarkEntry`` (or a
    dict with ``seq``/``done``). ``ranks`` restricts the scan to the
    trainer ranks the caller actually serves — a restarted queue SHARD
    (``queue_shard`` placement) passes its owned ranks so a foreign
    rank's absent journal entries cannot drag its start epoch back to
    zero. This is the one resume-math implementation;
    ``multiqueue_service._resume_plan`` and
    ``checkpoint.WatermarkJournal.resume_plan`` both delegate here.
    """
    owned = list(ranks) if ranks is not None else list(range(num_trainers))
    start_epoch = num_epochs
    for rank in owned:
        for epoch in range(num_epochs):
            entry = state.get(queue_index(epoch, rank, num_trainers))
            if entry is None or not _entry_fields(entry)[1]:
                start_epoch = min(start_epoch, epoch)
                break
    owned_set = set(owned)
    skip_items = {q: _entry_fields(entry)[0] + 1
                  for q, entry in state.items()
                  if queue_epoch(q, num_trainers) >= start_epoch
                  and queue_rank(q, num_trainers) in owned_set}
    return start_epoch, skip_items
