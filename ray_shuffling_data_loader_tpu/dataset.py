"""Framework-agnostic shuffling dataset API.

Capability parity with the reference's L3 dataset layer (reference:
dataset.py:17-230): a rank-aware iterable dataset where rank 0 creates the
batch queue and launches the multi-epoch shuffle while other ranks connect
by name; the iterator pops reducer-output refs from its per-(epoch, rank)
queue, materializes them, and re-chunks variable-size reducer outputs into
exact ``batch_size``-row batches with a leftover carry buffer, ``drop_last``
handling, a ``set_epoch`` misuse guard, and a join on the shuffle driver
after the final epoch.

TPU-native differences: batches are pyarrow Tables (zero-copy slices of
Arrow buffers) rather than pandas DataFrames; the shuffle driver is a
background thread task rather than a Ray remote task; and a ``seed``
parameter makes every epoch's order replayable. The JAX binding that turns
these tables into device-sharded ``jax.Array`` batches lives in
jax_dataset.py (L4).
"""

from __future__ import annotations

import functools
import timeit
from typing import Iterator, List, Optional, Sequence

import pyarrow as pa

import importlib

from ray_shuffling_data_loader_tpu import executor as ex
from ray_shuffling_data_loader_tpu import multiqueue as mq
from ray_shuffling_data_loader_tpu import spill

# Not ``from ray_shuffling_data_loader_tpu import shuffle``: the package
# __init__ rebinds that attribute to the shuffle() function, so attribute
# import resolves differently under ``python -m`` than under package import.
sh = importlib.import_module("ray_shuffling_data_loader_tpu.shuffle")
from ray_shuffling_data_loader_tpu.plan import ir as plan_ir
from ray_shuffling_data_loader_tpu.runtime import latency as rt_latency
from ray_shuffling_data_loader_tpu.runtime import telemetry as rt_telemetry
from ray_shuffling_data_loader_tpu.utils.config import default_num_reducers
from ray_shuffling_data_loader_tpu.utils.logger import setup_custom_logger

logger = setup_custom_logger(__name__)

# Well-known queue name (reference: dataset.py:11 MULTIQUEUE_ACTOR_NAME).
MULTIQUEUE_NAME = "MultiQueue"


class ShuffleFailure:
    """Poison pill broadcast into every trainer queue when the shuffle
    driver dies, so consumers blocked on ``queue.get`` raise immediately
    instead of hanging forever (the reference has no equivalent; a dead
    shuffle task strands its trainers)."""

    __slots__ = ("error",)

    def __init__(self, error: BaseException):
        self.error = error


def make_failure_broadcaster(batch_queue: mq.MultiQueue,
                             num_queues: int):
    """``on_failure`` hook for ``run_shuffle_in_background``: put a
    :class:`ShuffleFailure` into every queue. A full bounded queue has
    items EVICTED to make room — the pipeline is dead, so pending batches
    are worthless, and without the marker a consumer that drains the
    buffered batches would block forever on the next ``get``."""

    def broadcast(error: BaseException) -> None:
        marker = ShuffleFailure(error)
        for queue_idx in range(num_queues):
            # Evict-and-retry loop, bounded in case a live consumer races
            # the eviction: each iteration frees one slot, so maxsize
            # iterations always suffice absent consumers.
            for _ in range(10_000):
                try:
                    batch_queue.put_nowait(queue_idx, marker)
                    break
                except mq.Full:
                    try:
                        batch_queue.get_nowait(queue_idx)
                    except mq.Empty:
                        continue  # consumer drained it; retry the put
                except RuntimeError:
                    break  # queue shut down — nobody left to notify

    return broadcast


def batch_consumer(queue: mq.MultiQueue,
                   num_trainers: int,
                   rank: int,
                   epoch: int,
                   batches: Optional[Sequence[ex.TaskRef]]) -> None:
    """Glue given to the shuffler: route reducer refs into the right queue
    (reference: dataset.py:213-224). ``None`` is the epoch-end sentinel.
    The queue index is a plan query (plan/ir.py) — the one home of the
    route-key arithmetic the ``lineage-outside-plan`` lint rule pins."""
    queue_idx = plan_ir.queue_index(epoch, rank, num_trainers)
    if batches is None:
        queue.put(queue_idx, None)
    else:
        queue.put_batch(queue_idx, list(batches))


def debug_batch_consumer(rank: int,
                         epoch: int,
                         batches: Optional[Sequence[ex.TaskRef]]) -> None:
    """Print-only consumer for eyeballing the shuffle alone
    (reference: dataset.py:227-230)."""
    num_batches = len(batches) if batches is not None else 0
    print(f"Received {num_batches} batches in consumer {rank}.")


def create_batch_queue_and_shuffle(
        filenames: Sequence[str],
        num_epochs: int,
        num_trainers: int,
        batch_size: int,
        max_concurrent_epochs: int,
        num_reducers: Optional[int] = None,
        max_batch_queue_size: int = 0,
        seed: int = 0,
        num_workers: Optional[int] = None,
        queue_name: str = MULTIQUEUE_NAME,
        start_epoch: int = 0,
        map_transform=None,
        reduce_transform=None,
        task_retries: int = 0,
        file_cache="auto",
        max_inflight_bytes: Optional[int] = None,
        spill_dir: Optional[str] = None):
    """Driver-mode helper: create the queue and start the shuffle before any
    trainer exists, so every rank can be a pure consumer
    (reference: dataset.py:17-51)."""
    if not 0 <= start_epoch <= num_epochs:
        raise ValueError(
            f"start_epoch {start_epoch} out of range [0, {num_epochs}]")
    batch_queue = mq.MultiQueue(
        num_epochs * num_trainers, max_batch_queue_size, name=queue_name)
    batch_queue.size(0)  # liveness probe kept for parity (dataset.py:106)
    if num_reducers is None:
        num_reducers = default_num_reducers(num_trainers)
    logger.info(
        "Starting shuffle: %d files, %d epochs, %d reducers, %d trainers",
        len(filenames), num_epochs, num_reducers, num_trainers)
    shuffle_result = sh.run_shuffle_in_background(
        filenames,
        functools.partial(batch_consumer, batch_queue, num_trainers),
        num_epochs,
        num_reducers,
        num_trainers,
        max_concurrent_epochs,
        seed=seed,
        num_workers=num_workers,
        collect_stats=False,
        start_epoch=start_epoch,
        map_transform=map_transform,
        reduce_transform=reduce_transform,
        task_retries=task_retries,
        file_cache=file_cache,
        max_inflight_bytes=max_inflight_bytes,
        spill_dir=spill_dir,
        on_failure=make_failure_broadcaster(batch_queue,
                                            num_epochs * num_trainers))
    return batch_queue, shuffle_result


def connect_remote_queue(target, **remote_kwargs):
    """One connector for every remote-queue topology: pass a single
    ``(host, port)`` and get a ``multiqueue_service.RemoteQueue``; pass
    a shard map (a ``plan.ir.ShardMap``, its dict, or its JSON — what
    ``runtime.supervisor.launch_supervised_queue_shards`` returns) and
    get a ``multiqueue_service.ShardedRemoteQueue`` that routes each
    per-rank stream to its serving shard. Either return value drops
    into ``ShufflingDataset(batch_queue=...)`` unchanged — consumer
    code does not know how many shards serve it."""
    from ray_shuffling_data_loader_tpu import multiqueue_service as svc
    if isinstance(target, tuple) and len(target) == 2 \
            and isinstance(target[0], str):
        return svc.RemoteQueue(target, **remote_kwargs)
    return svc.ShardedRemoteQueue(target, **remote_kwargs)


class ShufflingDataset:
    """Iterable dataset of exact-size shuffled batches
    (reference: dataset.py:53-210).

    Rank 0 creates the named queue and kicks off shuffling for up to
    ``max_concurrent_epochs`` epochs at construction; other ranks connect to
    the queue by name. Alternatively pass ``batch_queue=``/
    ``shuffle_result=`` from :func:`create_batch_queue_and_shuffle` and all
    ranks are pure consumers (the pattern the distributed trainer example
    uses, reference: dataset.py:84-85,133-135).

    Call :meth:`set_epoch` before each epoch's iteration; the iterator
    yields pyarrow Tables of exactly ``batch_size`` rows (final partial
    batch included unless ``drop_last``).
    """

    def __init__(self,
                 filenames: Sequence[str],
                 num_epochs: Optional[int],
                 num_trainers: int,
                 batch_size: int,
                 rank: int,
                 drop_last: bool = False,
                 num_reducers: Optional[int] = None,
                 max_concurrent_epochs: int = 2,
                 batch_queue: Optional[mq.MultiQueue] = None,
                 shuffle_result: Optional[ex.TaskRef] = None,
                 max_batch_queue_size: int = 0,
                 seed: int = 0,
                 num_workers: Optional[int] = None,
                 queue_name: str = MULTIQUEUE_NAME,
                 start_epoch: int = 0,
                 map_transform=None,
                 reduce_transform=None,
                 task_retries: int = 0,
                 file_cache="auto",
                 max_inflight_bytes: Optional[int] = None,
                 spill_dir: Optional[str] = None):
        if num_reducers is None:
            num_reducers = default_num_reducers(num_trainers)
        self._batch_size = batch_size

        self._owns_queue = False
        if batch_queue is None:
            if rank == 0 and num_epochs is None:
                # Unbounded (streaming) consumption is pure-consumer:
                # epochs are produced by a streaming runner or a
                # supervised queue server whose window schedule bounds
                # the queue count — this constructor cannot size a
                # queue for "forever".
                raise ValueError(
                    "num_epochs=None (unbounded streaming) requires a "
                    "batch_queue from the streaming serving plane; "
                    "rank 0 cannot launch a static shuffle without an "
                    "epoch count")
            if rank == 0:
                self._batch_queue, self._shuffle_result = (
                    create_batch_queue_and_shuffle(
                        filenames, num_epochs, num_trainers, batch_size,
                        max_concurrent_epochs, num_reducers,
                        max_batch_queue_size, seed=seed,
                        num_workers=num_workers, queue_name=queue_name,
                        start_epoch=start_epoch,
                        map_transform=map_transform,
                        reduce_transform=reduce_transform,
                        task_retries=task_retries,
                        file_cache=file_cache,
                        max_inflight_bytes=max_inflight_bytes,
                        spill_dir=spill_dir))
                self._owns_queue = True
            else:
                self._batch_queue = mq.MultiQueue(
                    0, name=queue_name, connect=True)
                self._shuffle_result = None
        else:
            self._batch_queue = batch_queue
            self._shuffle_result = shuffle_result

        if num_epochs is not None and not 0 <= start_epoch <= num_epochs:
            raise ValueError(
                f"start_epoch {start_epoch} out of range [0, {num_epochs}]")
        if num_epochs is None and start_epoch < 0:
            raise ValueError(
                f"start_epoch {start_epoch} must be >= 0")
        self._start_epoch = start_epoch
        self._num_epochs = num_epochs
        self._num_trainers = num_trainers
        self._rank = rank
        self._seed = seed
        self._skip_batches = 0
        self._epoch: Optional[int] = None
        # Guards against iterating without a fresh set_epoch
        # (reference: dataset.py:143-168).
        self._last_epoch: Optional[int] = None
        self._drop_last = drop_last
        # Delivery-latency plane (runtime/latency.py): the end-to-end
        # birth->delivered hop is observed HERE for in-process queues
        # (reducer output metadata -> consumer hand-off). Remote queue
        # clients see the wire stamps first and observe it themselves —
        # their `observes_delivery` marker keeps the hop single-counted.
        self._lat_observe = not getattr(self._batch_queue,
                                        "observes_delivery", False)
        self._lat_queue = str(rank)
        self._lat_anchors = rt_latency.ClockAnchors()

    @property
    def batch_size(self) -> int:
        return self._batch_size

    @property
    def seed(self) -> int:
        return self._seed

    @property
    def num_epochs(self) -> Optional[int]:
        """Epoch count of the trial; None means unbounded (streaming)."""
        return self._num_epochs

    @property
    def num_trainers(self) -> int:
        return self._num_trainers

    @property
    def rank(self) -> int:
        return self._rank

    @property
    def start_epoch(self) -> int:
        return self._start_epoch

    @property
    def drop_last(self) -> bool:
        return self._drop_last

    def set_epoch(self, epoch: int, skip_batches: int = 0) -> None:
        """Declare the epoch about to be iterated. Must be called before
        each epoch's iteration (reference: dataset.py:147-157).

        ``skip_batches`` drops the first N batches of the epoch as zero-copy
        Arrow slices — the cheap path for checkpoint resume (the rows are
        still shuffled/fetched, but never converted or transferred).
        """
        if epoch < self._start_epoch:
            raise ValueError(
                f"epoch {epoch} precedes start_epoch {self._start_epoch}; "
                "epochs before the resume point are never shuffled and "
                "iterating them would block forever")
        if skip_batches < 0:
            raise ValueError(f"skip_batches must be >= 0, got {skip_batches}")
        self._skip_batches = skip_batches
        self._epoch = epoch

    def iter_tables(self) -> Iterator[pa.Table]:
        """Yield this epoch's raw reducer tables (variable row counts).

        Handles everything the batch iterator needs below it: the set_epoch
        guard, the epoch's queue drain with sentinel/failure handling, the
        ``skip_batches`` row skip (applied here as whole-table drops and one
        zero-copy slice), and the end-of-trial shuffle join. The JAX binding
        consumes this directly in device-rebatch mode, where batch slicing
        happens on the accelerator instead of in Arrow.
        """
        if self._epoch is None or self._epoch == self._last_epoch:
            raise ValueError(
                "You must set the epoch on this dataset via set_epoch() at "
                "the beginning of each epoch, before iterating over this "
                "dataset (e.g. via enumerate(ds)).")

        skip_rows = self._skip_batches * self._batch_size  # rows, not batches
        to_skip = skip_rows
        self._skip_batches = 0
        queue_idx = plan_ir.queue_index(self._epoch, self._rank,
                                        self._num_trainers)
        # Positioned gets (multiqueue_service.RemoteQueue) return the
        # table's absolute row offset in the queue's stream. A replaying
        # queue legally restarts the stream mid-epoch (at the consumer's
        # last durable watermark), so a checkpoint-resume skip must be
        # absolute — "drop rows before position skip_rows" — not a count
        # of rows seen on THIS connection.
        get_positioned = getattr(self._batch_queue, "get_positioned", None)
        while True:
            # Epoch-tagged queue wait: this is where a consumer blocks
            # when the shuffle cannot keep up — the "queue_wait" stage
            # of the bottleneck decomposition (the queue layer's own
            # queue_get events have no epoch identity). Manual
            # begin/end span so a get() that dies still records the
            # time the consumer sat here (the span-unbalanced lint
            # rule pins the finally shape).
            wait_span = rt_telemetry.span_begin(
                "queue_wait", epoch=self._epoch, task=queue_idx)
            try:
                if get_positioned is not None:
                    ref, row_offset = get_positioned(queue_idx)
                else:
                    ref = self._batch_queue.get(queue_idx, block=True)
                    row_offset = None
            finally:
                rt_telemetry.span_end(wait_span)
            if ref is None:
                break
            if isinstance(ref, ShuffleFailure):
                raise RuntimeError(
                    "the shuffle driver died; no more batches are coming"
                ) from ref.error
            # In-process queues carry TaskRefs; remote queue clients
            # (multiqueue_service.py) deliver materialized tables. A
            # budget-spilled reducer output arrives as a lazy handle and
            # is memory-mapped back here (spill.py) — but only if any of
            # it survives the resume skip: a fully-skipped handle is
            # dropped unloaded (its finalizer unlinks the file).
            raw = ref.result() if hasattr(ref, "result") else ref
            if row_offset is not None:
                to_skip = max(0, skip_rows - row_offset)
            if to_skip and raw.num_rows <= to_skip:
                if row_offset is None:
                    to_skip -= raw.num_rows
                continue
            table: pa.Table = spill.unwrap(raw)
            if self._lat_observe:
                meta = table.schema.metadata
                birth = rt_latency.parse_stamp(
                    meta.get(rt_latency.BIRTH_META_KEY) if meta else None)
                if birth is not None:
                    age = self._lat_anchors.latency_s(birth)
                    rt_latency.observe_hop(
                        rt_latency.HOP_BIRTH_TO_DELIVERED,
                        self._lat_queue, age)
                    rt_latency.set_freshness(self._lat_queue, age)
            if to_skip:
                table = table.slice(to_skip)
                to_skip = 0
            yield table
            # Drop the consumed table before blocking on the next get:
            # this frame would otherwise pin it (delaying its ledger
            # release — the budget wait in shuffle.py wakes on that
            # release) for as long as the queue stays empty.
            ref = raw = table = None
        self._last_epoch = self._epoch
        # Epoch-complete hook: logs the one-line bottleneck verdict
        # (first completion wins — the JAX binding's consumer-side end
        # calls this too, whichever finishes first).
        rt_telemetry.epoch_complete(self._epoch, source="dataset")
        if (self._num_epochs is not None
                and self._epoch == self._num_epochs - 1
                and self._shuffle_result is not None):
            # Join the shuffle driver (reference: dataset.py:208-210), then
            # release the queue's name so a later trial in the same process
            # can reuse it.
            self._shuffle_result.result()
            self.shutdown()

    def __iter__(self) -> Iterator[pa.Table]:
        return slice_batches(self.iter_tables(), self._batch_size,
                             self._drop_last)

    def commit_consumed(self) -> None:
        """Tell a manual-ack batch queue that consumption so far is
        durable (``checkpoint.resume_iterator`` calls this after every
        checkpoint save). No-op for in-process queues and auto-ack
        remote queues."""
        commit = getattr(self._batch_queue, "commit", None)
        if commit is not None:
            commit()

    def shutdown(self) -> None:
        """Release the named queue if this dataset created it. Idempotent.

        The reference leaks its named actor until process exit; we free the
        name so back-to-back trials in one process work.
        """
        if self._owns_queue:
            self._batch_queue.shutdown()
            self._owns_queue = False


def slice_batches(tables: Iterator[pa.Table], batch_size: int,
                  drop_last: bool) -> Iterator[pa.Table]:
    """Exact-size re-batching over a stream of variable-size tables.

    The leftover carry buffer spans table boundaries (reference keeps a
    DataFrame buffer, dataset.py:170-202; we keep a list of zero-copy
    table slices and concat only when yielding). Shared by
    ``ShufflingDataset.__iter__`` and the JAX binding's per-batch fallback
    so their batch grids cannot diverge.
    """
    carry: List[pa.Table] = []
    carry_rows = 0
    for table in tables:
        offset = 0
        num_rows = table.num_rows
        # Top up the carry buffer to a full batch first.
        if carry_rows:
            need = batch_size - carry_rows
            take = min(need, num_rows)
            carry.append(table.slice(0, take))
            carry_rows += take
            offset = take
            if carry_rows == batch_size:
                # permissive promotion: the >2GiB fallback promotes
                # offsets PER REDUCER OUTPUT (shuffle.py), so one epoch
                # stream may legally mix large_* and 32-bit-offset
                # schemas and an unpromoted concat would raise
                # ArrowInvalid exactly in the huge-corpus regime.
                yield pa.concat_tables(carry, promote_options="permissive")
                carry = []
                carry_rows = 0
        # Yield full batches straight out of this table, zero-copy.
        while num_rows - offset >= batch_size:
            yield table.slice(offset, batch_size)
            offset += batch_size
        # Stash the tail.
        if offset < num_rows:
            carry.append(table.slice(offset))
            carry_rows += num_rows - offset
    if carry_rows and not drop_last:
        yield pa.concat_tables(carry, promote_options="permissive")


if __name__ == "__main__":
    # Smoke driver (reference: dataset.py:233-276): generate synthetic rows
    # locally, run a few epochs through the full pipeline, count batches.
    import argparse
    import tempfile
    import timeit

    from ray_shuffling_data_loader_tpu import data_generation as dg

    parser = argparse.ArgumentParser(description="ShufflingDataset smoke run")
    parser.add_argument("--num-rows", type=int, default=10**6)
    parser.add_argument("--num-files", type=int, default=10)
    parser.add_argument("--num-epochs", type=int, default=4)
    parser.add_argument("--num-reducers", type=int, default=8)
    parser.add_argument("--batch-size", type=int, default=50_000)
    parser.add_argument("--max-concurrent-epochs", type=int, default=2)
    args = parser.parse_args()

    with tempfile.TemporaryDirectory() as tmpdir:
        print(f"Generating {args.num_rows} rows over {args.num_files} files.")
        filenames, _ = dg.generate_data_local(args.num_rows, args.num_files,
                                              1, 0.0, tmpdir)
        print(f"Starting {args.num_epochs}-epoch consumption, "
              f"{args.num_reducers} reducers, 1 trainer.")
        start = timeit.default_timer()
        ds = ShufflingDataset(filenames,
                              args.num_epochs,
                              num_trainers=1,
                              batch_size=args.batch_size,
                              rank=0,
                              num_reducers=args.num_reducers,
                              max_concurrent_epochs=args.max_concurrent_epochs)
        for epoch in plan_ir.epoch_range(0, args.num_epochs):
            ds.set_epoch(epoch)
            rows = batches = 0
            for batch in ds:
                batches += 1
                rows += batch.num_rows
            assert rows == args.num_rows, (rows, args.num_rows)
            print(f"epoch {epoch}: {batches} batches, {rows} rows")
        duration = timeit.default_timer() - start
        total = args.num_epochs * args.num_rows
        print(f"Done: {total} rows in {duration:.2f}s "
              f"({total / duration:,.0f} rows/s)")
