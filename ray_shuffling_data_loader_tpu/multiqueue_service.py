"""Cross-process queue service: trainer processes attach by address.

The reference's queue is a Ray actor precisely so that trainer processes
spawned elsewhere (Horovod workers with no handle to driver state) can
rendezvous with the pipeline by name (reference: multiqueue.py:310-332,
SURVEY.md §1). Our in-process ``MultiQueue`` covers the SPMD
one-process-per-host topology; this module restores the reference's
*separate-trainer-process* topology:

- :func:`serve_queue` exports an existing ``MultiQueue`` over TCP. For
  each GET the server resolves the queued ref to its pyarrow Table and
  streams it as Arrow IPC — consumers never see executor internals, and
  data crosses the process boundary zero-copy on the Arrow buffers.
- :class:`RemoteQueue` is the consumer side: ``get(queue_idx)`` returns a
  materialized ``pa.Table`` (or ``None`` for the epoch-end sentinel), so
  it plugs straight into ``ShufflingDataset(batch_queue=...)`` /
  ``JaxShufflingDataset`` — same consumer code as in-process, matching
  the reference's connect-by-name contract (retry with doubling backoff).

Wire format, little-endian: requests are ``(u32 queue_idx)``; responses
are ``(u8 kind, u64 length, payload)`` with kind 0=table IPC stream,
1=epoch-end sentinel, 2=shuffle-failure (payload = error text).
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from typing import Optional, Tuple

import pyarrow as pa

from ray_shuffling_data_loader_tpu import multiqueue as mq
from ray_shuffling_data_loader_tpu.dataset import ShuffleFailure
from ray_shuffling_data_loader_tpu.utils.logger import setup_custom_logger

logger = setup_custom_logger(__name__)

_REQUEST = struct.Struct("<I")
_RESPONSE = struct.Struct("<BQ")

KIND_TABLE = 0
KIND_SENTINEL = 1
KIND_FAILURE = 2


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    remaining = n
    while remaining:
        chunk = sock.recv(min(remaining, 1 << 20))
        if not chunk:
            raise ConnectionError("peer closed connection mid-message")
        chunks.append(chunk)
        remaining -= len(chunk)
    return b"".join(chunks) if len(chunks) != 1 else chunks[0]


def _serialize(table: pa.Table) -> pa.Buffer:
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, table.schema) as writer:
        writer.write_table(table)
    return sink.getvalue()


class QueueServer:
    """Exports a ``MultiQueue`` over TCP. One thread per consumer
    connection; a GET blocks server-side until the queue yields (and the
    ref materializes), so consumer backpressure is preserved."""

    def __init__(self, queue: mq.MultiQueue, address: Tuple[str, int]):
        self._queue = queue
        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.bind(address)
        listener.listen(16)
        self._listener = listener
        self._closed = threading.Event()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="rsdl-qserve-accept")
        self._accept_thread.start()

    @property
    def address(self) -> Tuple[str, int]:
        return self._listener.getsockname()

    def _accept_loop(self) -> None:
        while not self._closed.is_set():
            try:
                conn, peer = self._listener.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True, name="rsdl-qserve-conn").start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while not self._closed.is_set():
                raw = conn.recv(_REQUEST.size)
                if not raw:
                    return  # consumer done
                if len(raw) < _REQUEST.size:
                    raw += _recv_exact(conn, _REQUEST.size - len(raw))
                (queue_idx,) = _REQUEST.unpack(raw)
                item = self._queue.get(queue_idx, block=True)
                if item is None:
                    conn.sendall(_RESPONSE.pack(KIND_SENTINEL, 0))
                elif isinstance(item, ShuffleFailure):
                    text = repr(item.error).encode()
                    conn.sendall(_RESPONSE.pack(KIND_FAILURE, len(text)))
                    conn.sendall(text)
                else:
                    try:
                        table = (item.result() if hasattr(item, "result")
                                 else item)
                        from ray_shuffling_data_loader_tpu import spill
                        table = spill.unwrap(table)
                        payload = _serialize(table)
                    except Exception as e:  # noqa: BLE001 - forwarded
                        # A failed shuffle task ref: the consumer gets the
                        # real cause as a failure frame, not a dead socket.
                        text = repr(e).encode()
                        conn.sendall(
                            _RESPONSE.pack(KIND_FAILURE, len(text)))
                        conn.sendall(text)
                        continue
                    conn.sendall(_RESPONSE.pack(KIND_TABLE, payload.size))
                    conn.sendall(payload)
        except (ConnectionError, OSError) as e:
            if not self._closed.is_set():
                logger.warning("queue server connection dropped: %s", e)
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def close(self) -> None:
        self._closed.set()
        try:
            self._listener.close()
        except OSError:
            pass

    def __enter__(self) -> "QueueServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def serve_queue(queue: mq.MultiQueue,
                address: Tuple[str, int] = ("127.0.0.1", 0)) -> QueueServer:
    """Start serving ``queue`` on ``address`` (port 0 = ephemeral)."""
    return QueueServer(queue, address)


class RemoteQueue:
    """Consumer-side handle to a served queue.

    ``get`` returns a materialized ``pa.Table``, ``None`` (epoch end), or
    a :class:`ShuffleFailure` — the exact item vocabulary
    ``ShufflingDataset.__iter__`` consumes, so
    ``ShufflingDataset(batch_queue=RemoteQueue(addr), shuffle_result=None)``
    is a drop-in remote trainer. Connects with the reference's
    retry-with-doubling-backoff schedule (reference: multiqueue.py:310-332).
    """

    def __init__(self, address: Tuple[str, int],
                 retries: int = mq.CONNECT_RETRIES,
                 initial_backoff_s: float = mq.CONNECT_INITIAL_BACKOFF_S):
        last_err: Optional[Exception] = None
        backoff = initial_backoff_s
        for attempt in range(retries + 1):
            try:
                self._sock = socket.create_connection(address, timeout=30)
                self._sock.settimeout(None)
                self._sock.setsockopt(socket.IPPROTO_TCP,
                                      socket.TCP_NODELAY, 1)
                last_err = None
                break
            except OSError as e:
                last_err = e
                if attempt < retries:
                    time.sleep(backoff)
                    backoff *= 2
        if last_err is not None:
            raise ConnectionError(
                f"could not reach queue server at {address} after "
                f"{retries + 1} attempts: {last_err}")
        self._lock = threading.Lock()

    def get(self, queue_index: int, block: bool = True):
        if not block:
            raise ValueError("RemoteQueue only supports blocking gets")
        with self._lock:
            self._sock.sendall(_REQUEST.pack(queue_index))
            header = _recv_exact(self._sock, _RESPONSE.size)
            kind, length = _RESPONSE.unpack(header)
            payload = _recv_exact(self._sock, length) if length else b""
        if kind == KIND_SENTINEL:
            return None
        if kind == KIND_FAILURE:
            return ShuffleFailure(RuntimeError(payload.decode()))
        with pa.ipc.open_stream(pa.BufferReader(payload)) as reader:
            return reader.read_all()

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self) -> "RemoteQueue":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
