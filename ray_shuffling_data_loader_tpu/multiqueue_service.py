"""Cross-process queue service: trainer processes attach by address.

The reference's queue is a Ray actor precisely so that trainer processes
spawned elsewhere (Horovod workers with no handle to driver state) can
rendezvous with the pipeline by name (reference: multiqueue.py:310-332,
SURVEY.md §1). Our in-process ``MultiQueue`` covers the SPMD
one-process-per-host topology; this module restores the reference's
*separate-trainer-process* topology:

- :func:`serve_queue` exports an existing ``MultiQueue`` over TCP. For
  each GET the server resolves the queued ref to its pyarrow Table and
  streams it as Arrow IPC — consumers never see executor internals, and
  data crosses the process boundary zero-copy on the Arrow buffers.
- :class:`RemoteQueue` is the consumer side: ``get(queue_idx)`` returns a
  materialized ``pa.Table`` (or ``None`` for the epoch-end sentinel), so
  it plugs straight into ``ShufflingDataset(batch_queue=...)`` /
  ``JaxShufflingDataset`` — same consumer code as in-process, matching
  the reference's connect-by-name contract (retry with doubling backoff).

Round-trip amortization (the reference's batched actor ops existed for
exactly this, reference: multiqueue.py:127-154): a GET request carries
``max_items``; the server answers with one *batch* — a blocking get for
the first item, then an opportunistic non-blocking drain of up to
``max_items - 1`` more, stopping at an epoch sentinel. The consumer
buffers the batch locally and, while the trainer chews on it, a
background prefetcher keeps one batched request in flight — so steady
state pays ~one round trip per ``max_items`` tables and overlaps the
wire time with consumption.

Wire format, little-endian: requests are ``(u8 op=1, u32 queue_idx,
u32 max_items)``; responses are ``(u32 count)`` followed by ``count``
frames of ``(u8 kind, u64 length, payload)`` with kind 0=table IPC
stream, 1=epoch-end sentinel, 2=shuffle-failure (payload = error text).
"""

from __future__ import annotations

import collections
import concurrent.futures as cf
import socket
import struct
import threading
from typing import Dict, List, Tuple

import pyarrow as pa

from ray_shuffling_data_loader_tpu import multiqueue as mq
from ray_shuffling_data_loader_tpu.dataset import ShuffleFailure
from ray_shuffling_data_loader_tpu.runtime import faults as rt_faults
from ray_shuffling_data_loader_tpu.runtime import retry as rt_retry
from ray_shuffling_data_loader_tpu.runtime import telemetry as rt_telemetry
from ray_shuffling_data_loader_tpu.utils.logger import setup_custom_logger

logger = setup_custom_logger(__name__)

_REQUEST = struct.Struct("<BII")
_BATCH_HEADER = struct.Struct("<I")
_FRAME = struct.Struct("<BQ")

OP_GET_BATCH = 1

KIND_TABLE = 0
KIND_SENTINEL = 1
KIND_FAILURE = 2

DEFAULT_MAX_BATCH = 8


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    remaining = n
    while remaining:
        chunk = sock.recv(min(remaining, 1 << 20))
        if not chunk:
            raise ConnectionError("peer closed connection mid-message")
        chunks.append(chunk)
        remaining -= len(chunk)
    return b"".join(chunks) if len(chunks) != 1 else chunks[0]


def _serialize(table: pa.Table) -> pa.Buffer:
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, table.schema) as writer:
        writer.write_table(table)
    return sink.getvalue()


def _item_frame(item) -> Tuple[int, bytes]:
    """Convert one queued item into a ``(kind, payload)`` frame."""
    if item is None:
        return KIND_SENTINEL, b""
    if isinstance(item, ShuffleFailure):
        return KIND_FAILURE, repr(item.error).encode()
    try:
        table = item.result() if hasattr(item, "result") else item
        from ray_shuffling_data_loader_tpu import spill
        table = spill.unwrap(table)
        return KIND_TABLE, _serialize(table)
    except Exception as e:  # noqa: BLE001 - forwarded
        # A failed shuffle task ref: the consumer gets the real cause as
        # a failure frame, not a dead socket.
        return KIND_FAILURE, repr(e).encode()


class QueueServer:
    """Exports a ``MultiQueue`` over TCP. One thread per consumer
    connection; the first item of each batched GET blocks server-side
    until the queue yields (and the ref materializes), so consumer
    backpressure is preserved; the rest of the batch is an opportunistic
    non-blocking drain."""

    def __init__(self, queue: mq.MultiQueue, address: Tuple[str, int]):
        self._queue = queue
        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.bind(address)
        listener.listen(16)
        self._listener = listener
        self._closed = threading.Event()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="rsdl-qserve-accept")
        self._accept_thread.start()

    @property
    def address(self) -> Tuple[str, int]:
        return self._listener.getsockname()

    def _accept_loop(self) -> None:
        while not self._closed.is_set():
            try:
                conn, peer = self._listener.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True, name="rsdl-qserve-conn").start()

    def _drain_batch(self, queue_idx: int, max_items: int) -> List:
        """One blocking get, then drain up to ``max_items - 1`` more
        without blocking; stop after a sentinel/failure so requests never
        cross an epoch boundary (a speculative get past the sentinel
        would block forever on the drained per-epoch queue)."""
        items = [self._queue.get(queue_idx, block=True)]
        while (len(items) < max_items and items[-1] is not None
               and not isinstance(items[-1], ShuffleFailure)):
            try:
                items.append(self._queue.get_nowait(queue_idx))
            except mq.Empty:
                break
        return items

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while not self._closed.is_set():
                raw = conn.recv(_REQUEST.size)
                if not raw:
                    return  # consumer done
                if len(raw) < _REQUEST.size:
                    raw += _recv_exact(conn, _REQUEST.size - len(raw))
                op, queue_idx, max_items = _REQUEST.unpack(raw)
                if op != OP_GET_BATCH:
                    raise ConnectionError(f"unknown request op {op}")
                try:
                    items = self._drain_batch(queue_idx, max(1, max_items))
                except mq.ShutdownError as e:
                    # Queue shut down under a blocked GET: fail loudly
                    # (the reference's actor kill surfaced as
                    # RayActorError on the consumer).
                    text = repr(e).encode()
                    conn.sendall(_BATCH_HEADER.pack(1)
                                 + _FRAME.pack(KIND_FAILURE, len(text))
                                 + text)
                    return
                conn.sendall(_BATCH_HEADER.pack(len(items)))
                for item in items:
                    kind, payload = _item_frame(item)
                    size = (payload.size if isinstance(payload, pa.Buffer)
                            else len(payload))
                    conn.sendall(_FRAME.pack(kind, size))
                    if size:
                        conn.sendall(payload)
        except (ConnectionError, OSError) as e:
            if not self._closed.is_set():
                logger.warning("queue server connection dropped: %s", e)
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def close(self) -> None:
        self._closed.set()
        try:
            self._listener.close()
        except OSError:
            pass

    def __enter__(self) -> "QueueServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def serve_queue(queue: mq.MultiQueue,
                address: Tuple[str, int] = ("127.0.0.1", 0)) -> QueueServer:
    """Start serving ``queue`` on ``address`` (port 0 = ephemeral)."""
    return QueueServer(queue, address)


class RemoteQueue:
    """Consumer-side handle to a served queue.

    ``get`` returns a materialized ``pa.Table``, ``None`` (epoch end), or
    a :class:`ShuffleFailure` — the exact item vocabulary
    ``ShufflingDataset.__iter__`` consumes, so
    ``ShufflingDataset(batch_queue=RemoteQueue(addr), shuffle_result=None)``
    is a drop-in remote trainer. Connects with the reference's
    retry-with-doubling-backoff schedule (reference: multiqueue.py:310-332).

    ``max_batch`` tables ride each round trip, and with ``prefetch=True``
    (default) a background thread keeps the next batched request in
    flight while the consumer drains the local buffer — the wire is
    overlapped with consumption instead of serialized against it.
    """

    def __init__(self, address: Tuple[str, int],
                 retries: int = mq.CONNECT_RETRIES,
                 initial_backoff_s: float = mq.CONNECT_INITIAL_BACKOFF_S,
                 max_batch: int = DEFAULT_MAX_BATCH,
                 prefetch: bool = True):
        self._address = address
        # One RetryPolicy for connect AND mid-stream refetch: jittered
        # doubling backoff (many trainer processes dialing one server
        # de-synchronize), attempts pinned by the caller's budget.
        self._retry = rt_retry.RetryPolicy.for_component(
            "queue", retry_max_attempts=retries + 1,
            retry_initial_backoff_s=initial_backoff_s,
            retryable=rt_retry.transient_retryable)
        try:
            self._retry.call(self._reconnect, describe=f"connect {address}")
        except OSError as e:
            raise ConnectionError(
                f"could not reach queue server at {address} after "
                f"{retries + 1} attempts: {e}")
        self._max_batch = max(1, max_batch)
        self._prefetch = prefetch
        self._io_lock = threading.Lock()      # serializes wire round trips
        self._state_lock = threading.Lock()   # guards buffers/done/pending
        self._buffers: Dict[int, collections.deque] = \
            collections.defaultdict(collections.deque)
        self._done: set = set()
        self._pending: Dict[int, cf.Future] = {}
        self._io = cf.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="rsdl-rqueue-prefetch")

    def _reconnect(self) -> None:
        """(Re-)dial the queue server; the old socket (if any) is closed
        first so a half-dead connection cannot leak."""
        old = getattr(self, "_sock", None)
        if old is not None:
            try:
                old.close()
            except OSError:
                pass
        sock = socket.create_connection(self._address, timeout=30)
        sock.settimeout(None)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = sock

    def _fetch_batch(self, queue_index: int) -> List:
        """One wire round trip: request up to ``max_batch`` items and
        decode the response frames. Runs on the caller's thread or the
        prefetcher; ``_io_lock`` keeps round trips whole.

        Failure handling rides the shared RetryPolicy: a round trip that
        dies BEFORE any response byte arrived (server restart, injected
        ``queue_fetch`` fault) reconnects and re-issues the request — the
        server pops queue items only while writing the response, so an
        unanswered request consumed nothing and the re-request cannot
        skip data. Once response bytes have been read, a failure is NOT
        retried (items may already be popped server-side; a blind
        re-request could silently lose them) and surfaces loudly.
        """

        def _round_trip() -> List:
            response_started = False
            try:
                with self._io_lock:
                    rt_faults.inject("queue_fetch", task=queue_index)
                    self._sock.sendall(_REQUEST.pack(
                        OP_GET_BATCH, queue_index, self._max_batch))
                    (count,) = _BATCH_HEADER.unpack(
                        _recv_exact(self._sock, _BATCH_HEADER.size))
                    response_started = True
                    frames = []
                    for _ in range(count):
                        kind, length = _FRAME.unpack(
                            _recv_exact(self._sock, _FRAME.size))
                        payload = (_recv_exact(self._sock, length)
                                   if length else b"")
                        frames.append((kind, payload))
                return frames
            except (ConnectionError, OSError) as e:
                if response_started:
                    raise RuntimeError(
                        f"queue fetch for index {queue_index} died "
                        f"mid-response; items may be lost — not retrying: "
                        f"{e}") from e
                raise

        def _redial(error: BaseException) -> None:
            if isinstance(error, (ConnectionError, OSError)):
                self._reconnect()

        with rt_telemetry.span("queue_fetch", task=queue_index):
            frames = self._retry.call(
                _round_trip, describe=f"fetch queue {queue_index}",
                on_retry=_redial)
        items: List = []
        for kind, payload in frames:
            if kind == KIND_SENTINEL:
                items.append(None)
                break  # epoch over; nothing valid can follow
            if kind == KIND_FAILURE:
                items.append(ShuffleFailure(RuntimeError(payload.decode())))
                break
            with pa.ipc.open_stream(pa.BufferReader(payload)) as reader:
                items.append(reader.read_all())
        return items

    def _epoch_over(self, item) -> bool:
        return item is None or isinstance(item, ShuffleFailure)

    def _ingest(self, queue_index: int, items: List) -> None:
        buf = self._buffers[queue_index]
        buf.extend(items)
        if items and self._epoch_over(items[-1]):
            self._done.add(queue_index)
        elif self._prefetch and queue_index not in self._pending:
            # Submit the NEXT batched request as soon as this one lands,
            # so the wire round trip overlaps the consumption of the
            # whole freshly-buffered batch (costs one extra batch of
            # client-side buffering); waiting until the buffer drained
            # would overlap only the last item's consumption.
            # _ingest is only ever called with _state_lock held by its
            # caller (get below), so this write IS lock-guarded:
            # rsdl-lint: disable=lock-mutation
            self._pending[queue_index] = self._io.submit(
                self._fetch_batch, queue_index)

    def get(self, queue_index: int, block: bool = True):
        if not block:
            raise ValueError("RemoteQueue only supports blocking gets")
        with self._state_lock:
            buf = self._buffers[queue_index]
            while not buf:
                if queue_index in self._done:
                    raise RuntimeError(
                        f"remote queue {queue_index} already yielded its "
                        f"epoch-end sentinel")
                # At most ONE in-flight request per queue index: a second
                # concurrent getter on the same index waits on the SAME
                # future instead of issuing its own round trip, which
                # could ingest batches out of request order. The future
                # stays registered while in flight; whichever waiter
                # observes it still registered after completion unlinks
                # it and ingests — exactly once.
                fut = self._pending.get(queue_index)
                if fut is None:
                    fut = self._pending[queue_index] = self._io.submit(
                        self._fetch_batch, queue_index)
                # Do the (possibly long) wire wait without holding the
                # state lock, so a concurrent get on another queue index
                # can still drain its local buffer.
                self._state_lock.release()
                try:
                    # The wire wait runs with _state_lock RELEASED (the
                    # release/reacquire bracket above/below); the static
                    # with-block scope is wider than the dynamic hold:
                    # rsdl-lint: disable=lock-blocking-call
                    items = fut.result()
                finally:
                    self._state_lock.acquire()
                    mine = self._pending.get(queue_index) is fut
                    if mine:
                        del self._pending[queue_index]
                if mine:
                    self._ingest(queue_index, items)
            item = buf.popleft()
        return item

    def close(self) -> None:
        self._io.shutdown(wait=False, cancel_futures=True)
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self) -> "RemoteQueue":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
