"""Cross-process queue service: trainer processes attach by address.

The reference's queue is a Ray actor precisely so that trainer processes
spawned elsewhere (Horovod workers with no handle to driver state) can
rendezvous with the pipeline by name (reference: multiqueue.py:310-332,
SURVEY.md §1). Our in-process ``MultiQueue`` covers the SPMD
one-process-per-host topology; this module restores the reference's
*separate-trainer-process* topology:

- :func:`serve_queue` exports an existing ``MultiQueue`` over TCP. For
  each GET the server resolves the queued ref to its pyarrow Table and
  streams it as Arrow IPC — consumers never see executor internals, and
  data crosses the process boundary zero-copy on the Arrow buffers.
- :class:`RemoteQueue` is the consumer side: ``get(queue_idx)`` returns a
  materialized ``pa.Table`` (or ``None`` for the epoch-end sentinel), so
  it plugs straight into ``ShufflingDataset(batch_queue=...)`` /
  ``JaxShufflingDataset`` — same consumer code as in-process, matching
  the reference's connect-by-name contract (retry with doubling backoff).

Round-trip amortization (the reference's batched actor ops existed for
exactly this, reference: multiqueue.py:127-154): a GET request carries
``max_items``; the server answers with one *batch* — a blocking get for
the first item, then an opportunistic non-blocking drain of up to
``max_items - 1`` more, stopping at an epoch sentinel. The consumer
buffers the batch locally and, while the trainer chews on it, a
background prefetcher keeps one batched request in flight — so steady
state pays ~one round trip per ``max_items`` tables and overlaps the
wire time with consumption.

Wire format **v2** (process-crash recovery), little-endian. Requests are
a fixed 14-byte struct ``(u8 op, u8 flags, u32 a, u32 b, u32 c)``:

====================  =====================================================
op                    fields
====================  =====================================================
``1 OP_GET_BATCH``    a=queue_idx, b=max_items, c=ack watermark (the last
                      seq the consumer durably consumed for this queue;
                      ``0xFFFFFFFF`` = none). ``flags & FLAG_RESUME``:
                      first GET on a (re)connected socket — the server
                      rewinds its send cursor to the ack watermark and
                      replays exactly the unacked suffix.
``2 OP_HELLO``        a|b<<32 = 64-bit consumer id (lease identity; sent
                      once per connection, survives reconnects).
``3 OP_HEARTBEAT``    consumer-side lease keep-alive between GETs.
``4 OP_NACK``         a=queue_idx, b=seq of a frame whose CRC failed; the
                      server rewinds its send cursor to ``seq - 1`` and
                      re-sends from its replay buffer.
``5 OP_TENANT``       a|b<<32 = 64-bit consumer id, c = byte length of a
                      JSON ``TenantContext`` blob that follows the
                      request struct (tenancy/__init__.py canonical
                      form). Binds this consumer's lease — and the
                      ranks it subsequently GETs — to the tenant, so
                      the weighted-fair scheduler and per-tenant
                      metrics attribute its bytes. Optional: servers
                      ignore unknown-tenant blobs gracefully and
                      legacy clients never send it (v3.2, backward and
                      forward compatible).
====================  =====================================================

Responses are ``(u32 count)`` followed by ``count`` frames of
``(u8 kind, u32 epoch, u32 seq, u32 crc32, u64 row_offset, u64 length,
u32 task, payload)`` with kind 0=table IPC stream, 1=epoch-end
sentinel, 2=shuffle-failure (payload = error text). ``task`` is the
producing reduce task's lineage id (``0xFFFFFFFF`` = unknown), read
from the ``rsdl.trace`` schema metadata the reducer stamped on its
output — the cross-process causal-trace context (runtime/trace.py):
the consumer records it per frame, so a merged trace joins this
frame's fetch to the exact server-side reduce span that built it.
``seq`` is a per-queue
monotonic frame number (stable across server restarts — restored from
the delivered-watermark journal); ``crc32`` covers the payload bytes
(zlib CRC-32), so corruption anywhere on the wire or in a replayed
buffer is detected at the consumer and NACK'd; ``row_offset`` is the
cumulative row count of all preceding table frames in this queue's
stream, which lets a checkpoint-resuming consumer skip already-consumed
rows *absolutely* even when the stream replays from mid-epoch.

The **v1** format (pre-recovery, for archaeology): requests were
``(u8 op=1, u32 queue_idx, u32 max_items)`` and frames were bare
``(u8 kind, u64 length, payload)`` — no identity, no integrity, no ack:
the server popped items destructively before streaming them, so a
connection reset mid-response silently lost batches, and a killed
server process lost every queued table.

Recovery semantics built on v2 (see ``examples/fault_tolerance.md`` for
the full process-failure matrix):

- The server keeps a bounded per-queue **replay buffer** of unacked
  frames; acks piggyback on every GET and are journaled
  (``checkpoint.WatermarkJournal``), so a connection reset at ANY byte
  of a response is recovered by reconnect + FLAG_RESUME — exactly-once
  delivery, asserted bit-identical in tests.
- A killed server process is restarted by
  ``runtime.supervisor.ProcessSupervisor``; :func:`serve_pipeline`
  reloads the journal and re-runs the deterministic shuffle lineage for
  the in-flight epoch, re-enqueueing only the undelivered remainder.
- Per-consumer **leases** (heartbeats ride on every request plus an idle
  keep-alive thread) detect crashed trainers; expiry policy
  ``RSDL_QUEUE_ON_DEAD_CONSUMER`` = ``fail_fast`` | ``drain`` |
  ``redistribute`` decides whether the pipeline dies loudly, frees the
  dead rank's queues, or reroutes its undelivered tables to survivors.

Wire format **v3** (sharded zero-copy serving plane) extends v2 in
place — same request struct, same frame struct, same recovery matrix:

- The frame ``kind`` byte now carries a codec in its high nibble
  (``kind | codec << 4``; codec 0 = none, 1 = zlib, 2 = zstd, 3 = lz4).
  Streamed table payloads at/above ``RSDL_QUEUE_COMPRESSION_MIN_BYTES``
  are compressed when ``RSDL_QUEUE_COMPRESSION`` names a codec; ``crc``
  is computed over the UNCOMPRESSED payload, so corruption detection
  and NACK/replay semantics are byte-for-byte the v2 ones.
- New frame kind ``KIND_TABLE_HANDLE``: when server and consumer share
  a host (the consumer offered ``FLAG_HANDLES_OK`` on its HELLO), a
  table frame's payload is a ~100-byte shm **segment handle**
  (``{"path", "offset", "size", "crc"}``) instead of the table bytes —
  the consumer mmaps the very buffers the server serialized
  (``procpool.read_segment_buffer``), verifies the segment CRC off the
  mapped pages, and acks by seq exactly as before. The replay buffer
  retains the handle and PINS the segment via the NativeBufferPool
  ledger (``procpool.pin_segment``) until the ack lands — unacked
  bytes stay accounted, but exist exactly once, in shared memory.
  ``OP_NACK`` with ``c=1`` (``NACK_NO_HANDLE``) reports an unusable
  handle (a mis-detected host split, a vanished segment): the server
  marks that queue stream-only, rewinds, and replays the same frames
  as byte streams — delivery degrades, exactly-once does not.
- Queues are served by N **shard** processes placed by the plan query
  ``plan.ir.queue_shard`` (by trainer rank, so one rank's whole stream
  lives on one shard); a :class:`plan.ir.ShardMap` replaces the single
  ``(host, port)``. :class:`ShardedQueueServer` /
  :class:`ShardedRemoteQueue` are the in-process pair;
  ``runtime.supervisor.launch_supervised_queue_shards`` is the
  per-shard-supervised-process topology, each shard with its own
  watermark journal (``checkpoint.shard_journal_path``).

Wire format **v3.1** (delivery-latency plane, runtime/latency.py)
appends two clock stamps to every frame header: the payload's **birth**
(``(t_mono, t_unix, pid)`` taken where the reducer produced the table,
read from its ``rsdl.birth`` schema metadata) and the frame's
**queued** stamp (taken when the server built the frame). Zeroed
stamps mean "unknown" (sentinels, failure frames, tables from a
stamp-less producer). The server observes the ``birth_to_queued`` hop;
the consumer observes ``queued_to_delivered`` and the end-to-end
``birth_to_delivered`` into the ``rsdl_delivery_latency_seconds``
sketch, labeled by trainer rank. Latency honesty across failure:

- replay-buffer frames keep the stamps they were built with, so a
  reconnect/NACK replay is delivered with its ORIGINAL birth — a
  replay surfaces as the latency spike it really is;
- a frame's birth is also journaled (``WatermarkJournal.record_birth``)
  when the frame is first built, so a ``kill -9``'d server's restarted
  incarnation re-attaches the original births to the frames it
  regenerates — crash recovery cannot launder delivery latency into
  recompute-fresh stamps. Exactly-once semantics (seqs, CRCs, acks)
  are untouched by all of this: stamps are header-only evidence.
"""

from __future__ import annotations

import base64
import collections
import concurrent.futures as cf
import itertools
import json
import os
import shutil
import socket
import struct
import sys
import tempfile
import threading
import time
import zlib
from typing import Callable, Dict, List, Optional, Tuple, Union

import pyarrow as pa

from ray_shuffling_data_loader_tpu import multiqueue as mq
from ray_shuffling_data_loader_tpu import procpool as pp
from ray_shuffling_data_loader_tpu import tenancy as rt_tenancy
from ray_shuffling_data_loader_tpu.dataset import ShuffleFailure
from ray_shuffling_data_loader_tpu.plan import ir as plan_ir
from ray_shuffling_data_loader_tpu.tenancy import fairshare as rt_fairshare
from ray_shuffling_data_loader_tpu.runtime import faults as rt_faults
from ray_shuffling_data_loader_tpu.runtime import latency as rt_lat
from ray_shuffling_data_loader_tpu.runtime import metrics as rt_metrics
from ray_shuffling_data_loader_tpu.runtime import policy as rt_policy
from ray_shuffling_data_loader_tpu.runtime import retry as rt_retry
from ray_shuffling_data_loader_tpu.runtime import telemetry as rt_telemetry
from ray_shuffling_data_loader_tpu.utils.logger import setup_custom_logger

logger = setup_custom_logger(__name__)

_REQUEST = struct.Struct("<BBIII")
_BATCH_HEADER = struct.Struct("<I")
#: v3.3 frame header: (kind|codec<<4, epoch, seq, crc, row_offset,
#: length, task) + the delivery-latency stamps — birth (t_mono, t_unix,
#: pid) then queued (t_mono, t_unix, pid); all-zero stamp = unknown —
#: then the placement ``generation`` (rebalance/): the fence a consumer
#: compares against its per-rank floor, so a zombie source shard's
#: post-migration frames are loudly droppable (the membership
#: incarnation-fencing idiom applied to queue placement). Pre-rebalance
#: servers stamp 0 and pre-rebalance clients never raise their floor,
#: so the fence is inert until a move commits.
_FRAME = struct.Struct("<BIIIQQIddIddII")


def _pack_stamp(stamp) -> tuple:
    """A latency Stamp (or None) as the 3 header fields."""
    if stamp is None:
        return (0.0, 0.0, 0)
    return (stamp.t_mono, stamp.t_unix, stamp.pid)


def _unpack_stamp(t_mono: float, t_unix: float, pid: int):
    if not t_mono and not t_unix:
        return None
    return rt_lat.Stamp(pid, t_mono, t_unix)

#: Frame ``task`` value for payloads with no lineage metadata
#: (sentinels, failure frames, tables from a non-reduce producer).
TASK_NONE = 0xFFFFFFFF

OP_GET_BATCH = 1
OP_HELLO = 2
OP_HEARTBEAT = 3
OP_NACK = 4
#: v3.2: bind a consumer lease to a TenantContext (a|b<<32 = consumer
#: id, c = length of the JSON blob following the request struct).
OP_TENANT = 5
#: v3.3: rebalance admin verb (rebalance/). ``flags`` is the phase
#: (REB_*), ``a`` = trainer rank, ``b`` = placement generation, ``c`` =
#: length of the JSON payload following the request. The response is a
#: u32 length + a ``checkpoint.crc_line`` JSON payload (the handoff
#: manifest for PREPARE; an ack/error blob otherwise).
OP_REBALANCE = 6

#: OP_REBALANCE phases: PREPARE seals the rank at a watermark and
#: exports the CRC'd handoff manifest; ADOPT imports it on the target
#: at the new generation (journaled — the durable half of COMMIT);
#: RELEASE drops the rank on the source and arms MOVED redirects;
#: UNSEAL is the abort path (source resumes, authoritative).
REB_PREPARE = 1
REB_ADOPT = 2
REB_RELEASE = 3
REB_UNSEAL = 4

FLAG_RESUME = 1
#: OP_HELLO flag: the consumer can mmap paths on the server's host
#: (loopback or a shared shm mount) — the server may answer table GETs
#: with segment handles instead of streamed bytes.
FLAG_HANDLES_OK = 2

KIND_TABLE = 0
KIND_SENTINEL = 1
KIND_FAILURE = 2
#: Table delivered as a shm segment handle (payload = JSON blob with
#: path/offset/size/crc); the header CRC covers the blob itself.
KIND_TABLE_HANDLE = 3
#: v3.3 redirect (rebalance/): the queue's rank migrated to another
#: shard. Payload = JSON blob with host/port/generation/rank; the
#: header CRC covers the blob and the header generation carries the
#: new placement generation (the consumer raises its fence floor
#: BEFORE redialing, so the old home's stale frames can never race in
#: after the redirect).
KIND_MOVED = 4

#: High nibble of the frame kind byte: payload codec.
_KIND_MASK = 0x0F
CODEC_NONE, CODEC_ZLIB, CODEC_ZSTD, CODEC_LZ4 = 0, 1, 2, 3
_CODEC_IDS = {"zlib": CODEC_ZLIB, "zstd": CODEC_ZSTD, "lz4": CODEC_LZ4}

#: OP_NACK ``c`` field: 0 = CRC corruption (rewind + re-send), 1 = the
#: consumer cannot use shm handles on this queue (downgrade to stream).
NACK_CRC = 0
NACK_NO_HANDLE = 1

#: "no watermark" on the wire (seq is u32; -1 internally).
ACK_NONE = 0xFFFFFFFF

DEFAULT_MAX_BATCH = 8

_LOOPBACK_HOSTS = frozenset({"127.0.0.1", "localhost", "::1"})


def _crc(payload) -> int:
    """CRC-32 (zlib-compatible) of a bytes-like payload, as an unsigned
    u32. Runs on the native hardware/slice-by-8 kernel when loaded
    (``RSDL_CRC_BACKEND`` selects; the polynomial and output match
    ``zlib.crc32`` bit for bit, so frames CRC'd by either backend verify
    under the other)."""
    from ray_shuffling_data_loader_tpu import native
    return native.crc32(memoryview(payload)) & 0xFFFFFFFF


_codec_warned: set = set()


def _resolve_compression() -> Optional[Tuple[int, Callable]]:
    """``(codec_id, compress)`` for the RSDL_QUEUE_COMPRESSION policy, or
    None when off. zstd/lz4 degrade to zlib with a one-time warning when
    the codec module is not importable (nothing is pip-installed here)."""
    name = str(rt_policy.resolve("queue", "queue_compression")).strip()
    name = name.lower()
    if name in ("", "off", "0", "none", "false"):
        return None
    if name not in _CODEC_IDS:
        raise ValueError(
            f"RSDL_QUEUE_COMPRESSION must be off, zlib, zstd or lz4; "
            f"got {name!r}")
    if name == "zstd":
        try:
            import zstandard
            return CODEC_ZSTD, zstandard.ZstdCompressor().compress
        except ImportError:
            pass
    elif name == "lz4":
        try:
            import lz4.frame
            return CODEC_LZ4, lz4.frame.compress
        except ImportError:
            pass
    if name != "zlib" and name not in _codec_warned:
        _codec_warned.add(name)
        logger.warning("queue compression codec %r is not installed; "
                       "degrading to zlib", name)
    # level 1: the wire win is latency-bound, not ratio-bound. zlib
    # accepts any buffer-protocol object, so pa.Buffer payloads compress
    # without an intermediate bytes copy.
    return CODEC_ZLIB, lambda data: zlib.compress(data, 1)


def _decompress(codec: int, payload) -> bytes:
    if codec == CODEC_ZLIB:
        return zlib.decompress(payload)
    if codec == CODEC_ZSTD:
        import zstandard
        return zstandard.ZstdDecompressor().decompress(bytes(payload))
    if codec == CODEC_LZ4:
        import lz4.frame
        return lz4.frame.decompress(bytes(payload))
    raise ValueError(f"unknown frame codec {codec}")


try:
    _IOV_MAX = os.sysconf("SC_IOV_MAX")
    if _IOV_MAX <= 0:
        _IOV_MAX = 1024
except (AttributeError, ValueError, OSError):
    _IOV_MAX = 1024


def _sendmsg_all(sock: socket.socket, buffers) -> None:
    """Write every buffer to ``sock`` with scatter-gather ``sendmsg`` —
    one syscall for a whole GET response (headers + payloads) where the
    legacy path issued ``1 + 2N`` ``sendall`` calls. Handles partial
    sends with a continuation loop and batches the iovec list under the
    kernel's IOV_MAX; the bytes on the wire are identical to the
    sequential-sendall ordering by construction."""
    views = [m for m in (memoryview(b).cast("B") for b in buffers)
             if m.nbytes]
    idx = 0
    while idx < len(views):
        sent = sock.sendmsg(views[idx:idx + _IOV_MAX])
        while sent > 0:
            view = views[idx]
            if sent >= view.nbytes:
                sent -= view.nbytes
                idx += 1
            else:
                views[idx] = view[sent:]
                sent = 0


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    remaining = n
    while remaining:
        chunk = sock.recv(min(remaining, 1 << 20))
        if not chunk:
            raise ConnectionError("peer closed connection mid-message")
        chunks.append(chunk)
        remaining -= len(chunk)
    return b"".join(chunks) if len(chunks) != 1 else chunks[0]


def _recv_payload(sock: socket.socket, n: int) -> memoryview:
    """Receive exactly ``n`` payload bytes into ONE preallocated buffer
    via ``recv_into`` — no per-chunk bytes objects, no join copy (the
    v2 path built a chunk list and re-copied it into one ``bytes``;
    large frames paid the payload twice). The returned memoryview is
    held end to end: CRC, decompression and Arrow IPC decode all read
    it in place."""
    buf = bytearray(n)
    view = memoryview(buf)
    received = 0
    while received < n:
        got = sock.recv_into(view[received:], n - received)
        if not got:
            raise ConnectionError("peer closed connection mid-message")
        received += got
    return view


def _serialize(table: pa.Table) -> pa.Buffer:
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, table.schema) as writer:
        writer.write_table(table)
    return sink.getvalue()


def _producer_task(table: pa.Table) -> int:
    """Producing reduce task from the ``rsdl.trace`` schema metadata the
    reducer stamped (``"seed:epoch:task"``); TASK_NONE when absent."""
    meta = table.schema.metadata
    if not meta:
        return TASK_NONE
    raw = meta.get(b"rsdl.trace")
    if not raw:
        return TASK_NONE
    try:
        return int(raw.rsplit(b":", 1)[-1])
    except ValueError:
        return TASK_NONE


def _materialize(item) -> Tuple[int, object, int, int]:
    """Resolve one queued item into ``(kind, data, num_rows, task)`` —
    ``data`` is the pa.Table for KIND_TABLE (serialization is the frame
    builder's business, because handle delivery writes a segment instead
    of wire bytes) and the payload bytes for sentinel/failure frames."""
    if item is None:
        return KIND_SENTINEL, b"", 0, TASK_NONE
    if isinstance(item, ShuffleFailure):
        return KIND_FAILURE, repr(item.error).encode(), 0, TASK_NONE
    try:
        table = item.result() if hasattr(item, "result") else item
        from ray_shuffling_data_loader_tpu import spill
        table = spill.unwrap(table)
        return KIND_TABLE, table, table.num_rows, _producer_task(table)
    except Exception as e:  # noqa: BLE001 - forwarded
        # A failed shuffle task ref: the consumer gets the real cause as
        # a failure frame, not a dead socket.
        return KIND_FAILURE, repr(e).encode(), 0, TASK_NONE


class _Frame:
    """One response frame held in the server replay buffer.

    ``wire`` is the exact on-wire payload (a pa.Buffer / memoryview /
    bytes — built once, never re-copied); ``crc`` covers the logical
    payload (pre-compression; for handle frames, the blob itself, with
    the segment CRC inside the blob); ``data_crc`` is the CRC of the
    serialized TABLE bytes, kept so a handle frame can be downgraded to
    a byte stream without re-CRCing the segment. ``payload_bytes`` is
    the logical (uncompressed) size; handle frames pin that many shm
    bytes in the buffer ledger (``ledger_id``) until acked.
    """

    __slots__ = ("seq", "kind", "epoch", "wire", "crc", "row_offset",
                 "nrows", "task", "codec", "payload_bytes", "data_crc",
                 "handle_path", "ledger_id", "birth", "queued",
                 "pending_codec", "tenant")

    def __init__(self, seq, kind, epoch, wire, crc, row_offset, nrows,
                 task=TASK_NONE, codec=CODEC_NONE, payload_bytes=None,
                 data_crc=None, handle_path=None, ledger_id=None,
                 birth=None, queued=None):
        self.seq = seq
        self.kind = kind
        self.epoch = epoch
        self.wire = wire
        self.crc = crc
        self.row_offset = row_offset
        self.nrows = nrows
        self.task = task
        self.codec = codec
        self.payload_bytes = (payload_bytes if payload_bytes is not None
                              else self.wire_len)
        self.data_crc = data_crc if data_crc is not None else crc
        self.handle_path = handle_path
        self.ledger_id = ledger_id
        # Delivery-latency stamps (runtime/latency.py). A frame in the
        # replay buffer keeps these, so replays carry the ORIGINAL
        # birth/queued times — late delivery stays visible as such.
        self.birth = birth
        self.queued = queued
        # (future, codec_id) while a codec-pool compression is in
        # flight; the frame serves the uncompressed buffer until
        # :meth:`resolve_codec` swaps the result in.
        self.pending_codec = None
        # The tenant this frame's bytes were CHARGED to at pop time
        # (set by _collect_frames). Ack/reset credit the same account,
        # so a rank->tenant rebind between pop and ack cannot strand
        # the debit on one tenant and land the credit on another.
        self.tenant = None

    def resolve_codec(self) -> int:
        """Finish a deferred codec-pool compression: swap the compressed
        payload in as the wire buffer iff it actually shrank (mirroring
        the inline path's keep-smaller rule). Returns the resident-byte
        delta (<= 0) the caller applies to its replay accounting."""
        fut, codec_id = self.pending_codec
        self.pending_codec = None
        old = self.wire_len
        compressed = fut.result()
        if len(compressed) < self.payload_bytes:
            self.wire = compressed
            self.codec = codec_id
        return self.wire_len - old

    @property
    def wire_len(self) -> int:
        wire = self.wire
        return wire.size if isinstance(wire, pa.Buffer) else len(wire)

    @property
    def size(self) -> int:
        """Bytes this unacked frame actually holds resident — the shm
        segment for handle frames, the (possibly compressed) wire
        payload otherwise. Each byte is charged exactly once: the wire
        buffer IS the replay copy, never a second materialization."""
        if self.kind == KIND_TABLE_HANDLE:
            return self.payload_bytes
        return self.wire_len


class _QueueState:
    """Per-queue-index sequencing + replay state (one consumer per queue
    by the ``queue_id = epoch * num_trainers + rank`` contract)."""

    __slots__ = ("next_seq", "sent_seq", "acked_seq", "acked_rows",
                 "rows_total", "replay", "replay_bytes", "done", "lock",
                 "no_handles", "births")

    def __init__(self, next_seq: int = 0, rows: int = 0,
                 done: bool = False, births=None):
        self.next_seq = next_seq       # seq the next popped item gets
        self.sent_seq = next_seq - 1   # last seq sent on the live conn
        self.acked_seq = next_seq - 1  # last seq the consumer acked
        self.acked_rows = rows         # rows delivered through acked_seq
        self.rows_total = rows         # rows assigned through next_seq-1
        self.replay: collections.deque = collections.deque()  # unacked
        self.replay_bytes = 0
        self.done = done               # sentinel acked: queue complete
        self.lock = threading.Lock()
        self.no_handles = False        # NACK_NO_HANDLE: stream-only
        #: seq -> original birth Stamp restored from the journal: a
        #: restarted server re-attaches these to the frames it
        #: regenerates, so crash replays keep their true birth.
        self.births: Dict[int, rt_lat.Stamp] = births or {}


class _Lease:
    __slots__ = ("consumer_id", "last_beat", "queues", "expired",
                 "tenant")

    def __init__(self, consumer_id: int):
        self.consumer_id = consumer_id
        self.last_beat = time.monotonic()
        self.queues: set = set()
        self.expired = False
        #: tenant id bound by OP_TENANT (None = unbound / legacy client;
        #: attribution then falls back to the server's config table).
        self.tenant: Optional[str] = None


class QueueMoved(Exception):
    """A GET hit a queue whose rank migrated to another shard (the
    server answered with a ``KIND_MOVED`` redirect). Carries everything
    a router needs to follow: the new ``address`` and the committed
    placement ``generation`` (the consumer's fence floor is already
    raised when this is thrown). :class:`ShardedRemoteQueue` handles it
    transparently; a bare :class:`RemoteQueue` surfaces it — a consumer
    that cached a ``(host, port)`` is exactly what the
    ``shard-affinity-assumption`` lint rule exists to catch."""

    def __init__(self, queue_index: int, rank: int,
                 address: Tuple[str, int], generation: int):
        super().__init__(
            f"queue {queue_index} (rank {rank}) moved to "
            f"{address[0]}:{address[1]} at placement generation "
            f"{generation}")
        self.queue_index = queue_index
        self.rank = rank
        self.address = (str(address[0]), int(address[1]))
        self.generation = generation


_POP_CLOSED = object()
_POP_EMPTY = object()


def _put_quiet(queue: mq.MultiQueue, queue_idx: int, item) -> bool:
    """Best-effort redistribution put: a full or shut-down target queue
    drops the item (degrading to drain) instead of wedging the lease
    drainer."""
    try:
        queue.put(queue_idx, item)
        return True
    except (mq.Full, RuntimeError):
        return False


class QueueServer:
    """Exports a ``MultiQueue`` over TCP with the v2 sequenced/acked
    protocol. One thread per consumer connection; the first item of each
    batched GET blocks server-side until the queue yields (and the ref
    materializes), so consumer backpressure is preserved; the rest of the
    batch is an opportunistic non-blocking drain.

    ``journal`` (a ``checkpoint.WatermarkJournal``) persists ack
    watermarks so a restarted server process (``serve_pipeline``) can
    regenerate exactly the undelivered remainder; ``initial_state`` is
    that journal's loaded ``{queue_idx: WatermarkEntry}`` map, which
    restores per-queue sequence numbers and row offsets so frame
    identity is stable across restarts. ``exit_on_crash_site=True``
    (the dedicated-server-process mode) turns an injected
    ``queue_server_crash`` fault into a hard ``os._exit`` — a real
    process death for the supervisor to recover, not an exception.
    """

    def __init__(self, queue: mq.MultiQueue, address: Tuple[str, int],
                 num_trainers: int = 1, journal=None,
                 initial_state: Optional[Dict[int, object]] = None,
                 exit_on_crash_site: bool = False,
                 shard_index: int = 0, num_shards: int = 1,
                 handle_dir: Optional[str] = None,
                 tenants: Optional[dict] = None,
                 placement: Optional[dict] = None):
        self._queue = queue
        self._num_trainers = max(1, num_trainers)
        self._journal = journal
        self._exit_on_crash_site = exit_on_crash_site
        self._shard_index = shard_index
        self._num_shards = max(1, num_shards)
        # -- live-migration placement plane (rebalance/). ``placement``
        # is the serialized state the controller journals:
        # ``{"generation": G, "overrides": {rank: shard},
        #    "rank_generations": {rank: gen}, "addresses": [[h, p]..]}``.
        # A rank whose override routes it *here* is adopted
        # (``_extra_ranks``); a rank that statically belongs here but is
        # overridden *away* answers GETs with a ``KIND_MOVED`` redirect
        # (``_moved``). ``_rank_gen`` is stamped into every outbound
        # frame header — the fence that makes a zombie source's
        # post-move frames loudly droppable at the consumer.
        placement = placement or {}
        self._placement_gen = int(placement.get("generation", 0))
        self._rank_gen: Dict[int, int] = {
            int(r): int(g)
            for r, g in dict(placement.get("rank_generations", {})).items()}
        self._sealed_ranks: set = set()
        self._extra_ranks: set = set()
        self._moved: Dict[int, Tuple[int, Tuple[str, int]]] = {}
        addresses = [tuple(a) for a in placement.get("addresses", ())]
        for r, s in dict(placement.get("overrides", {})).items():
            rank, shard_for_rank = int(r), int(s)
            static = rank % self._num_shards
            if shard_for_rank == static:
                continue
            if shard_for_rank == self._shard_index:
                self._extra_ranks.add(rank)
            elif static == self._shard_index:
                if shard_for_rank >= len(addresses):
                    raise ValueError(
                        f"placement override routes rank {rank} to shard "
                        f"{shard_for_rank} but only {len(addresses)} "
                        f"addresses were supplied")
                self._moved[rank] = (
                    self._rank_gen.get(rank, self._placement_gen),
                    (str(addresses[shard_for_rank][0]),
                     int(addresses[shard_for_rank][1])))
        self._timeout_s = rt_policy.resolve("queue", "queue_timeout_s")
        self._nodelay = rt_policy.resolve("queue", "queue_nodelay")
        self._replay_budget = rt_policy.resolve("queue",
                                                "queue_replay_bytes")
        # -- tenancy plane (tenancy/): weighted-fair sharing of the
        # replay-byte budget. ``tenants`` is the config table
        # ``{tenant_id: {"weight": w, "ranks": [...]}}``; with no table
        # and no OP_TENANT binding the scheduler stays None and every
        # byte of behavior is the pre-tenancy single-tenant one.
        self._tenants = rt_tenancy.tenants_from_config(tenants)
        self._tenant_lock = threading.Lock()
        self._rank_tenant: Dict[int, str] = {}
        for tenant_id, spec in self._tenants.items():
            for rank in spec.get("ranks", ()):
                self._rank_tenant[int(rank)] = tenant_id
        self._fair: Optional[rt_fairshare.FairShare] = None
        if self._tenants:
            self._fair = rt_fairshare.FairShare(
                {t: spec["weight"] for t, spec in self._tenants.items()},
                int(self._replay_budget),
                quantum_bytes=int(rt_policy.resolve(
                    "queue", "tenant_drr_quantum_bytes")),
                active_window_s=float(rt_policy.resolve(
                    "queue", "tenant_active_window_s")))
        self._floor_pace_s = float(rt_policy.resolve(
            "queue", "tenant_floor_pace_s"))
        self._tenant_replay: Dict[str, int] = {}
        self._tenant_metrics: Dict[str, tuple] = {}
        self._lease_timeout_s = rt_policy.resolve("queue",
                                                  "queue_lease_timeout_s")
        self._on_dead_consumer = rt_policy.resolve("queue",
                                                   "on_dead_consumer")
        if self._on_dead_consumer not in ("fail_fast", "drain",
                                          "redistribute"):
            raise ValueError(
                f"RSDL_QUEUE_ON_DEAD_CONSUMER must be fail_fast, drain, or "
                f"redistribute, got {self._on_dead_consumer!r}")
        self._delivery = rt_policy.resolve("queue", "queue_delivery")
        if self._delivery not in ("auto", "stream", "handle"):
            raise ValueError(
                f"RSDL_QUEUE_DELIVERY must be auto, stream or handle, "
                f"got {self._delivery!r}")
        self._compression = _resolve_compression()
        self._compression_min = rt_policy.resolve(
            "queue", "queue_compression_min_bytes")
        self._sendmsg = bool(rt_policy.resolve("queue", "queue_sendmsg"))
        codec_threads = int(rt_policy.resolve("queue",
                                              "queue_codec_threads"))
        # Bounded codec pool: frame compression runs on these threads
        # (overlapping the serving thread's next pop/serialize) and is
        # capped at codec_threads cores across every connection. 0 =
        # compress inline on the serving thread (the legacy shape).
        self._codec_pool = (
            cf.ThreadPoolExecutor(
                max_workers=codec_threads,
                thread_name_prefix=f"rsdl-codec-s{shard_index}")
            if self._compression and codec_threads > 0 else None)
        self._handle_dir = handle_dir
        self._own_handle_dir = False
        self._handle_names = itertools.count()
        shard = str(shard_index)
        self._payload_bytes = rt_metrics.counter(
            "rsdl_queue_payload_bytes_total",
            "logical (uncompressed) table-payload bytes served",
            shard=shard)
        self._wire_bytes = rt_metrics.counter(
            "rsdl_queue_bytes_on_wire_total",
            "payload bytes actually written to consumer sockets",
            shard=shard)
        self._handle_hits = rt_metrics.counter(
            "rsdl_queue_handle_hits_total",
            "table frames delivered as shm segment handles", shard=shard)
        self._handle_misses = rt_metrics.counter(
            "rsdl_queue_handle_misses_total",
            "table frames streamed as bytes (no handle possible)",
            shard=shard)
        self._compression_saved = rt_metrics.counter(
            "rsdl_queue_compression_saved_bytes_total",
            "payload bytes saved by frame compression", shard=shard)
        self._shard_depth = rt_metrics.gauge(
            "rsdl_queue_shard_depth",
            "items resident across this shard's served queues",
            shard=shard)
        self._anchors = rt_lat.ClockAnchors()
        self._states: Dict[int, _QueueState] = {}
        self._states_lock = threading.Lock()
        if initial_state:
            for q, entry in initial_state.items():
                births = {
                    seq: rt_lat.Stamp(int(pid), float(tm), float(tu))
                    for seq, (pid, tm, tu) in
                    getattr(entry, "births", {}).items()}
                self._states[q] = _QueueState(next_seq=entry.seq + 1,
                                              rows=entry.rows,
                                              done=entry.done,
                                              births=births)
        self._leases: Dict[int, _Lease] = {}
        self._lease_lock = threading.Lock()
        self._lease_thread: Optional[threading.Thread] = None
        self._drained_ranks: set = set()
        self._conn_threads: set = set()
        self._conn_lock = threading.Lock()
        self._replayed = rt_metrics.counter(
            "rsdl_queue_frames_replayed_total",
            "frames re-sent from the server replay buffer")
        self._nacked = rt_metrics.counter(
            "rsdl_queue_frames_nacked_total",
            "frames NACK'd by consumers (CRC mismatch)")
        self._lease_expiries = rt_metrics.counter(
            "rsdl_queue_lease_expiries_total",
            "consumer leases that expired without a heartbeat")
        self._consumers_alive = rt_metrics.gauge(
            "rsdl_queue_consumers_alive",
            "consumers with a live (unexpired) lease")
        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.bind(address)
        listener.listen(16)
        # Finite accept timeout: the accept loop ticks so close() can
        # stop it deterministically on every platform (and the
        # socket-op-no-timeout invariant holds by construction).
        listener.settimeout(1.0)
        self._listener = listener
        self._closed = threading.Event()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="rsdl-qserve-accept")
        self._accept_thread.start()

    @property
    def address(self) -> Tuple[str, int]:
        return self._listener.getsockname()

    # -- connection plumbing ------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._closed.is_set():
            try:
                conn, peer = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            if self._nodelay:
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # Socket hygiene (runtime/policy.py): a finite recv timeout
            # so a wedged peer cannot pin this handler past the watchdog;
            # 0 disables (deliberate infinite wait).
            conn.settimeout(self._timeout_s or None)
            thread = threading.Thread(target=self._serve_conn, args=(conn,),
                                      daemon=True, name="rsdl-qserve-conn")
            with self._conn_lock:
                self._conn_threads.add(thread)
            thread.start()

    def _state(self, queue_idx: int) -> _QueueState:
        with self._states_lock:
            state = self._states.get(queue_idx)
            if state is None:
                state = self._states[queue_idx] = _QueueState()
            return state

    def _pop(self, queue_idx: int, blocking: bool, consumer_id):
        """One queue pop; blocking pops tick on a short timeout so close()
        (and the consumer's lease) stay live while the queue is idle.
        ``mq.ShutdownError`` (the QUEUE shut down, not this server)
        propagates so the consumer gets a loud failure frame."""
        rank = plan_ir.queue_rank(queue_idx, self._num_trainers)
        while not self._closed.is_set():
            try:
                return self._queue.get(queue_idx, block=blocking,
                                       timeout=0.25 if blocking else None)
            except mq.Empty:
                if not blocking:
                    return _POP_EMPTY
                if rank in self._sealed_ranks:
                    # The rank was PREPARE-sealed while this GET was
                    # parked on an idle live stream. The caller holds
                    # the queue's state lock, which the migration's
                    # export needs to snapshot the replay suffix — so
                    # give the lock back with an empty batch (the
                    # consumer refetches and lands on the seal path /
                    # MOVED redirect) instead of stalling PREPARE
                    # behind the next produced item.
                    return _POP_EMPTY
                # A consumer blocked in a server-side GET is alive by
                # definition — beat its lease while it waits.
                self._lease_beat(consumer_id, None)
        return _POP_CLOSED

    # -- frame building / serving -------------------------------------------

    def _epoch_of(self, queue_idx: int) -> int:
        return plan_ir.queue_epoch(queue_idx, self._num_trainers)

    def _owns_queue(self, queue_idx: int) -> bool:
        rank = plan_ir.queue_rank(queue_idx, self._num_trainers)
        if rank in self._moved:
            return False
        if rank in self._extra_ranks:
            return True
        return (self._num_shards <= 1
                or plan_ir.queue_shard(queue_idx, self._num_trainers,
                                       self._num_shards)
                == self._shard_index)

    # -- tenancy attribution ------------------------------------------------

    def _tenant_of_queue(self, queue_idx: int) -> str:
        """The tenant a queue's bytes belong to: the config table's
        rank mapping (or an OP_TENANT binding recorded against the
        rank), else the default tenant — attribution never fails, it
        degrades to the single-tenant account."""
        rank = plan_ir.queue_rank(queue_idx, self._num_trainers)
        with self._tenant_lock:
            return self._rank_tenant.get(rank,
                                         rt_tenancy.DEFAULT_TENANT_ID)

    def _tenant_counters(self, tenant_id: str) -> tuple:
        """(delivered-bytes counter, replay gauge, budget gauge) for one
        tenant, cached — label cardinality is bounded by the tenant
        table plus wire-bound tenants."""
        with self._tenant_lock:
            counters = self._tenant_metrics.get(tenant_id)
            if counters is None:
                counters = self._tenant_metrics[tenant_id] = (
                    rt_metrics.counter(
                        "rsdl_tenant_bytes_delivered_total",
                        "payload bytes delivered per tenant",
                        tenant=tenant_id),
                    rt_metrics.gauge(
                        "rsdl_tenant_replay_bytes",
                        "unacked (in-flight) bytes held per tenant",
                        tenant=tenant_id),
                    rt_metrics.gauge(
                        "rsdl_tenant_budget_bytes",
                        "weighted-fair share of the replay budget",
                        tenant=tenant_id),
                )
            return counters

    def _charge_tenant(self, queue_idx: int, delta: int,
                       tenant_id: Optional[str] = None) -> str:
        """Mirror every replay-byte mutation into the owning tenant's
        ledger (the quantity the fair scheduler partitions). Positive
        deltas also charge the DRR deficit — delivered bytes are what
        the round-robin meters.

        Returns the tenant charged. Pop-time callers pin it on the
        frame; release paths pass that pinned tenant back, so the
        credit lands on the account that was debited even when the
        rank's tenant binding changed in between (an OP_TENANT landing
        after GETs already charged the default tenant would otherwise
        drive the new tenant's ledger permanently negative while the
        old one stays inflated)."""
        if tenant_id is None:
            tenant_id = self._tenant_of_queue(queue_idx)
        with self._tenant_lock:
            self._tenant_replay[tenant_id] = \
                self._tenant_replay.get(tenant_id, 0) + delta
            replay = self._tenant_replay[tenant_id]
        self._tenant_counters(tenant_id)[1].set(replay)
        if delta > 0 and self._fair is not None:
            self._fair.charge(tenant_id, delta)
        return tenant_id

    def _tenant_may_pop(self, tenant_id: str) -> bool:
        """The weighted-fair gate in the GET pop loop (frames past the
        first only): a tenant may keep popping while its unacked bytes
        sit under its weighted share of the replay budget AND the
        deficit round robin grants it another frame."""
        fair = self._fair
        if fair is None:
            return True
        budget = fair.budget(tenant_id)
        self._tenant_counters(tenant_id)[2].set(budget)
        with self._tenant_lock:
            replay = self._tenant_replay.get(tenant_id, 0)
        if replay >= budget:
            return False
        return fair.grant(tenant_id)

    def _bind_wire_tenant(self, consumer_id: Optional[int],
                          blob: bytes) -> None:
        """OP_TENANT: bind a consumer's lease (and, as its GETs arrive,
        its ranks) to the announced TenantContext. A malformed blob is
        logged and ignored — tenancy is a policy layer, never a way to
        kill a serving connection."""
        try:
            ctx = rt_tenancy.TenantContext.from_json(blob)
        except (ValueError, KeyError, TypeError,
                UnicodeDecodeError) as e:
            logger.warning("ignoring malformed OP_TENANT blob: %s", e)
            return
        # The whole bind — known-check, table mutation, FairShare
        # creation/weight registration — is one critical section: two
        # concurrent OP_TENANT binds racing here could each observe
        # ``_fair is None`` and build rival schedulers (losing one
        # tenant's weight), or one could iterate ``_tenants`` while the
        # other mutates it. FairShare's own lock is leaf-level, so
        # taking it (set_weight) under _tenant_lock cannot invert.
        with self._tenant_lock:
            known = ctx.tenant_id in self._tenants
            if not known:
                self._tenants[ctx.tenant_id] = \
                    {"weight": ctx.effective_weight}
            if self._fair is None:
                self._fair = rt_fairshare.FairShare(
                    {t: spec["weight"]
                     for t, spec in self._tenants.items()},
                    int(self._replay_budget),
                    quantum_bytes=int(rt_policy.resolve(
                        "queue", "tenant_drr_quantum_bytes")),
                    active_window_s=float(rt_policy.resolve(
                        "queue", "tenant_active_window_s")))
            elif not known:
                # The server-side config table wins over a
                # wire-announced weight for tenants it already names.
                self._fair.set_weight(ctx.tenant_id,
                                      ctx.effective_weight)
        with self._lease_lock:
            if consumer_id is not None:
                lease = self._leases.get(consumer_id)
                if lease is not None:
                    lease.tenant = ctx.tenant_id
        logger.info("consumer %s bound to tenant %r (weight %.1f)",
                    f"{consumer_id:x}" if consumer_id is not None
                    else "?", ctx.tenant_id, ctx.effective_weight)

    def _ensure_handle_dir(self) -> Optional[str]:
        """The segment dir for handle frames (created on first use under
        the procpool shm root, or the path the supervised config pinned
        so restarts reuse it)."""
        if self._handle_dir is None:
            self._handle_dir = tempfile.mkdtemp(
                prefix=f"rsdl-qhandles-s{self._shard_index}-",
                dir=pp.shm_base_dir())
            self._own_handle_dir = True
        else:
            os.makedirs(self._handle_dir, exist_ok=True)
        return self._handle_dir

    def _release_frame(self, frame: _Frame) -> None:
        """Drop an unacked frame's resident bytes: unpin (and unlink)
        the shm segment for handle frames — consumers that already
        mmap'd it keep their mapping."""
        pp.release_segment(frame.ledger_id, frame.handle_path,
                           unlink=True)
        frame.ledger_id = None

    def _make_frame(self, queue_idx: int, seq: int, kind: int, data,
                    nrows: int, task: int, row_offset: int,
                    want_handle: bool,
                    restored_birth=None) -> _Frame:
        """Build one frame, serializing the table exactly once. Handle
        delivery publishes the serialized buffer as a shm segment and
        puts only the ~100-byte handle blob on the wire; streamed
        delivery keeps the pa.Buffer AS the wire payload (the same
        object rides the socket and the replay buffer — satellite fix:
        no fresh ``bytes`` copy), optionally compressed.

        Latency plane: ``restored_birth`` (the journal's stamp for this
        seq, when this server is a restarted incarnation regenerating
        it) wins over the table's own ``rsdl.birth`` metadata — the
        regenerated table carries a recompute-fresh stamp, and using it
        would launder the crash out of the latency record. A NEWLY
        assigned seq's birth is journaled here (flush, no fsync), and
        the ``birth_to_queued`` hop is observed server-side."""
        epoch = self._epoch_of(queue_idx)
        queued = rt_lat.now_stamp()
        if kind != KIND_TABLE:
            return _Frame(seq, kind, epoch, data, _crc(data), row_offset,
                          nrows, task, queued=queued)
        birth = restored_birth
        if birth is None:
            meta = data.schema.metadata
            birth = rt_lat.parse_stamp(
                meta.get(rt_lat.BIRTH_META_KEY) if meta else None)
            if birth is not None and self._journal is not None:
                self._journal.record_birth(queue_idx, seq, *birth)
        if birth is not None:
            rt_lat.observe_hop(
                rt_lat.HOP_BIRTH_TO_QUEUED,
                str(plan_ir.queue_rank(queue_idx, self._num_trainers)),
                self._anchors.latency_s(birth, now_mono=queued.t_mono,
                                        now_unix=queued.t_unix))
        buf = _serialize(data)
        logical = buf.size
        data_crc = _crc(buf)
        if want_handle and self._delivery != "stream":
            path = os.path.join(
                self._ensure_handle_dir(),
                f"h{os.getpid()}_{next(self._handle_names)}.arrow")
            pp.write_buffer_segment(buf, path)
            ledger_id = pp.pin_segment(logical)
            blob = json.dumps({"path": path, "offset": 0,
                               "size": logical,
                               "crc": data_crc}).encode()
            self._handle_hits.inc()
            return _Frame(seq, KIND_TABLE_HANDLE, epoch, blob, _crc(blob),
                          row_offset, nrows, task,
                          payload_bytes=logical, data_crc=data_crc,
                          handle_path=path, ledger_id=ledger_id,
                          birth=birth, queued=queued)
        self._handle_misses.inc()
        wire: object = buf
        codec = CODEC_NONE
        pending = None
        if self._compression and logical >= self._compression_min:
            codec_id, compress = self._compression
            if self._codec_pool is not None:
                # Deferred: the pool compresses while the serving thread
                # pops/serializes the next frame; _collect_frames
                # resolves every pending codec before the batch leaves
                # its queue lock. The CRC was taken pre-compression, so
                # the deferral cannot change what the consumer verifies.
                pending = (self._codec_pool.submit(compress, buf),
                           codec_id)
            else:
                compressed = compress(buf)
                if len(compressed) < logical:
                    wire, codec = compressed, codec_id
                    self._compression_saved.inc(logical - len(compressed))
        frame = _Frame(seq, KIND_TABLE, epoch, wire, data_crc, row_offset,
                      nrows, task, codec=codec, payload_bytes=logical,
                      data_crc=data_crc, birth=birth, queued=queued)
        frame.pending_codec = pending
        return frame

    def _downgrade_frame(self, frame: _Frame) -> _Frame:
        """Replay a handle frame as a byte stream (NACK_NO_HANDLE): mmap
        the segment the server itself wrote and make its buffer the wire
        payload. Seq/row accounting and the segment pin carry over, so
        ack release and exactly-once hold unchanged; the CRC is the
        stored segment CRC — the bytes are identical by construction."""
        buf = pp.read_segment_buffer(frame.handle_path)
        downgraded = _Frame(frame.seq, KIND_TABLE, frame.epoch, buf,
                            frame.data_crc, frame.row_offset,
                            frame.nrows, frame.task,
                            payload_bytes=frame.payload_bytes,
                            data_crc=frame.data_crc,
                            handle_path=frame.handle_path,
                            ledger_id=frame.ledger_id,
                            birth=frame.birth, queued=frame.queued)
        downgraded.tenant = frame.tenant
        return downgraded

    def _note_shard_depth(self) -> None:
        if rt_telemetry.stamp():
            with self._states_lock:
                queues = list(self._states)
            self._shard_depth.set(sum(self._queue.sizes(queues)))

    def _apply_ack(self, queue_idx: int, state: _QueueState,
                   ack: int) -> None:
        state.acked_seq = ack
        done = state.done
        while state.replay and state.replay[0].seq <= ack:
            frame = state.replay.popleft()
            state.replay_bytes -= frame.size
            self._charge_tenant(queue_idx, -frame.size, frame.tenant)
            self._release_frame(frame)
            state.acked_rows = frame.row_offset + frame.nrows
            if frame.kind == KIND_SENTINEL:
                done = True
        state.done = done
        if self._journal is not None:
            self._journal.record(queue_idx, ack, state.acked_rows,
                                 done=done)

    def _collect_frames(self, queue_idx: int, max_items: int,
                        ack: Optional[int], resume: bool,
                        consumer_id,
                        handles_ok: bool = False) -> Optional[List[_Frame]]:
        """Assemble one response: unacked replay suffix first, then new
        pops. Returns None when the server closed under the blocking get.
        ``handles_ok`` is the CONNECTION's capability (the consumer's
        HELLO offered shm-handle delivery); a queue NACK'd with
        NACK_NO_HANDLE stays stream-only regardless.
        """
        # Fault site: a crash HERE models the whole server process dying
        # mid-epoch (the supervisor's recovery unit). In dedicated-server
        # mode it is a real process exit; in-process it downs the server.
        try:
            rt_faults.inject("queue_server_crash",
                             epoch=self._epoch_of(queue_idx),
                             task=queue_idx)
        except rt_faults.InjectedFault:
            if self._exit_on_crash_site:
                os._exit(137)
            self.close()
            raise
        tenant_id = self._tenant_of_queue(queue_idx)
        if self._fair is not None:
            # Every GET marks its tenant active: the fair scheduler's
            # work-conserving partition is over tenants currently asking.
            self._fair.touch(tenant_id)
            if not sum(self._queue.sizes([queue_idx])):
                # Nothing queued for this tenant right now (a live
                # stream between frames): drop its claim so unspent
                # credit cannot gate tenants that DO have work — work
                # conservation without waiting out the activity window.
                # It rejoins with a fresh quantum on its next GET.
                self._fair.idle(tenant_id)
            elif self._floor_pace_s > 0 and not self._tenant_may_pop(
                    tenant_id):
                # Pace the liveness floor: a tenant the scheduler is
                # currently denying still gets its one frame per GET
                # (liveness — acks must always be able to progress),
                # but not at raw round-trip rate. On a fast loopback an
                # unpaced floor alone out-delivers the DRR grants and
                # the configured weights stop shaping anything.
                # ``_tenant_may_pop`` consumes no credit (only
                # ``charge`` does), so this probe never alters the
                # round-robin accounting.
                time.sleep(self._floor_pace_s)
        state = self._state(queue_idx)
        rank = plan_ir.queue_rank(queue_idx, self._num_trainers)
        sealed = rank in self._sealed_ranks
        with state.lock:
            want_handle = handles_ok and not state.no_handles
            if ack is not None and ack > state.acked_seq:
                self._apply_ack(queue_idx, state, ack)
            if resume:
                # Reconnect: rewind the send cursor to the watermark so
                # the unacked suffix replays — the frames a reset ate.
                state.sent_seq = state.acked_seq
            if not want_handle and any(
                    f.kind == KIND_TABLE_HANDLE and f.seq > state.sent_seq
                    for f in state.replay):
                # The consumer (or a NACK_NO_HANDLE) withdrew handle
                # capability: downgrade the unsent handle frames to byte
                # streams in place — same seqs, same bytes, same CRCs.
                state.replay = collections.deque(
                    self._downgrade_frame(f)
                    if f.kind == KIND_TABLE_HANDLE
                    and f.seq > state.sent_seq else f
                    for f in state.replay)
            frames: List[_Frame] = [f for f in state.replay
                                    if f.seq > state.sent_seq][:max_items]
            if frames:
                self._replayed.inc(len(frames))
                rt_telemetry.record("frame_replay", epoch=frames[0].epoch,
                                    task=queue_idx, count=len(frames))
            try:
                # A PREPARE-sealed rank serves ONLY its replay suffix —
                # the handoff manifest snapshotted everything past the
                # watermark, so popping anything new here would fork the
                # stream the target is about to adopt.
                while (not sealed and len(frames) < max_items
                       and (not frames
                            or frames[-1].kind in (KIND_TABLE,
                                                   KIND_TABLE_HANDLE))):
                    if frames and state.replay_bytes > self._replay_budget:
                        # Backpressure: unacked bytes are at budget — stop
                        # popping (never below one frame per GET, so the
                        # consumer's acks always make progress possible).
                        break
                    if frames and not self._tenant_may_pop(tenant_id):
                        # Weighted-fair backpressure (tenancy/fairshare):
                        # this tenant's unacked bytes reached its share
                        # of the budget, or the deficit round robin owes
                        # the next frames to a competing tenant. Same
                        # one-frame-per-GET floor as the global check.
                        break
                    item = self._pop(queue_idx, blocking=not frames,
                                     consumer_id=consumer_id)
                    if item is _POP_CLOSED:
                        return None if not frames else frames
                    if item is _POP_EMPTY:
                        break
                    kind, data, nrows, task = _materialize(item)
                    seq = state.next_seq
                    state.next_seq += 1
                    row_offset = state.rows_total
                    state.rows_total += nrows
                    if seq <= state.acked_seq:
                        # Regenerated-after-restart item the consumer
                        # already consumed (its ack outran the journal's
                        # last fsync): drop it, but keep the row
                        # accounting advancing.
                        state.acked_rows = row_offset + nrows
                        state.births.pop(seq, None)
                        continue
                    frame = self._make_frame(queue_idx, seq, kind, data,
                                             nrows, task, row_offset,
                                             want_handle,
                                             restored_birth=state.births.pop(
                                                 seq, None))
                    state.replay.append(frame)
                    state.replay_bytes += frame.size
                    frame.tenant = self._charge_tenant(queue_idx,
                                                       frame.size)
                    frames.append(frame)
            finally:
                # Land every deferred codec-pool compression before the
                # batch leaves the queue lock (runs on EVERY exit, the
                # mid-loop server-closed return included): the replay
                # buffer and the wire must serve the same bytes.
                for f in frames:
                    if f.pending_codec is not None:
                        delta = f.resolve_codec()
                        state.replay_bytes += delta
                        if delta:
                            self._charge_tenant(queue_idx, delta,
                                                f.tenant)
                        if delta < 0:
                            self._compression_saved.inc(-delta)
            if frames:
                state.sent_seq = frames[-1].seq
        if sealed and not frames:
            # Pace a consumer polling a sealed-and-drained queue: an
            # empty batch is a valid response (the client just refetches)
            # but an unpaced loop would spin the loopback until the
            # MOVED redirect or an UNSEAL lands.
            time.sleep(0.05)
        self._note_shard_depth()
        return frames

    def _send_frames(self, conn: socket.socket, queue_idx: int,
                     frames: List[_Frame]) -> None:
        """Write one GET response. With ``RSDL_QUEUE_SENDMSG`` (default
        on) the batch header plus every frame header and payload gather
        into a single scatter-gather ``sendmsg`` call — one syscall per
        response instead of the legacy ``1 + 2N`` ``sendall``s — with
        byte-for-byte identical wire content, chaos sites included: a
        torn header flushes exactly the bytes the sequential path would
        have pushed before the injected reset."""
        gather = self._sendmsg and hasattr(conn, "sendmsg")
        gen = self._rank_gen.get(
            plan_ir.queue_rank(queue_idx, self._num_trainers), 0)
        vecs: List = [_BATCH_HEADER.pack(len(frames))]
        if not gather:
            conn.sendall(vecs[0])
            vecs.clear()
        for frame in frames:
            size = frame.wire_len
            kind_byte = frame.kind | (frame.codec << 4)
            header = _FRAME.pack(kind_byte, frame.epoch, frame.seq,
                                 frame.crc, frame.row_offset, size,
                                 frame.task,
                                 *_pack_stamp(frame.birth),
                                 *_pack_stamp(frame.queued), gen)
            try:
                rt_faults.inject("conn_reset_midframe", epoch=frame.epoch,
                                 task=queue_idx)
            except rt_faults.InjectedFault as e:
                # A torn frame then a hard close: the consumer observes
                # bytes stopping mid-frame — the exact reset-mid-response
                # shape v2 recovery exists for.
                if gather:
                    vecs.append(header[:_FRAME.size // 2])
                    _sendmsg_all(conn, vecs)
                else:
                    # Sequential fallback's torn-frame chaos write — one
                    # deliberate half-header, nothing to gather.
                    # rsdl-lint: disable=sendall-in-loop
                    conn.sendall(header[:_FRAME.size // 2])
                raise ConnectionError(
                    f"injected connection reset mid-frame: {e}") from e
            corrupt = False
            if size:
                # Only payload frames are corruptible: firing the site
                # on a zero-length sentinel would record an "injected"
                # event with nothing on the wire to corrupt — the
                # consumer sees a clean CRC and the chaos<->telemetry
                # join (fault_events_joinable) loses the event.
                try:
                    rt_faults.inject("frame_corrupt", epoch=frame.epoch,
                                     task=queue_idx)
                except rt_faults.InjectedFault:
                    corrupt = True
            payload = None
            if size:
                if corrupt:
                    # Flip one payload byte ON THE WIRE only — the replay
                    # buffer keeps the good copy the NACK re-send needs.
                    damaged = bytearray(memoryview(frame.wire))
                    damaged[-1] ^= 0xFF
                    payload = damaged
                else:
                    # pa.Buffer / memoryview go straight to the socket —
                    # the serialized table is never flattened into a
                    # fresh bytes object on this path.
                    payload = frame.wire
            if gather:
                vecs.append(header)
                if payload is not None:
                    vecs.append(payload)
            else:
                # The RSDL_QUEUE_SENDMSG=0 sequential arm: kept as the
                # byte-for-byte reference the gather path is tested
                # against, so these two writes stay per-frame by design.
                # rsdl-lint: disable=sendall-in-loop
                conn.sendall(header)
                if payload is not None:
                    # rsdl-lint: disable=sendall-in-loop
                    conn.sendall(payload)
            if frame.kind in (KIND_TABLE, KIND_TABLE_HANDLE):
                self._wire_bytes.inc(size)
                self._payload_bytes.inc(frame.payload_bytes)
                self._tenant_counters(self._tenant_of_queue(queue_idx))[
                    0].inc(frame.payload_bytes)
        if gather:
            _sendmsg_all(conn, vecs)

    def _fail_frame(self, text: bytes) -> bytes:
        """A one-frame failure response (v2 shape: count + header +
        payload). Failure frames stamp placement generation 0 — they
        are exempt from the consumer's fence so an error always lands,
        even from a zombie."""
        return (_BATCH_HEADER.pack(1)
                + _FRAME.pack(KIND_FAILURE, 0, ACK_NONE, _crc(text), 0,
                              len(text), TASK_NONE, 0.0, 0.0, 0,
                              0.0, 0.0, 0, 0) + text)

    def _moved_frame(self, queue_idx: int, rank: int) -> bytes:
        """A one-frame ``KIND_MOVED`` redirect: the JSON payload carries
        the adopting shard's address and the committed placement
        generation; the header's generation field repeats it so the
        consumer raises its fence floor before it ever dials the new
        address."""
        generation, (host, port) = self._moved[rank]
        blob = json.dumps({"host": host, "port": port,
                           "generation": generation, "rank": rank},
                          sort_keys=True).encode()
        return (_BATCH_HEADER.pack(1)
                + _FRAME.pack(KIND_MOVED, 0, ACK_NONE, _crc(blob), 0,
                              len(blob), TASK_NONE, 0.0, 0.0, 0,
                              0.0, 0.0, 0, generation) + blob)

    def _serve_conn(self, conn: socket.socket) -> None:
        consumer_id: Optional[int] = None
        handles_ok = False
        try:
            while not self._closed.is_set():
                try:
                    raw = conn.recv(_REQUEST.size)
                except socket.timeout:
                    continue  # idle tick; leases expire separately
                if not raw:
                    return  # consumer done
                if len(raw) < _REQUEST.size:
                    raw += _recv_exact(conn, _REQUEST.size - len(raw))
                op, flags, a, b, c = _REQUEST.unpack(raw)
                if op == OP_HELLO:
                    consumer_id = a | (b << 32)
                    handles_ok = bool(flags & FLAG_HANDLES_OK)
                    self._lease_beat(consumer_id, None)
                    continue
                if op == OP_HEARTBEAT:
                    self._lease_beat(consumer_id, None)
                    continue
                if op == OP_TENANT:
                    blob = _recv_exact(conn, c) if c else b""
                    self._lease_beat(consumer_id, None)
                    self._bind_wire_tenant(consumer_id, blob)
                    continue
                if op == OP_NACK:
                    self._handle_nack(a, b, c)
                    self._lease_beat(consumer_id, a)
                    continue
                if op == OP_REBALANCE:
                    blob = _recv_exact(conn, c) if c else b""
                    payload = self._rebalance_admin(flags, a, b, blob)
                    conn.sendall(_BATCH_HEADER.pack(len(payload)) + payload)
                    continue
                if op != OP_GET_BATCH:
                    raise ConnectionError(f"unknown request op {op}")
                queue_idx, max_items = a, b
                moved_rank = plan_ir.queue_rank(queue_idx,
                                                self._num_trainers)
                if moved_rank in self._moved:
                    # This rank migrated away under a committed placement
                    # decision: answer with a redirect (new address +
                    # generation), never a foreign-rank stream.
                    conn.sendall(self._moved_frame(queue_idx, moved_rank))
                    continue
                if not self._owns_queue(queue_idx):
                    # Routing bug (a consumer dialing the wrong shard)
                    # must fail loudly, not serve a foreign rank's
                    # stream with divergent seq state.
                    conn.sendall(self._fail_frame(
                        f"queue {queue_idx} is not served by shard "
                        f"{self._shard_index}/{self._num_shards} "
                        f"(plan query queue_shard)".encode()))
                    continue
                ack = None if c == ACK_NONE else c
                self._lease_beat(consumer_id, queue_idx)
                try:
                    frames = self._collect_frames(
                        queue_idx, max(1, max_items), ack,
                        bool(flags & FLAG_RESUME), consumer_id,
                        handles_ok=handles_ok)
                except mq.ShutdownError as e:
                    # Queue shut down under a blocked GET: fail loudly
                    # (the reference's actor kill surfaced as
                    # RayActorError on the consumer).
                    conn.sendall(self._fail_frame(repr(e).encode()))
                    return
                if frames is None:
                    return  # server closing: drain quietly
                self._send_frames(conn, queue_idx, frames)
        except (ConnectionError, OSError) as e:
            if not self._closed.is_set():
                logger.warning("queue server connection dropped: %s", e)
        finally:
            try:
                conn.close()
            except OSError:
                pass
            with self._conn_lock:
                self._conn_threads.discard(threading.current_thread())

    def _handle_nack(self, queue_idx: int, bad_seq: int,
                     mode: int = NACK_CRC) -> None:
        state = self._state(queue_idx)
        with state.lock:
            state.sent_seq = min(state.sent_seq, bad_seq - 1)
            if mode == NACK_NO_HANDLE:
                # The consumer cannot map this queue's segments (handle
                # capability was mis-detected, or the segment vanished):
                # stream-only from here on; the rewound replay suffix is
                # downgraded frame-by-frame at the next GET.
                state.no_handles = True
        self._nacked.inc()
        if mode == NACK_NO_HANDLE:
            rt_telemetry.record("handle_downgrade",
                                epoch=self._epoch_of(queue_idx),
                                task=queue_idx, seq=bad_seq)
            logger.warning(
                "queue %d: consumer cannot use shm handle for frame %d; "
                "downgrading the queue to streamed delivery", queue_idx,
                bad_seq)
            return
        rt_telemetry.record("frame_nack", epoch=self._epoch_of(queue_idx),
                            task=queue_idx, seq=bad_seq)
        logger.warning("queue %d: consumer NACK'd frame %d (CRC mismatch); "
                       "re-sending from replay", queue_idx, bad_seq)

    # -- live queue migration (rebalance/) ----------------------------------

    def _rank_queues(self, rank: int) -> List[int]:
        """Every queue index of ``rank`` that has server-side state
        (``queue_id = epoch * num_trainers + rank``)."""
        with self._states_lock:
            return sorted(q for q in self._states
                          if plan_ir.queue_rank(q, self._num_trainers)
                          == rank)

    def _crash_site(self, site: str, generation: int, rank: int) -> None:
        """One injected chaos site = the whole server process dying at
        this exact migration phase (same recovery unit as
        ``queue_server_crash``)."""
        try:
            rt_faults.inject(site, epoch=generation, task=rank)
        except rt_faults.InjectedFault:
            if self._exit_on_crash_site:
                os._exit(137)
            self.close()
            raise

    def _rebalance_admin(self, phase: int, rank: int, generation: int,
                         payload: bytes) -> bytes:
        """Dispatch one OP_REBALANCE phase. Every response is a
        ``checkpoint.crc_line`` JSON payload; errors come back as
        ``{"error": ...}`` lines so the driver can abort cleanly instead
        of eating a connection reset."""
        from ray_shuffling_data_loader_tpu import checkpoint as ckpt
        try:
            if phase == REB_PREPARE:
                self._crash_site("rebalance_prepare", generation, rank)
                line = self._export_rank(rank, generation)
                rt_telemetry.record("rebalance_prepare", epoch=generation,
                                    task=rank, shard=self._shard_index)
                return line
            if phase == REB_ADOPT:
                self._crash_site("rebalance_commit", generation, rank)
                # Verify the manifest line's CRC HERE, on the adopting
                # shard: the driver ships the source's crc_line verbatim,
                # so corruption anywhere on the path is caught before a
                # single byte of state is installed.
                manifest = ckpt.parse_crc_line(
                    payload.decode("utf-8"))["manifest"]
                self._import_rank(manifest)
                rt_telemetry.record("rebalance_commit", epoch=generation,
                                    task=rank, shard=self._shard_index)
                return ckpt.crc_line({"adopted": rank,
                                      "generation": generation}).encode()
            if phase == REB_RELEASE:
                target = json.loads(payload.decode("utf-8"))
                self._release_rank(rank, generation,
                                   (str(target["host"]),
                                    int(target["port"])))
                rt_telemetry.record("rebalance_release", epoch=generation,
                                    task=rank, shard=self._shard_index)
                return ckpt.crc_line({"released": rank,
                                      "generation": generation}).encode()
            if phase == REB_UNSEAL:
                self._sealed_ranks.discard(rank)
                rt_telemetry.record("rebalance_unseal", epoch=generation,
                                    task=rank, shard=self._shard_index)
                return ckpt.crc_line({"unsealed": rank}).encode()
            return ckpt.crc_line(
                {"error": f"unknown rebalance phase {phase}"}).encode()
        except rt_faults.InjectedFault:
            raise
        except Exception as e:  # noqa: BLE001 - reported to the driver
            logger.warning("rebalance phase %d for rank %d failed: %s",
                           phase, rank, e)
            return ckpt.crc_line({"error": repr(e)}).encode()

    def _export_rank(self, rank: int, generation: int) -> bytes:
        """PREPARE: seal ``rank`` at its watermark and export everything
        a target shard needs to continue its streams exactly-once — per
        queue the sequence cursor, row accounting, journal birth stamps,
        and the full unacked replay suffix as base64 byte frames (handle
        frames are downgraded first: a foreign shard cannot mmap this
        host's shm segments). The whole manifest rides one
        ``checkpoint.crc_line`` so it is tamper-evident end to end."""
        from ray_shuffling_data_loader_tpu import checkpoint as ckpt
        self._sealed_ranks.add(rank)
        queues: Dict[str, dict] = {}
        for q in self._rank_queues(rank):
            state = self._state(q)
            with state.lock:
                frames = []
                for frame in state.replay:
                    if frame.pending_codec is not None:
                        state.replay_bytes += frame.resolve_codec()
                    if frame.kind == KIND_TABLE_HANDLE:
                        frame = self._downgrade_frame(frame)
                    frames.append({
                        "seq": frame.seq, "kind": frame.kind,
                        "epoch": frame.epoch, "crc": frame.crc,
                        "data_crc": frame.data_crc,
                        "row_offset": frame.row_offset,
                        "nrows": frame.nrows, "task": frame.task,
                        "codec": frame.codec,
                        "payload_bytes": frame.payload_bytes,
                        "wire": base64.b64encode(
                            bytes(memoryview(frame.wire))).decode("ascii"),
                        "birth": list(frame.birth) if frame.birth else None,
                        "queued": (list(frame.queued)
                                   if frame.queued else None),
                    })
                queues[str(q)] = {
                    "next_seq": state.next_seq,
                    "acked_seq": state.acked_seq,
                    "acked_rows": state.acked_rows,
                    "rows_total": state.rows_total,
                    "done": state.done,
                    "births": {str(seq): list(stamp)
                               for seq, stamp in state.births.items()},
                    "frames": frames,
                }
        manifest = {"rank": rank, "generation": generation,
                    "num_trainers": self._num_trainers,
                    "source_shard": self._shard_index,
                    "queues": queues}
        return ckpt.crc_line({"manifest": manifest}).encode()

    def _import_rank(self, manifest: dict) -> None:
        """COMMIT: install an exported rank's queue states (idempotent —
        re-adopting the same generation is a no-op) and merge its
        watermarks into this shard's journal, so even a restart of the
        TARGET after adoption regenerates exactly the undelivered
        remainder through the normal resume machinery."""
        rank = int(manifest["rank"])
        generation = int(manifest["generation"])
        if int(manifest["num_trainers"]) != self._num_trainers:
            raise ValueError(
                f"manifest num_trainers {manifest['num_trainers']} != "
                f"server num_trainers {self._num_trainers}")
        if self._rank_gen.get(rank, 0) >= generation > 0:
            logger.warning("rank %d already adopted at generation >= %d; "
                           "treating re-adopt as a no-op", rank, generation)
            return
        for q_str, entry in manifest["queues"].items():
            q = int(q_str)
            births = {
                int(seq): rt_lat.Stamp(int(pid), float(tm), float(tu))
                for seq, (pid, tm, tu) in entry["births"].items()}
            state = _QueueState(next_seq=int(entry["next_seq"]),
                                done=bool(entry["done"]), births=births)
            state.acked_seq = int(entry["acked_seq"])
            state.sent_seq = state.acked_seq
            state.acked_rows = int(entry["acked_rows"])
            state.rows_total = int(entry["rows_total"])
            for f in entry["frames"]:
                birth = (rt_lat.Stamp(int(f["birth"][0]),
                                      float(f["birth"][1]),
                                      float(f["birth"][2]))
                         if f["birth"] else None)
                queued = (rt_lat.Stamp(int(f["queued"][0]),
                                       float(f["queued"][1]),
                                       float(f["queued"][2]))
                          if f["queued"] else None)
                frame = _Frame(int(f["seq"]), int(f["kind"]),
                               int(f["epoch"]),
                               base64.b64decode(f["wire"]),
                               int(f["crc"]), int(f["row_offset"]),
                               int(f["nrows"]), int(f["task"]),
                               codec=int(f["codec"]),
                               payload_bytes=int(f["payload_bytes"]),
                               data_crc=int(f["data_crc"]),
                               birth=birth, queued=queued)
                state.replay.append(frame)
                state.replay_bytes += frame.size
                frame.tenant = self._charge_tenant(q, frame.size)
            with self._states_lock:
                self._states[q] = state
            if self._journal is not None:
                for seq, stamp in births.items():
                    self._journal.record_birth(q, seq, stamp.pid,
                                               stamp.t_mono, stamp.t_unix)
                for frame in state.replay:
                    if frame.birth is not None:
                        self._journal.record_birth(
                            q, frame.seq, frame.birth.pid,
                            frame.birth.t_mono, frame.birth.t_unix)
                if state.acked_seq >= 0:
                    self._journal.record(q, state.acked_seq,
                                         state.acked_rows,
                                         done=state.done)
        self._rank_gen[rank] = generation
        self._extra_ranks.add(rank)
        self._moved.pop(rank, None)
        self._sealed_ranks.discard(rank)
        logger.warning("shard %d adopted rank %d at placement generation "
                       "%d (%d queue(s))", self._shard_index, rank,
                       generation, len(manifest["queues"]))

    def _release_rank(self, rank: int, generation: int,
                      target: Tuple[str, int]) -> None:
        """Post-COMMIT: drop the source's copy of a migrated rank and
        start answering its GETs with ``KIND_MOVED`` redirects. The
        shared ``MultiQueue`` is deliberately NOT drained — in the
        in-process topology the adopting server pops the same queue
        objects, so undelivered items flow to the target untouched."""
        for q in self._rank_queues(rank):
            state = self._state(q)
            with state.lock:
                while state.replay:
                    frame = state.replay.popleft()
                    state.replay_bytes -= frame.size
                    self._charge_tenant(q, -frame.size, frame.tenant)
                    self._release_frame(frame)
            with self._states_lock:
                self._states.pop(q, None)
        self._sealed_ranks.discard(rank)
        self._extra_ranks.discard(rank)
        self._moved[rank] = (generation,
                             (str(target[0]), int(target[1])))
        logger.warning("shard %d released rank %d to %s:%d at placement "
                       "generation %d", self._shard_index, rank,
                       target[0], target[1], generation)

    # -- consumer leases ----------------------------------------------------

    def _lease_beat(self, consumer_id: Optional[int],
                    queue_idx: Optional[int]) -> None:
        if consumer_id is None:
            return
        with self._lease_lock:
            lease = self._leases.get(consumer_id)
            if lease is None:
                lease = self._leases[consumer_id] = _Lease(consumer_id)
                logger.info("consumer %x: lease granted", consumer_id)
            lease.last_beat = time.monotonic()
            lease.expired = False
            if queue_idx is not None:
                lease.queues.add(queue_idx)
                if lease.tenant is not None:
                    # A wire-bound tenant claims the ranks it GETs, so
                    # attribution works without a server-side table.
                    rank = plan_ir.queue_rank(queue_idx,
                                              self._num_trainers)
                    with self._tenant_lock:
                        self._rank_tenant.setdefault(rank, lease.tenant)
            self._consumers_alive.set(
                sum(1 for le in self._leases.values() if not le.expired))
            if (self._lease_thread is None
                    or not self._lease_thread.is_alive()):
                self._lease_thread = threading.Thread(
                    target=self._lease_sweeper, daemon=True,
                    name="rsdl-qserve-lease")
                self._lease_thread.start()

    def _lease_sweeper(self) -> None:
        interval = max(0.05, self._lease_timeout_s / 4.0)
        while not self._closed.wait(interval):
            now = time.monotonic()
            newly_dead: List[_Lease] = []
            with self._lease_lock:
                for lease in self._leases.values():
                    if (not lease.expired
                            and now - lease.last_beat
                            > self._lease_timeout_s):
                        lease.expired = True
                        newly_dead.append(lease)
                alive = sum(1 for le in self._leases.values()
                            if not le.expired)
                self._consumers_alive.set(alive)
            for lease in newly_dead:
                self._on_lease_expired(lease)

    def _on_lease_expired(self, lease: _Lease) -> None:
        self._lease_expiries.inc()
        rt_telemetry.record("lease_expired", consumer=lease.consumer_id,
                            queues=sorted(lease.queues),
                            policy=self._on_dead_consumer)
        logger.error(
            "consumer %x: lease expired after %.1fs without a heartbeat "
            "(queues %s); policy=%s", lease.consumer_id,
            self._lease_timeout_s, sorted(lease.queues),
            self._on_dead_consumer)
        if self._on_dead_consumer == "fail_fast":
            # The strictest contract: a dead trainer downs the pipeline
            # loudly rather than silently shuffling for nobody.
            self.close()
            return
        ranks = {plan_ir.queue_rank(q, self._num_trainers)
                 for q in lease.queues}
        with self._lease_lock:
            ranks -= self._drained_ranks
            self._drained_ranks |= ranks
        if not ranks:
            return
        redistribute = self._on_dead_consumer == "redistribute"
        threading.Thread(
            target=self._drain_dead_ranks, args=(ranks, redistribute),
            daemon=True, name="rsdl-qserve-lease-drain").start()

    def notify_member_down(self, rank: int) -> None:
        """View-aware lease sweep (membership/): a ``member_down``
        verdict force-expires every lease holding queues that route to
        the dead rank — the failure detector's seconds-scale verdict
        beats the lease timeout, so the dead rank's queues drain (or
        redistribute, per ``RSDL_QUEUE_ON_DEAD_CONSUMER``) without
        waiting out the lease clock."""
        rank = int(rank)
        victims: List[_Lease] = []
        with self._lease_lock:
            for lease in self._leases.values():
                if lease.expired:
                    continue
                if any(plan_ir.queue_rank(q, self._num_trainers) == rank
                       for q in lease.queues):
                    lease.expired = True
                    victims.append(lease)
            self._consumers_alive.set(
                sum(1 for le in self._leases.values() if not le.expired))
        rt_telemetry.record("member_lease_sweep", task=rank,
                            leases=[le.consumer_id for le in victims])
        for lease in victims:
            logger.warning(
                "consumer %x: lease force-expired (membership declared "
                "rank %d down)", lease.consumer_id, rank)
            self._on_lease_expired(lease)

    def attach_membership(self, manager) -> None:
        """Subscribe this server to a ``MembershipManager``: each
        ``down`` transition triggers :meth:`notify_member_down` for the
        dead rank."""

        def _listener(event, view) -> None:
            if event.kind == "down":
                self.notify_member_down(event.rank)

        manager.add_listener(_listener)

    def _survivor_rank(self) -> Optional[int]:
        with self._lease_lock:
            ranks = sorted(
                plan_ir.queue_rank(q, self._num_trainers)
                for lease in self._leases.values() if not lease.expired
                for q in lease.queues)
        for rank in ranks:
            if rank not in self._drained_ranks:
                return rank
        return None

    def _drain_dead_ranks(self, ranks: set, redistribute: bool) -> None:
        """Free (or reroute) a dead consumer's queues so producers are
        unblocked and its tables don't leak until process exit."""
        num_queues = self._queue.num_queues
        dead_queues = [
            q for q in range(num_queues)
            if plan_ir.queue_rank(q, self._num_trainers) in ranks]
        for q in dead_queues:
            state = self._state(q)
            with state.lock:
                for frame in state.replay:
                    self._release_frame(frame)
                    # Credit each frame's PINNED tenant (the one charged
                    # at pop time), not whatever the rank maps to now.
                    self._charge_tenant(q, -frame.size, frame.tenant)
                state.replay.clear()
                state.replay_bytes = 0
        while not self._closed.wait(0.2):
            moved = 0
            for q in dead_queues:
                while True:
                    try:
                        item = self._queue.get_nowait(q)
                    except (mq.Empty, mq.ShutdownError, RuntimeError):
                        break
                    moved += 1
                    if not redistribute or item is None or isinstance(
                            item, ShuffleFailure):
                        continue  # drained and dropped
                    survivor = self._survivor_rank()
                    if survivor is None:
                        continue  # nobody left: degrade to drain
                    target = (self._epoch_of(q) * self._num_trainers
                              + survivor)
                    if _put_quiet(self._queue, target, item):
                        rt_telemetry.record(
                            "frame_redistributed", epoch=self._epoch_of(q),
                            task=target, source_queue=q)
            if moved:
                logger.info("dead-consumer policy %s: moved %d items off "
                            "ranks %s",
                            "redistribute" if redistribute else "drain",
                            moved, sorted(ranks))

    # -- lifecycle ----------------------------------------------------------

    def close(self) -> None:
        """Stop accepting, drain in-flight responses, join every handler.

        Handler threads finish the frame they are writing, observe the
        closed flag at the next loop tick (blocking pops tick at 250 ms),
        and exit without logging — so no thread can raise into the logger
        after the listener is gone (the PR-5 shutdown-race fix).
        """
        if self._closed.is_set():
            return
        self._closed.set()
        try:
            self._listener.close()
        except OSError:
            pass
        with self._conn_lock:
            threads = list(self._conn_threads)
        for thread in threads:
            if thread is threading.current_thread():
                continue  # a handler downing its own server cannot join itself
            thread.join(timeout=5.0)
            if thread.is_alive():
                logger.warning(
                    "queue server handler %s did not drain within 5s",
                    thread.name)
        self._accept_thread.join(timeout=2.0)
        # Release the handle-frame segment pins the replay buffers still
        # hold (consumers that mmap'd a segment keep their mapping), and
        # the segment dir if this server created it.
        with self._states_lock:
            states = list(self._states.values())
        for state in states:
            with state.lock:
                for frame in state.replay:
                    self._release_frame(frame)
        if self._own_handle_dir and self._handle_dir:
            shutil.rmtree(self._handle_dir, ignore_errors=True)
        if self._codec_pool is not None:
            self._codec_pool.shutdown(wait=True)

    def __enter__(self) -> "QueueServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def serve_queue(queue: mq.MultiQueue,
                address: Tuple[str, int] = ("127.0.0.1", 0),
                num_trainers: int = 1,
                journal=None,
                initial_state: Optional[Dict[int, object]] = None,
                exit_on_crash_site: bool = False,
                shard_index: int = 0, num_shards: int = 1,
                handle_dir: Optional[str] = None,
                tenants: Optional[dict] = None,
                placement: Optional[dict] = None) -> QueueServer:
    """Start serving ``queue`` on ``address`` (port 0 = ephemeral)."""
    return QueueServer(queue, address, num_trainers=num_trainers,
                       journal=journal, initial_state=initial_state,
                       exit_on_crash_site=exit_on_crash_site,
                       shard_index=shard_index, num_shards=num_shards,
                       handle_dir=handle_dir, tenants=tenants,
                       placement=placement)


def _rebalance_call(address: Tuple[str, int], phase: int, rank: int,
                    generation: int, payload: bytes = b"",
                    timeout_s: float = 30.0) -> str:
    """One OP_REBALANCE round trip on a short-lived admin connection.
    Returns the raw ``checkpoint.crc_line`` response (CRC verified;
    ``{"error": ...}`` entries raise)."""
    from ray_shuffling_data_loader_tpu import checkpoint as ckpt
    with socket.create_connection(tuple(address),
                                  timeout=timeout_s) as sock:
        sock.sendall(_REQUEST.pack(OP_REBALANCE, phase, rank, generation,
                                   len(payload)) + payload)
        (length,) = _BATCH_HEADER.unpack(
            _recv_exact(sock, _BATCH_HEADER.size))
        line = _recv_exact(sock, length).decode("utf-8")
    entry = ckpt.parse_crc_line(line)
    if "error" in entry:
        raise RuntimeError(
            f"rebalance phase {phase} for rank {rank} failed on "
            f"{address[0]}:{address[1]}: {entry['error']}")
    return line


def rebalance_prepare(address: Tuple[str, int], rank: int,
                      generation: int, timeout_s: float = 30.0) -> str:
    """PREPARE on the source shard: seal ``rank`` at its watermark and
    return its CRC'd handoff manifest line — ship this string VERBATIM
    to :func:`rebalance_adopt` so the target re-verifies the same CRC
    the source computed."""
    return _rebalance_call(address, REB_PREPARE, rank, generation,
                           timeout_s=timeout_s)


def rebalance_adopt(address: Tuple[str, int], manifest_line: str,
                    timeout_s: float = 30.0) -> str:
    """COMMIT on the target shard: install the manifest's queue states
    and merge its watermarks into the target's journal."""
    from ray_shuffling_data_loader_tpu import checkpoint as ckpt
    manifest = ckpt.parse_crc_line(manifest_line)["manifest"]
    return _rebalance_call(address, REB_ADOPT, int(manifest["rank"]),
                           int(manifest["generation"]),
                           payload=manifest_line.encode("utf-8"),
                           timeout_s=timeout_s)


def rebalance_release(address: Tuple[str, int], rank: int,
                      generation: int, target: Tuple[str, int],
                      timeout_s: float = 30.0) -> str:
    """Post-COMMIT on the source shard: drop the migrated rank's state
    and start redirecting its consumers to ``target``."""
    payload = json.dumps({"host": str(target[0]),
                          "port": int(target[1])}).encode("utf-8")
    return _rebalance_call(address, REB_RELEASE, rank, generation,
                           payload=payload, timeout_s=timeout_s)


def rebalance_unseal(address: Tuple[str, int], rank: int,
                     timeout_s: float = 30.0) -> str:
    """ABORT cleanup on the source shard: lift a PREPARE seal so the
    still-authoritative source resumes serving new frames."""
    return _rebalance_call(address, REB_UNSEAL, rank, 0,
                           timeout_s=timeout_s)


class ShardedQueueServer:
    """N in-process :class:`QueueServer` shards over one ``MultiQueue``.

    The in-process face of the sharded serving plane: each shard owns
    the queues of its ranks (``plan.ir.queue_shard``), listens on its
    own port, keeps its own replay/lease/journal state, and publishes
    per-shard metrics. ``shard_map`` is the :class:`plan.ir.ShardMap`
    consumers route by (hand it to :class:`ShardedRemoteQueue`). The
    process-per-shard topology lives in
    ``runtime.supervisor.launch_supervised_queue_shards``.
    """

    def __init__(self, queue: mq.MultiQueue, num_shards: int,
                 num_trainers: int = 1, host: str = "127.0.0.1",
                 journals: Optional[List] = None,
                 initial_states: Optional[List] = None,
                 handle_dir: Optional[str] = None,
                 tenants: Optional[dict] = None):
        num_shards = max(1, num_shards)
        self.servers: List[QueueServer] = []
        try:
            for shard in range(num_shards):
                self.servers.append(QueueServer(
                    queue, (host, 0), num_trainers=num_trainers,
                    journal=journals[shard] if journals else None,
                    initial_state=(initial_states[shard]
                                   if initial_states else None),
                    shard_index=shard, num_shards=num_shards,
                    handle_dir=(os.path.join(handle_dir, f"s{shard}")
                                if handle_dir else None),
                    tenants=tenants))
        except BaseException:
            self.close()
            raise
        self.shard_map = plan_ir.ShardMap(
            num_trainers=max(1, num_trainers),
            addresses=[s.address for s in self.servers])
        rt_metrics.gauge(
            "rsdl_queue_serve_shards",
            "shard count of the live queue serving plane").set(num_shards)

    @property
    def num_shards(self) -> int:
        return len(self.servers)

    def close(self) -> None:
        for server in self.servers:
            server.close()

    def __enter__(self) -> "ShardedQueueServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def serve_queue_sharded(queue: mq.MultiQueue,
                        num_shards: Optional[int] = None,
                        num_trainers: int = 1,
                        host: str = "127.0.0.1",
                        **kwargs) -> ShardedQueueServer:
    """Shard-serve ``queue`` (``num_shards`` defaults to the
    ``RSDL_QUEUE_SHARDS`` policy; 1 reproduces the single-server
    topology exactly)."""
    if num_shards is None:
        num_shards = rt_policy.resolve("queue", "queue_shards")
    return ShardedQueueServer(queue, num_shards,
                              num_trainers=num_trainers, host=host,
                              **kwargs)


class RemoteQueue:
    """Consumer-side handle to a served queue.

    ``get`` returns a materialized ``pa.Table``, ``None`` (epoch end), or
    a :class:`ShuffleFailure` — the exact item vocabulary
    ``ShufflingDataset.__iter__`` consumes, so
    ``ShufflingDataset(batch_queue=RemoteQueue(addr), shuffle_result=None)``
    is a drop-in remote trainer. Connects with the reference's
    retry-with-doubling-backoff schedule (reference: multiqueue.py:310-332).

    ``max_batch`` tables ride each round trip, and with ``prefetch=True``
    (default) a background thread keeps the next batched request in
    flight while the consumer drains the local buffer — the wire is
    overlapped with consumption instead of serialized against it.

    v2 recovery surface:

    - every frame's CRC is verified; a corrupt frame is NACK'd and
      re-fetched from the server's replay buffer — the stream never
      carries damaged bytes forward.
    - a connection failure at ANY point (including mid-response) is
      recovered by reconnect + resume: the first GET per queue after a
      (re)connect carries ``FLAG_RESUME`` and the delivered watermark,
      the server replays the unacked suffix, and frames at-or-below the
      watermark are dropped client-side — exactly-once delivery.
    - ``ack_mode="delivered"`` (default) acks each frame as ``get``
      returns it. ``ack_mode="manual"`` holds acks until
      :meth:`commit` — the checkpoint integration: ``resume_iterator``
      commits at every checkpoint save, so a killed-and-resumed trainer
      finds everything since its last checkpoint still replayable.
    - a heartbeat thread keeps the server-side consumer lease alive
      between GETs (long train steps must not read as a dead trainer).
    """

    #: Consumer-side delivery-latency hops are observed HERE (the wire
    #: client sees the stamps first); datasets layered on top read this
    #: marker and skip their own birth_to_delivered observation.
    observes_delivery = True

    def __init__(self, address: Tuple[str, int],
                 retries: int = mq.CONNECT_RETRIES,
                 initial_backoff_s: float = mq.CONNECT_INITIAL_BACKOFF_S,
                 max_batch: int = DEFAULT_MAX_BATCH,
                 prefetch: bool = True,
                 ack_mode: str = "delivered",
                 consumer_id: Optional[int] = None,
                 delivery: Optional[str] = None,
                 num_trainers: int = 1,
                 tenant=None):
        if ack_mode not in ("delivered", "manual"):
            raise ValueError(
                f"ack_mode must be 'delivered' or 'manual', got {ack_mode!r}")
        self._address = address
        self._ack_mode = ack_mode
        # Tenancy (tenancy/): a TenantContext / id / dict announces this
        # consumer's identity via OP_TENANT right after every HELLO, so
        # reconnects re-bind it; None sends nothing (the legacy wire).
        self._tenant = (rt_tenancy.resolve(tenant)
                        if tenant is not None else None)
        # Latency-plane labeling: the queue label is the TRAINER RANK
        # (bounded cardinality), derived from the queue index by the
        # plan's route contract. Single-trainer consumers (the default)
        # resolve every queue to rank 0; sharded consumers get the real
        # width from their shard map.
        self._num_trainers = max(1, int(num_trainers))
        self._lat_anchors = rt_lat.ClockAnchors()
        # Shm-handle capability (v3): "auto" offers handles when the
        # server address is loopback (same host by construction);
        # "handle" forces the offer (shared shm mounts); "stream" never
        # offers — the v2 wire exactly. A handle that turns out to be
        # unusable is NACK'd with NACK_NO_HANDLE and the queue degrades
        # to streamed delivery, so a wrong "handle" is slow, not wrong.
        self._delivery = rt_policy.resolve("queue", "queue_delivery",
                                           override=delivery)
        if self._delivery not in ("auto", "stream", "handle"):
            raise ValueError(
                f"delivery must be auto, stream or handle, "
                f"got {self._delivery!r}")
        host = str(address[0])
        self._offer_handles = (
            self._delivery == "handle"
            or (self._delivery == "auto"
                and (host in _LOOPBACK_HOSTS or host.startswith("127."))))
        self._consumer_id = (consumer_id if consumer_id is not None
                             else int.from_bytes(os.urandom(8), "little"))
        self._timeout_s = rt_policy.resolve("queue", "queue_timeout_s")
        self._nodelay = rt_policy.resolve("queue", "queue_nodelay")
        self._lease_timeout_s = rt_policy.resolve("queue",
                                                  "queue_lease_timeout_s")
        # One RetryPolicy for connect AND mid-stream refetch: jittered
        # doubling backoff (many trainer processes dialing one server
        # de-synchronize), attempts pinned by the caller's budget.
        self._retry = rt_retry.RetryPolicy.for_component(
            "queue", retry_max_attempts=retries + 1,
            retry_initial_backoff_s=initial_backoff_s,
            retryable=rt_retry.transient_retryable)
        self._io_lock = threading.Lock()      # serializes wire round trips
        self._state_lock = threading.Lock()   # guards buffers/done/pending
        self._closed = threading.Event()
        #: queue -> deque of (seq, row_offset_or_None, item)
        self._buffers: Dict[int, collections.deque] = \
            collections.defaultdict(collections.deque)
        self._done: set = set()
        self._pending: Dict[int, cf.Future] = {}
        #: last seq handed to the application, per queue (-1 = none).
        self._delivered: Dict[int, int] = collections.defaultdict(lambda: -1)
        #: ack watermark for manual mode (advanced by commit()).
        self._committed: Dict[int, int] = collections.defaultdict(lambda: -1)
        #: queues that completed a fetch on the CURRENT connection; the
        #: first GET per queue per connection carries FLAG_RESUME (a
        #: no-op on a healthy stream, a replay after any reconnect).
        self._fetched_since_connect: set = set()
        self._reconnects = rt_metrics.counter(
            "rsdl_queue_client_reconnects_total",
            "RemoteQueue reconnect-and-resume cycles")
        self._corrupt = rt_metrics.counter(
            "rsdl_queue_frames_corrupt_total",
            "frames rejected client-side on CRC mismatch")
        #: rank -> placement-generation fence floor (rebalance/). Raised
        #: by a KIND_MOVED redirect or adopt_positions(); any data frame
        #: stamped BELOW the floor is a zombie source still serving a
        #: migrated rank — dropped loudly, never delivered. Plain int
        #: reads/writes under the GIL; 0 (the pre-rebalance stamp) means
        #: no fence and reproduces the v3.2 wire behavior exactly.
        self._gen_floor: Dict[int, int] = {}
        self._fenced = rt_metrics.counter(
            "rsdl_rebalance_fenced_frames_total",
            "frames dropped below the placement-generation fence")
        try:
            self._retry.call(self._reconnect, describe=f"connect {address}")
        except OSError as e:
            raise ConnectionError(
                f"could not reach queue server at {address} after "
                f"{retries + 1} attempts: {e}")
        self._max_batch = max(1, max_batch)
        self._prefetch = prefetch
        self._io = cf.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="rsdl-rqueue-prefetch")
        self._heartbeat_thread = threading.Thread(
            target=self._heartbeat_loop, daemon=True,
            name="rsdl-rqueue-heartbeat")
        self._heartbeat_thread.start()

    def _reconnect(self) -> None:
        """(Re-)dial the queue server; the old socket (if any) is closed
        first so a half-dead connection cannot leak. Sends the lease
        HELLO and arms per-queue resume so the next GET on every queue
        replays the unacked suffix."""
        with self._io_lock:
            old = getattr(self, "_sock", None)
            if old is not None:
                try:
                    old.close()
                except OSError:
                    pass
                self._reconnects.inc()
            sock = socket.create_connection(self._address, timeout=30)
            # Socket hygiene via runtime/policy.py: finite recv timeout
            # (0 disables). With v2 resume, a timed-out response is
            # simply reconnected-and-replayed — never lost data.
            sock.settimeout(self._timeout_s or None)
            if self._nodelay:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            sock.sendall(_REQUEST.pack(
                OP_HELLO,
                FLAG_HANDLES_OK if self._offer_handles else 0,
                self._consumer_id & 0xFFFFFFFF,
                (self._consumer_id >> 32) & 0xFFFFFFFF, 0))
            if self._tenant is not None:
                blob = self._tenant.to_json()
                sock.sendall(_REQUEST.pack(
                    OP_TENANT, 0,
                    self._consumer_id & 0xFFFFFFFF,
                    (self._consumer_id >> 32) & 0xFFFFFFFF,
                    len(blob)) + blob)
            self._sock = sock
            self._fetched_since_connect = set()

    def _heartbeat_loop(self) -> None:
        """Keep the server-side lease alive while the trainer chews on a
        long step between GETs. Skips a beat rather than queueing behind
        an in-flight round trip (which beats the lease by itself)."""
        interval = max(0.2, self._lease_timeout_s / 3.0)
        while not self._closed.wait(interval):
            if not self._io_lock.acquire(timeout=interval / 2):
                continue  # a round trip is in flight: that IS a beat
            try:
                self._sock.sendall(_REQUEST.pack(OP_HEARTBEAT, 0, 0, 0, 0))
            except OSError:
                pass  # next fetch reconnects; lease survives the gap
            finally:
                self._io_lock.release()

    def _ack_for(self, queue_index: int) -> int:
        watermark = (self._committed[queue_index]
                     if self._ack_mode == "manual"
                     else self._delivered[queue_index])
        return ACK_NONE if watermark < 0 else watermark

    def commit(self, queue_index: Optional[int] = None) -> None:
        """Advance the manual-ack watermark to everything delivered so
        far (one queue, or all). Call after durably recording consumption
        — e.g. a checkpoint save; ``resume_iterator`` does this through
        ``ShufflingDataset.commit_consumed``."""
        with self._state_lock:
            indices = ([queue_index] if queue_index is not None
                       else list(self._delivered))
            for q in indices:
                self._committed[q] = max(self._committed[q],
                                         self._delivered[q])

    def export_positions(self, rank: int) -> Dict[int, Tuple[int, int]]:
        """Snapshot ``{queue: (delivered, committed)}`` for every queue
        of ``rank`` this client has touched, dropping its local buffers
        (a post-migration replay from the adopting shard supersedes
        them). The router hands this to the new shard's client via
        :meth:`adopt_positions` so the handoff stays exactly-once."""
        positions: Dict[int, Tuple[int, int]] = {}
        with self._state_lock:
            for q in set(self._delivered) | set(self._committed):
                if plan_ir.queue_rank(q, self._num_trainers) != rank:
                    continue
                positions[q] = (self._delivered[q], self._committed[q])
                self._buffers.pop(q, None)
                self._pending.pop(q, None)
        return positions

    def adopt_positions(self, positions: Dict[int, Tuple[int, int]],
                        generation: int = 0,
                        rank: Optional[int] = None) -> None:
        """Merge another client's delivered/committed watermarks (max
        wins — positions only ever advance) and raise ``rank``'s fence
        floor to ``generation``, so this client's first GET resumes at
        the exact frame the old shard's stream stopped at."""
        with self._state_lock:
            for q, (delivered, committed) in positions.items():
                self._delivered[q] = max(self._delivered[q], delivered)
                self._committed[q] = max(self._committed[q], committed)
            if rank is not None and generation > self._gen_floor.get(rank, 0):
                self._gen_floor[rank] = generation

    def _fetch_batch(self, queue_index: int) -> Tuple[List, bool]:
        """One wire round trip: request up to ``max_batch`` items and
        decode + CRC-verify the response frames. Runs on the caller's
        thread or the prefetcher; ``_io_lock`` keeps round trips whole.

        Failure handling rides the shared RetryPolicy: ANY round-trip
        death — before or after response bytes — reconnects and resumes.
        The v2 sequence numbers make the resume exact: the server replays
        from the ack watermark and frames the client already delivered
        are dropped by seq, so a reset can neither lose nor duplicate an
        item (the v1 protocol had to fail loudly mid-response here).
        """

        def _round_trip() -> Tuple[List[Tuple], bool]:
            response_started = False
            epoch_hint = None
            try:
                with self._io_lock:
                    rt_faults.inject("queue_fetch", task=queue_index)
                    resume = queue_index not in self._fetched_since_connect
                    ack = self._ack_for(queue_index)
                    try:
                        rt_faults.inject("ack_lost", task=queue_index)
                    except rt_faults.InjectedFault:
                        # A lost ack is harmless by design: acks are
                        # cumulative, the next GET's watermark covers it.
                        rt_telemetry.record("ack_lost", task=queue_index,
                                            suppressed_ack=ack)
                        ack = ACK_NONE
                    self._sock.sendall(_REQUEST.pack(
                        OP_GET_BATCH, FLAG_RESUME if resume else 0,
                        queue_index, self._max_batch, ack))
                    (count,) = _BATCH_HEADER.unpack(
                        _recv_exact(self._sock, _BATCH_HEADER.size))
                    response_started = True
                    frames = []
                    corrupt_seq = None
                    handle_fail_seq = None
                    rank = plan_ir.queue_rank(queue_index,
                                              self._num_trainers)
                    for _ in range(count):
                        (kind_byte, epoch, seq, crc, row_offset, length,
                         src_task, b_mono, b_unix, b_pid, q_mono, q_unix,
                         q_pid, gen) = _FRAME.unpack(
                             _recv_exact(self._sock, _FRAME.size))
                        kind = kind_byte & _KIND_MASK
                        codec = kind_byte >> 4
                        epoch_hint = epoch
                        birth = _unpack_stamp(b_mono, b_unix, b_pid)
                        queued = _unpack_stamp(q_mono, q_unix, q_pid)
                        payload = (_recv_payload(self._sock, length)
                                   if length else b"")
                        if corrupt_seq is not None \
                                or handle_fail_seq is not None:
                            continue  # drain framing past the bad frame
                        if kind == KIND_MOVED:
                            # Live-migration redirect (rebalance/): raise
                            # this rank's fence floor FIRST (so a zombie
                            # source can never out-race the redirect),
                            # then surface the new address to the router.
                            blob = bytes(payload)
                            if _crc(blob) != crc:
                                raise ConnectionError(
                                    "MOVED redirect failed CRC; refetching")
                            info = json.loads(blob.decode())
                            moved_gen = int(info["generation"])
                            if moved_gen > self._gen_floor.get(rank, 0):
                                self._gen_floor[rank] = moved_gen
                            raise QueueMoved(queue_index,
                                             int(info["rank"]),
                                             (info["host"], info["port"]),
                                             moved_gen)
                        if kind != KIND_FAILURE:
                            # Placement-generation fence: a data frame
                            # stamped below this rank's floor comes from
                            # a zombie source still serving a migrated
                            # rank — drop it loudly. Failure frames are
                            # exempt (stamped 0): errors always land.
                            floor = self._gen_floor.get(rank, 0)
                            if gen < floor:
                                self._fenced.inc()
                                rt_telemetry.record(
                                    "rebalance_fence", epoch=epoch,
                                    task=queue_index, seq=seq,
                                    generation=gen, floor=floor)
                                logger.warning(
                                    "queue %d: fenced frame %d from "
                                    "zombie source (generation %d < "
                                    "floor %d)", queue_index, seq, gen,
                                    floor)
                                continue
                            if gen > floor:
                                self._gen_floor[rank] = gen
                        try:
                            # CRC is pre-compression: decompress first,
                            # verify the logical bytes (a torn
                            # compressed stream raises and is NACK'd
                            # like any corruption).
                            raw = (_decompress(codec, payload)
                                   if codec != CODEC_NONE else payload)
                        except Exception:  # noqa: BLE001 - NACK'd below
                            raw = None
                        if raw is None or _crc(raw) != crc:
                            # End-to-end integrity: reject the frame and
                            # everything after it (in-order delivery),
                            # but keep READING so the stream framing
                            # stays aligned; NACK below so the server
                            # rewinds and re-sends the good copy from
                            # its replay buffer.
                            corrupt_seq = seq
                            self._corrupt.inc()
                            rt_telemetry.record("frame_corrupt",
                                                epoch=epoch,
                                                task=queue_index, seq=seq)
                            logger.warning(
                                "queue %d: frame %d failed CRC; NACKing",
                                queue_index, seq)
                            continue
                        if kind == KIND_TABLE_HANDLE:
                            # Shm-handle delivery: mmap the segment the
                            # server serialized and verify its CRC off
                            # the mapped pages — zero-copy, nothing but
                            # the blob crossed the socket. Any failure
                            # downgrades this queue to streamed bytes
                            # (NACK_NO_HANDLE below).
                            try:
                                handle = json.loads(bytes(raw).decode())
                                buf = pp.read_segment_buffer(
                                    handle["path"])
                                if _crc(buf) != handle["crc"]:
                                    raise ValueError(
                                        "segment CRC mismatch")
                            except (OSError, ValueError, KeyError,
                                    TypeError) as e:
                                handle_fail_seq = seq
                                rt_telemetry.record(
                                    "handle_downgrade", epoch=epoch,
                                    task=queue_index, seq=seq)
                                logger.warning(
                                    "queue %d: shm handle for frame %d "
                                    "unusable (%s); requesting streamed "
                                    "delivery", queue_index, seq, e)
                                continue
                            kind, raw = KIND_TABLE, buf
                        if kind == KIND_TABLE and src_task != TASK_NONE:
                            # Cross-process causal link: this frame's
                            # payload was built by reduce task
                            # ``src_task`` in the SERVER process — the
                            # merged trace (runtime/trace.py) joins the
                            # consumer-side fetch to that exact span by
                            # (epoch, task).
                            rt_telemetry.record("frame_recv", epoch=epoch,
                                                task=src_task, seq=seq)
                        frames.append((kind, seq, row_offset, raw,
                                       birth, queued))
                    if corrupt_seq is not None:
                        self._sock.sendall(_REQUEST.pack(
                            OP_NACK, 0, queue_index, corrupt_seq,
                            NACK_CRC))
                    elif handle_fail_seq is not None:
                        self._sock.sendall(_REQUEST.pack(
                            OP_NACK, 0, queue_index, handle_fail_seq,
                            NACK_NO_HANDLE))
                    self._fetched_since_connect.add(queue_index)
                return frames, resume
            except (ConnectionError, OSError) as e:
                if response_started:
                    # Mid-response reset: v1's unrecoverable case, now
                    # the recovery path's bread and butter. The plain
                    # event joins an injected conn_reset_midframe fault
                    # by (kind, epoch, task) — by construction.
                    rt_telemetry.record("conn_reset_midframe",
                                        epoch=epoch_hint, task=queue_index,
                                        error=str(e))
                    logger.warning(
                        "queue %d: connection died mid-response (%s); "
                        "reconnecting and replaying the unacked suffix",
                        queue_index, e)
                raise

        def _redial(error: BaseException) -> None:
            if not isinstance(error, (ConnectionError, OSError)):
                return
            try:
                self._reconnect()
            except OSError as e:
                # A restarting server may not be accepting yet; the old
                # socket is already closed, so the NEXT attempt fails
                # fast and this redial runs again after its backoff —
                # the reconnect storm spends the retry budget, it does
                # not escape it.
                logger.info("queue redial to %s not up yet (%s); will "
                            "retry", self._address, e)

        with rt_telemetry.span("queue_fetch", task=queue_index):
            frames, resumed = self._retry.call(
                _round_trip, describe=f"fetch queue {queue_index}",
                on_retry=_redial)
        items: List[Tuple] = []
        for kind, seq, row_offset, payload, birth, queued in frames:
            if kind == KIND_SENTINEL:
                items.append((seq, None, None, None, None))
                break  # epoch over; nothing valid can follow
            if kind == KIND_FAILURE:
                items.append((seq, None, ShuffleFailure(
                    RuntimeError(bytes(payload).decode())), None, None))
                break
            # ``payload`` is a pa.Buffer (mmap'd segment), a memoryview
            # of the recv buffer, or decompressed bytes — all read
            # zero-copy through py_buffer; the table's Arrow buffers
            # alias it, so no re-materialization happens here either.
            source = (payload if isinstance(payload, pa.Buffer)
                      else pa.py_buffer(payload))
            with pa.ipc.open_stream(pa.BufferReader(source)) as reader:
                items.append((seq, row_offset, reader.read_all(),
                              birth, queued))
        return items, resumed

    def _epoch_over(self, entry) -> bool:
        _, _, item = entry
        return item is None or isinstance(item, ShuffleFailure)

    def _ingest(self, queue_index: int, items: List[Tuple],
                resumed: bool) -> None:
        buf = self._buffers[queue_index]
        if resumed:
            # The server replayed from the ack watermark: locally
            # buffered-but-undelivered copies are superseded by the
            # replay (same seqs), so drop them rather than double-buffer.
            buf.clear()
        delivered = self._delivered[queue_index]
        rank = str(plan_ir.queue_rank(queue_index, self._num_trainers))
        fresh = []
        for seq, row_offset, item, birth, queued in items:
            if seq <= delivered or (buf and seq <= buf[-1][0]):
                continue  # replayed frame we already have: exactly-once
            # Delivery-latency hops, observed only for frames actually
            # entering the stream (a dup dropped by seq above was
            # already delivered once — observing it again would count
            # one payload twice). Replayed frames carry their ORIGINAL
            # stamps, so a replay records its true, crash/reset-spanning
            # latency here.
            queued_lat = self._lat_anchors.latency_s(queued)
            rt_lat.observe_hop(rt_lat.HOP_QUEUED_TO_DELIVERED, rank,
                               queued_lat)
            if self._tenant is not None and queued_lat is not None:
                rt_metrics.sketch(
                    "rsdl_tenant_delivery_latency_seconds",
                    "per-tenant delivery latency by hop",
                    hop=rt_lat.HOP_QUEUED_TO_DELIVERED,
                    tenant=self._tenant.tenant_id).observe(queued_lat)
            if birth is not None:
                age = self._lat_anchors.latency_s(birth)
                rt_lat.observe_hop(rt_lat.HOP_BIRTH_TO_DELIVERED, rank,
                                   age)
                if self._tenant is not None and age is not None:
                    rt_metrics.sketch(
                        "rsdl_tenant_delivery_latency_seconds",
                        "per-tenant delivery latency by hop",
                        hop=rt_lat.HOP_BIRTH_TO_DELIVERED,
                        tenant=self._tenant.tenant_id).observe(age)
                rt_lat.set_freshness(rank, age)
            if item is None and row_offset is None:
                fresh.append((seq, None, None))
            else:
                fresh.append((seq, row_offset, item))
        buf.extend(fresh)
        if fresh and self._epoch_over(fresh[-1]):
            self._done.add(queue_index)
        elif self._prefetch and queue_index not in self._pending:
            # Submit the NEXT batched request as soon as this one lands,
            # so the wire round trip overlaps the consumption of the
            # whole freshly-buffered batch (costs one extra batch of
            # client-side buffering); waiting until the buffer drained
            # would overlap only the last item's consumption.
            # _ingest is only ever called with _state_lock held by its
            # caller (get below), so this write IS lock-guarded:
            # rsdl-lint: disable=lock-mutation
            self._pending[queue_index] = self._io.submit(
                self._fetch_batch, queue_index)

    def get_positioned(self, queue_index: int):
        """Blocking get returning ``(item, row_offset)``: the item plus
        the absolute row position of its first row in this queue's stream
        (None for sentinels/failures). ``ShufflingDataset`` uses the
        position to make checkpoint-resume skips exact against a
        replaying stream."""
        with self._state_lock:
            buf = self._buffers[queue_index]
            while not buf:
                if queue_index in self._done:
                    raise RuntimeError(
                        f"remote queue {queue_index} already yielded its "
                        f"epoch-end sentinel")
                # At most ONE in-flight request per queue index: a second
                # concurrent getter on the same index waits on the SAME
                # future instead of issuing its own round trip, which
                # could ingest batches out of request order. The future
                # stays registered while in flight; whichever waiter
                # observes it still registered after completion unlinks
                # it and ingests — exactly once.
                fut = self._pending.get(queue_index)
                if fut is None:
                    fut = self._pending[queue_index] = self._io.submit(
                        self._fetch_batch, queue_index)
                # Do the (possibly long) wire wait without holding the
                # state lock, so a concurrent get on another queue index
                # can still drain its local buffer.
                self._state_lock.release()
                try:
                    # The wire wait runs with _state_lock RELEASED (the
                    # release/reacquire bracket above/below); the static
                    # with-block scope is wider than the dynamic hold:
                    # rsdl-lint: disable=lock-blocking-call
                    items, resumed = fut.result()
                finally:
                    self._state_lock.acquire()
                    mine = self._pending.get(queue_index) is fut
                    if mine:
                        del self._pending[queue_index]
                if mine:
                    self._ingest(queue_index, items, resumed)
            seq, row_offset, item = buf.popleft()
            if seq != ACK_NONE:  # out-of-band failure frames carry no seq
                self._delivered[queue_index] = max(
                    self._delivered[queue_index], seq)
        return item, row_offset

    def get(self, queue_index: int, block: bool = True):
        if not block:
            raise ValueError("RemoteQueue only supports blocking gets")
        item, _ = self.get_positioned(queue_index)
        return item

    def close(self) -> None:
        self._closed.set()
        self._io.shutdown(wait=False, cancel_futures=True)
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self) -> "RemoteQueue":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class ShardedRemoteQueue:
    """Consumer-side handle to the sharded serving plane.

    Routes every queue index to its shard by the plan query the server
    placed it with (:meth:`plan.ir.ShardMap.shard_for_queue`), holding
    one :class:`RemoteQueue` per shard it actually touches (a trainer
    rank touches exactly one, by the rank-based placement). Duck-types
    the ``RemoteQueue`` consumer surface (``get`` / ``get_positioned``
    / ``commit`` / ``close``), so
    ``ShufflingDataset(batch_queue=ShardedRemoteQueue(shard_map))`` is
    the same drop-in remote trainer — each shard connection keeps its
    own lease, resume watermarks and prefetch pipeline, so one dead
    shard never stalls a stream served by its siblings.
    """

    #: See RemoteQueue.observes_delivery (every shard client observes).
    observes_delivery = True

    def __init__(self, shard_map: Union[plan_ir.ShardMap, dict, str],
                 **remote_kwargs):
        if isinstance(shard_map, str):
            shard_map = plan_ir.ShardMap.from_json(shard_map)
        elif isinstance(shard_map, dict):
            shard_map = plan_ir.ShardMap.from_dict(shard_map)
        shard_map.validate()
        self._shard_map = shard_map
        # The shard map knows the trainer width — hand it to each shard
        # client so latency-plane queue labels resolve to real ranks.
        remote_kwargs.setdefault("num_trainers", shard_map.num_trainers)
        self._remote_kwargs = remote_kwargs
        self._clients: Dict[int, RemoteQueue] = {}
        # _client() constructs a RemoteQueue while held, and that
        # __init__ dials through RetryPolicy.call — a bound-method hop
        # the static lock pass cannot follow, so locksan reports the
        # _clients_lock -> _io_lock edge as statically missing. It
        # cannot invert: the _io_lock taken under this lock belongs to
        # a client no other thread can reach until _client publishes
        # it into self._clients and returns.
        # rsdl-lint: disable=inconsistent-lock-order
        self._clients_lock = threading.Lock()

    @property
    def shard_map(self) -> plan_ir.ShardMap:
        return self._shard_map

    def _client(self, shard: int) -> RemoteQueue:
        with self._clients_lock:
            client = self._clients.get(shard)
            if client is None:
                client = self._clients[shard] = RemoteQueue(
                    tuple(self._shard_map.addresses[shard]),
                    **self._remote_kwargs)
            return client

    def client_for_queue(self, queue_index: int) -> RemoteQueue:
        return self._client(self._shard_map.shard_for_queue(queue_index))

    def _apply_move(self, moved: QueueMoved) -> None:
        """Follow a live-migration redirect: rewrite the local shard
        map's override for the moved rank, transfer the old shard
        client's delivered/committed positions to the new shard's client
        (max-merge — exactly-once across the handoff), and raise its
        fence floor so the zombie source's stragglers are dropped."""
        target_shard = None
        for shard, addr in enumerate(self._shard_map.addresses):
            if (str(addr[0]), int(addr[1])) == moved.address:
                target_shard = shard
                break
        if target_shard is None:
            raise RuntimeError(
                f"MOVED redirect names {moved.address[0]}:"
                f"{moved.address[1]}, which is not in this consumer's "
                f"shard map — the placement decision and the map "
                f"disagree") from moved
        with self._clients_lock:
            old_shard = self._shard_map.shard_for_rank(moved.rank)
            self._shard_map.overrides[moved.rank] = target_shard
            self._shard_map.generation = max(self._shard_map.generation,
                                             moved.generation)
            old_client = self._clients.get(old_shard)
        positions = (old_client.export_positions(moved.rank)
                     if old_client is not None else {})
        self._client(target_shard).adopt_positions(
            positions, generation=moved.generation, rank=moved.rank)
        logger.warning(
            "following MOVED redirect: rank %d shard %d -> %d at "
            "placement generation %d (%d queue position(s) carried)",
            moved.rank, old_shard, target_shard, moved.generation,
            len(positions))

    def _route(self, queue_index: int, op: Callable):
        """Run one consumer op against the owning shard, transparently
        following up to a handful of MOVED redirects (a stable placement
        needs exactly one; a bound stops a routing loop from a
        misconfigured plane)."""
        for _ in range(4):
            try:
                return op(self.client_for_queue(queue_index))
            except QueueMoved as moved:
                self._apply_move(moved)
        raise RuntimeError(
            f"queue {queue_index} still redirecting after 4 MOVED "
            f"hops; placement plane is unstable or misconfigured")

    def get_positioned(self, queue_index: int):
        return self._route(
            queue_index,
            lambda client: client.get_positioned(queue_index))

    def get(self, queue_index: int, block: bool = True):
        return self._route(
            queue_index,
            lambda client: client.get(queue_index, block=block))

    def commit(self, queue_index: Optional[int] = None) -> None:
        if queue_index is not None:
            self.client_for_queue(queue_index).commit(queue_index)
            return
        with self._clients_lock:
            clients = list(self._clients.values())
        for client in clients:
            client.commit()

    def close(self) -> None:
        with self._clients_lock:
            clients = list(self._clients.values())
            self._clients.clear()
        for client in clients:
            client.close()

    def __enter__(self) -> "ShardedRemoteQueue":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# ---------------------------------------------------------------------------
# Dedicated-server-process mode: build the whole producer pipeline (queue +
# deterministic shuffle + v2 server) from a config dict, resuming from the
# delivered-watermark journal — the unit runtime.supervisor restarts.
# ---------------------------------------------------------------------------


def _resume_plan(state: Dict[int, object], num_epochs: int,
                 num_trainers: int,
                 ranks: Optional[List[int]] = None
                 ) -> Tuple[int, Dict[int, int]]:
    """``(start_epoch, skip_items)`` from a loaded journal: the first
    epoch any rank has not fully consumed, and per-queue counts of items
    (tables + sentinel) already delivered that the re-run must not
    re-enqueue. The math is a plan query
    (``plan.ir.resume_from_watermarks``) — the server no longer carries
    private resume arithmetic; this wrapper keeps the historical name.
    ``ranks`` restricts the scan to a shard's owned ranks."""
    return plan_ir.resume_from_watermarks(state, num_epochs, num_trainers,
                                          ranks=ranks)


def _resuming_batch_consumer(queue: mq.MultiQueue, num_trainers: int,
                             skip_items: Dict[int, int],
                             owned_ranks: Optional[List[int]] = None):
    """``batch_consumer`` that re-runs the lineage but enqueues only the
    undelivered remainder: the first ``skip_items[q]`` items of each
    queue's deterministic stream (tables, then the sentinel) are dropped
    — they are already journaled as delivered. A serving SHARD passes
    its ``owned_ranks`` so foreign ranks' outputs (recomputed by the
    deterministic lineage regardless) are never enqueued or held."""
    remaining = dict(skip_items)
    owned = set(owned_ranks) if owned_ranks is not None else None
    lock = threading.Lock()

    def consumer(rank, epoch, refs):
        if owned is not None and rank not in owned:
            return
        queue_idx = plan_ir.queue_index(epoch, rank, num_trainers)
        with lock:
            to_skip = remaining.get(queue_idx, 0)
            if refs is None:
                if to_skip > 0:
                    remaining[queue_idx] = to_skip - 1
                    return
            else:
                refs = list(refs)
                dropped = min(to_skip, len(refs))
                remaining[queue_idx] = to_skip - dropped
                refs = refs[dropped:]
                if not refs:
                    return
        if refs is None:
            queue.put(queue_idx, None)
        else:
            queue.put_batch(queue_idx, refs)

    return consumer


def serve_pipeline(config: dict):
    """Child-process entry: queue + shuffle + v2/v3 server from
    ``config``.

    Resumes from the journal at ``config["journal_path"]``: per-queue
    sequence numbers and row offsets restore to their journaled
    watermarks, the shuffle re-runs from the first incomplete epoch
    (``(seed, epoch, task)`` determinism makes the re-run bit-identical),
    and already-delivered items are dropped before the queue — so the
    restarted server serves exactly the undelivered remainder.

    Sharding (``config["num_shards"]`` > 1 with ``"shard_index"``): this
    process serves ONLY the ranks ``plan.ir.shard_ranks`` assigns it —
    its journal covers exactly those queues, the resume scan is
    restricted to them, and foreign ranks' regenerated outputs are
    dropped before the queue. ``config["handle_dir"]`` (optional) pins
    the shm-handle segment dir so restarts reuse one location; stale
    segments from a killed incarnation are swept at startup.

    Returns ``(server, shuffle_result, queue)``.
    """
    from ray_shuffling_data_loader_tpu import checkpoint as ckpt
    from ray_shuffling_data_loader_tpu import dataset as ds
    import importlib
    sh = importlib.import_module("ray_shuffling_data_loader_tpu.shuffle")

    # Streaming mode: ``config["epochs"]`` is a FROZEN window schedule
    # (one ``{"epoch", "filenames", "window"}`` record per closed window,
    # ``streaming/window.py``). The schedule is data in the config, so a
    # restarted incarnation re-derives the identical epoch sequence —
    # the window-boundary half of the exactly-once proof; the journal
    # half below is epoch-generic and applies unchanged.
    stream_epochs = config.get("epochs")
    if stream_epochs is not None:
        num_epochs = len(stream_epochs)
    else:
        num_epochs = int(config["num_epochs"])
    num_trainers = int(config["num_trainers"])
    num_shards = int(config.get("num_shards", 1))
    shard_index = int(config.get("shard_index", 0))
    # Placement overrides (rebalance/): a restarted incarnation launched
    # AFTER a committed migration owns the post-move rank set — the
    # journal merge the adoption performed makes the resume exact.
    placement = config.get("placement") or {}
    overrides = {int(r): int(s)
                 for r, s in dict(placement.get("overrides", {})).items()}
    if num_shards > 1:
        owned_ranks = [r for r in range(num_trainers)
                       if overrides.get(r, r % num_shards) == shard_index]
    else:
        owned_ranks = None
    journal_path = config["journal_path"]
    handle_dir = config.get("handle_dir")
    if not handle_dir:
        # A STABLE per-journal segment dir under shm: a kill -9'd
        # incarnation cannot clean its segments, so the restarted child
        # (same journal identity -> same dir) must find and sweep them
        # instead of leaking shm until reboot.
        digest = zlib.crc32(os.path.abspath(journal_path).encode())
        handle_dir = os.path.join(pp.shm_base_dir(),
                                  f"rsdl-qhandles-{digest:08x}")
    if os.path.isdir(handle_dir):
        # Sweep stale segments from the previous incarnation (safe:
        # consumers mmap segments at fetch time, so a live mapping
        # survives the unlink).
        for name in os.listdir(handle_dir):
            try:
                os.unlink(os.path.join(handle_dir, name))
            except OSError:
                pass
    state = ckpt.WatermarkJournal.load(journal_path)
    start_epoch, skip_items = _resume_plan(state, num_epochs, num_trainers,
                                           ranks=owned_ranks)
    if state:
        logger.warning(
            "queue server (shard %d/%d) resuming from journal %s: "
            "start_epoch=%d, skipping %s already-delivered items",
            shard_index, num_shards, journal_path, start_epoch,
            {q: n for q, n in skip_items.items() if n})
    journal = ckpt.WatermarkJournal(journal_path)
    journal.compact()
    queue = mq.MultiQueue(num_epochs * num_trainers)
    consumer = _resuming_batch_consumer(queue, num_trainers, skip_items,
                                        owned_ranks=owned_ranks)
    if stream_epochs is not None:
        specs = [plan_ir.EpochSpec(
                     epoch=int(e["epoch"]),
                     filenames=tuple(str(f) for f in e["filenames"]),
                     window=(dict(e["window"])
                             if e.get("window") is not None else None),
                     tenant_id=e.get("tenant_id"))
                 for e in stream_epochs]
        specs = [s for s in specs if s.epoch >= start_epoch]
        serve_gauge = rt_metrics.gauge(
            "rsdl_stream_serve_watermark",
            "stream time fully handed to the serving plane")

        def _on_epoch_done(epoch: int,
                           by_epoch={s.epoch: s for s in specs}) -> None:
            spec = by_epoch.get(epoch)
            watermark = (spec.window or {}).get("ingest_watermark") \
                if spec is not None else None
            if watermark is not None:
                serve_gauge.set(float(watermark))

        shuffle_result = sh.run_shuffle_epochs_in_background(
            specs, consumer, int(config["num_reducers"]), num_trainers,
            int(config.get("max_concurrent_epochs", 2)),
            seed=int(config.get("seed", 0)),
            num_workers=config.get("num_workers"),
            file_cache=config.get("file_cache", "auto"),
            epochs_hint=len(specs), on_epoch_done=_on_epoch_done,
            on_failure=ds.make_failure_broadcaster(
                queue, num_epochs * num_trainers))
    else:
        shuffle_result = sh.run_shuffle_in_background(
            list(config["filenames"]), consumer, num_epochs,
            int(config["num_reducers"]), num_trainers,
            int(config.get("max_concurrent_epochs", 2)),
            seed=int(config.get("seed", 0)),
            num_workers=config.get("num_workers"),
            collect_stats=False, start_epoch=start_epoch,
            file_cache=config.get("file_cache", "auto"),
            on_failure=ds.make_failure_broadcaster(
                queue, num_epochs * num_trainers))
    server = QueueServer(
        queue, (config.get("host", "127.0.0.1"), int(config["port"])),
        num_trainers=num_trainers, journal=journal, initial_state=state,
        exit_on_crash_site=True, shard_index=shard_index,
        num_shards=num_shards, handle_dir=handle_dir,
        tenants=config.get("tenants"),
        placement=config.get("placement"))
    rt_metrics.gauge(
        "rsdl_queue_serve_shards",
        "shard count of the live queue serving plane").set(num_shards)
    return server, shuffle_result, queue


def _serve_main(argv: List[str]) -> int:
    """``python -m ray_shuffling_data_loader_tpu.multiqueue_service
    <config.json>`` — the supervised queue-server child process."""
    if len(argv) != 2:
        print("usage: python -m ray_shuffling_data_loader_tpu."
              "multiqueue_service <config.json>", file=sys.stderr)
        return 2
    with open(argv[1]) as f:
        config = json.load(f)

    # The supervisor stops a child with SIGTERM; convert it into a
    # normal SystemExit unwind so the finally below (and the atexit
    # trace dump telemetry registers under RSDL_TRACE_DIR, which this
    # child inherits through the environment) actually runs — a killed
    # incarnation's flight recorder is exactly the evidence a merged
    # cross-process trace needs from it.
    import signal as _signal

    def _on_sigterm(_signum, _frame):
        raise SystemExit(0)

    try:
        _signal.signal(_signal.SIGTERM, _on_sigterm)
    except (ValueError, OSError):  # pragma: no cover - non-main thread
        pass

    # Ops-plane federation (both inherited through the child env, like
    # RSDL_TRACE_DIR): the server's registry joins the merged exposition
    # via its per-pid shard, and an incident capture's SIGUSR1 gets a
    # live flight-recorder dump instead of waiting for process exit.
    rt_telemetry.install_signal_dump()
    rt_metrics.maybe_start_shard_writer()

    server, shuffle_result, queue = serve_pipeline(config)
    print(f"READY {server.address[1]}", flush=True)
    try:
        shuffle_result.result()
        # Shuffling is done but consumers may still be draining (and
        # re-fetching replays); serve until the supervisor stops us.
        threading.Event().wait()
    finally:
        server.close()
        queue.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(_serve_main(sys.argv))
