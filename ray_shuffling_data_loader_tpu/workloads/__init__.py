"""End-to-end workload recipes for the BASELINE.json target configs.

Each module pairs a seeded synthetic data generator (the de-facto universal
fixture, following the reference's data_generation.py) with the column spec
and transform hooks that wire the workload into ``JaxShufflingDataset``:

- ``imagenet``: ResNet-50 on ImageNet-style Parquet shards — encoded image
  bytes shuffled as-is, decoded to fixed-shape pixel columns INSIDE the
  shuffle reducers (BASELINE config 3).
- ``bert_mlm``: BERT MLM on pre-tokenized sequence Parquet — fixed-length
  token list columns batched through the shuffle, with on-device dynamic
  masking (BASELINE config 4).

The tabular DLRM workload (configs 1/2/5) lives in ``data_generation`` +
``models/dlrm`` since it is the reference's own data spec.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple


def generate_shards(write_file: Callable[[int, int, int], Tuple[str, int]],
                    total_rows: int,
                    num_files: int,
                    num_workers: Optional[int] = None,
                    thread_name_prefix: str = "rsdl-gen"
                    ) -> Tuple[List[str], int]:
    """Shared parallel shard writer: fan ``write_file(file_index,
    global_row_index, num_rows) -> (path, nbytes)`` out over the host pool
    using data_generation's file plan (same stride arithmetic as the
    reference, data_generation.py:19-23)."""
    from ray_shuffling_data_loader_tpu import executor as ex
    from ray_shuffling_data_loader_tpu.data_generation import _file_plan

    with ex.Executor(num_workers=num_workers,
                     thread_name_prefix=thread_name_prefix) as pool:
        refs = [
            pool.submit(write_file, file_index, start, n)
            for file_index, start, n in _file_plan(total_rows, num_files)
        ]
        results = ex.get(refs)
    filenames, sizes = zip(*results)
    return list(filenames), sum(sizes)
