"""DLRM click-log workload: the reference's own data spec, end to end.

BASELINE config 5: "DLRM on Criteo-1TB click logs, distributed shuffle
across v4-32". The reference generates DLRM-shaped rows (17 embedding
columns with Criteo-like cardinalities + 2 one-hots + float label,
reference: data_generation.py:74-95) but never trains on them — its train
step is a mock sleep (reference: ray_torch_shuffle.py:199-204). This
module wires that schema through the shuffle into the real DLRM model
(models/dlrm.py):

- :func:`narrowest_dtype` / :func:`dlrm_feature_types`: per-column
  narrowest integer dtype covering the cardinality (int8/int16/int32).
  Applied at the map stage (``cast_at_map``), it shrinks every downstream
  byte — partition, permute-gather, re-batch, host->HBM DMA — from 76 to
  43 bytes/row for the reference spec; indices widen for free on device.
- :func:`dlrm_spec`: ``JaxShufflingDataset`` kwargs for the schema.
- Multi-host (v4-32 and up): run the same spec with
  ``parallel.distributed.create_distributed_batch_queue_and_shuffle`` on
  each host — examples/jax_train_shuffle.py shows the full recipe
  (``RSDL_HOSTS`` global shuffle + per-host consumer queues).

Online training (streaming/): click logs are the canonical UNBOUNDED
input — the click-through rate drifts as campaigns rotate, and a model
trained on a frozen snapshot decays. :func:`generate_drifting_stream`
writes DLRM-schema files whose CTR drifts sinusoidally with stream
position, and :func:`run_online_training` consumes them through a
:class:`streaming.runner.StreamingShuffleRunner` — one closed window =
one training epoch — updating an :class:`OnlineCTRModel` per window.
The returned history shows the estimate tracking the drift, which a
static-shuffle trainer structurally cannot do (examples/streaming.md).
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional

import numpy as np

from ray_shuffling_data_loader_tpu import data_generation as dg


def narrowest_dtype(cardinality: int) -> np.dtype:
    """Smallest signed integer dtype that represents [0, cardinality)."""
    if cardinality <= 2**7:
        return np.dtype(np.int8)
    if cardinality <= 2**15:
        return np.dtype(np.int16)
    if cardinality <= 2**31:
        return np.dtype(np.int32)
    return np.dtype(np.int64)


def dlrm_feature_types(
        columns: List[str] = None) -> List[np.dtype]:
    """Narrowest dtype per feature column of the reference DATA_SPEC."""
    if columns is None:
        columns = list(dg.FEATURE_COLUMNS)
    return [narrowest_dtype(dg.DATA_SPEC[c][1]) for c in columns]


def dlrm_spec() -> Dict[str, Any]:
    """``JaxShufflingDataset`` kwargs for the reference's DLRM schema with
    narrow-dtype transfer. Features arrive as one per-column list (the
    access pattern DLRM's per-table lookups want)."""
    return {
        "feature_columns": list(dg.FEATURE_COLUMNS),
        "feature_types": dlrm_feature_types(),
        "label_column": dg.LABEL_COLUMN,
        "label_type": np.float32,
    }


# ---------------------------------------------------------------------------
# Drifting click stream: the online-training scenario
# ---------------------------------------------------------------------------


def drifting_ctr(file_index: int, drift_period: float = 8.0,
                 base: float = 0.25, amplitude: float = 0.2) -> float:
    """True click-through rate at stream position ``file_index`` — a slow
    sinusoid (campaign rotation), the ground truth an online model must
    track and a frozen model drifts away from."""
    return base + amplitude * math.sin(
        2.0 * math.pi * file_index / drift_period)


def generate_drifting_click_file(file_index: int, num_rows: int,
                                 data_dir: str, seed: int = 0,
                                 drift_period: float = 8.0) -> str:
    """One stream file of DLRM-schema rows whose labels are Bernoulli
    draws at :func:`drifting_ctr`. Features reuse the reference
    generator (same columns, same cardinalities); only the label
    distribution moves. Deterministic in ``(seed, file_index)``."""
    from ray_shuffling_data_loader_tpu.utils import fileio
    table = dg.generate_row_group(0, file_index * num_rows, num_rows,
                                  seed=seed)
    ctr = drifting_ctr(file_index, drift_period)
    rng = np.random.Generator(np.random.Philox(
        np.random.SeedSequence([seed, file_index])))
    labels = (rng.random(num_rows) < ctr).astype(np.float64)
    table = table.set_column(table.schema.get_field_index(dg.LABEL_COLUMN),
                             dg.LABEL_COLUMN, [labels])
    filename = fileio.join(data_dir,
                           f"clicks_{file_index:05d}.parquet.snappy")
    fileio.write_parquet(table, filename, compression="snappy",
                         row_group_size=num_rows)
    return filename


def generate_drifting_stream(num_files: int, rows_per_file: int,
                             data_dir: str, seed: int = 0,
                             drift_period: float = 8.0) -> List[str]:
    """The whole drifting stream, in arrival order."""
    from ray_shuffling_data_loader_tpu.utils import fileio
    fileio.makedirs(data_dir)
    return [generate_drifting_click_file(i, rows_per_file, data_dir,
                                         seed=seed,
                                         drift_period=drift_period)
            for i in range(num_files)]


class OnlineCTRModel:
    """Bias-only logistic regression trained by online SGD.

    The smallest model that exhibits the online-training property: its
    single logit must keep MOVING to follow the label drift, so a run
    over a drifting stream shows per-window estimates tracking
    :func:`drifting_ctr` while any frozen estimate accumulates error.
    (The full DLRM tower from models/dlrm.py plugs into the same loop —
    this keeps the example hermetic and CPU-cheap.)"""

    def __init__(self, lr: float = 0.5):
        self.lr = float(lr)
        self.logit = 0.0
        self.steps = 0

    def predict(self) -> float:
        return 1.0 / (1.0 + math.exp(-self.logit))

    def update(self, labels: np.ndarray) -> None:
        """One SGD step on a batch: gradient of mean log-loss w.r.t. the
        logit is ``predict() - mean(labels)``."""
        if labels.size == 0:
            return
        self.logit += self.lr * (float(np.mean(labels)) - self.predict())
        self.steps += 1


def run_online_training(files: List[str], num_windows: int,
                        files_per_window: int = 2, seed: int = 0,
                        num_reducers: int = 2,
                        journal_path: Optional[str] = None,
                        lr: float = 0.5) -> List[Dict[str, Any]]:
    """Online training over a drifting click stream, end to end.

    Streams ``files`` through a seeded :class:`SyntheticEventSource`,
    seals ``files_per_window``-file windows, shuffles each closed window
    as a normal epoch, and runs one :class:`OnlineCTRModel` SGD pass per
    delivered reducer table. Returns one record per window:
    ``{"window", "observed_ctr", "estimate"}`` — ``estimate`` is the
    model AFTER training on that window, ``observed_ctr`` the window's
    empirical label mean. Deterministic in ``(files, seed)``."""
    from ray_shuffling_data_loader_tpu import streaming as st
    from ray_shuffling_data_loader_tpu.streaming import window as st_window

    model = OnlineCTRModel(lr=lr)
    per_epoch: Dict[int, Dict[str, float]] = {}
    history: List[Dict[str, Any]] = []

    def consumer(rank, epoch, refs):
        if refs is None:
            stats = per_epoch.pop(epoch, {"clicks": 0.0, "rows": 0.0})
            rows = max(1.0, stats["rows"])
            history.append({
                "window": epoch,
                "observed_ctr": stats["clicks"] / rows,
                "estimate": model.predict(),
            })
            return
        for ref in refs:
            table = ref.result() if hasattr(ref, "result") else ref
            labels = np.asarray(
                table.column(dg.LABEL_COLUMN).combine_chunks())
            model.update(labels)
            stats = per_epoch.setdefault(epoch,
                                         {"clicks": 0.0, "rows": 0.0})
            stats["clicks"] += float(labels.sum())
            stats["rows"] += float(labels.size)

    source = st.SyntheticEventSource(
        files, seed=seed, total_events=num_windows * files_per_window)
    # max_concurrent_epochs=1: online SGD consumes windows in stream
    # order — overlapping window N+1's shuffle under window N's training
    # is a serving-plane optimization (the runner's default), but HERE
    # the model update order must be the stream order to be meaningful.
    runner = st.StreamingShuffleRunner(
        source, consumer, num_reducers=num_reducers, num_trainers=1,
        seed=seed, max_concurrent_epochs=1,
        policy=st_window.WindowPolicy(max_files=files_per_window),
        journal_path=journal_path)
    runner.run()
    history.sort(key=lambda rec: rec["window"])
    return history
