"""DLRM click-log workload: the reference's own data spec, end to end.

BASELINE config 5: "DLRM on Criteo-1TB click logs, distributed shuffle
across v4-32". The reference generates DLRM-shaped rows (17 embedding
columns with Criteo-like cardinalities + 2 one-hots + float label,
reference: data_generation.py:74-95) but never trains on them — its train
step is a mock sleep (reference: ray_torch_shuffle.py:199-204). This
module wires that schema through the shuffle into the real DLRM model
(models/dlrm.py):

- :func:`narrowest_dtype` / :func:`dlrm_feature_types`: per-column
  narrowest integer dtype covering the cardinality (int8/int16/int32).
  Applied at the map stage (``cast_at_map``), it shrinks every downstream
  byte — partition, permute-gather, re-batch, host->HBM DMA — from 76 to
  43 bytes/row for the reference spec; indices widen for free on device.
- :func:`dlrm_spec`: ``JaxShufflingDataset`` kwargs for the schema.
- Multi-host (v4-32 and up): run the same spec with
  ``parallel.distributed.create_distributed_batch_queue_and_shuffle`` on
  each host — examples/jax_train_shuffle.py shows the full recipe
  (``RSDL_HOSTS`` global shuffle + per-host consumer queues).
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from ray_shuffling_data_loader_tpu import data_generation as dg


def narrowest_dtype(cardinality: int) -> np.dtype:
    """Smallest signed integer dtype that represents [0, cardinality)."""
    if cardinality <= 2**7:
        return np.dtype(np.int8)
    if cardinality <= 2**15:
        return np.dtype(np.int16)
    if cardinality <= 2**31:
        return np.dtype(np.int32)
    return np.dtype(np.int64)


def dlrm_feature_types(
        columns: List[str] = None) -> List[np.dtype]:
    """Narrowest dtype per feature column of the reference DATA_SPEC."""
    if columns is None:
        columns = list(dg.FEATURE_COLUMNS)
    return [narrowest_dtype(dg.DATA_SPEC[c][1]) for c in columns]


def dlrm_spec() -> Dict[str, Any]:
    """``JaxShufflingDataset`` kwargs for the reference's DLRM schema with
    narrow-dtype transfer. Features arrive as one per-column list (the
    access pattern DLRM's per-table lookups want)."""
    return {
        "feature_columns": list(dg.FEATURE_COLUMNS),
        "feature_types": dlrm_feature_types(),
        "label_column": dg.LABEL_COLUMN,
        "label_type": np.float32,
    }
