"""BERT-MLM workload: pre-tokenized sequence Parquet -> sequence batches.

BASELINE config 4: "BERT-base MLM on pre-tokenized Wikipedia Parquet
(sequence batching)". Rows are fixed-length token sequences stored as
``FixedSizeList<int32>`` columns; the shuffle moves them untouched (the
fused reduce falls back to Arrow concat+take for list columns,
shuffle.py:339-347) and ``JaxShufflingDataset`` reshapes each batch to
``(batch, seq_len)``.

MLM masking is **dynamic and on-device**: :func:`mlm_mask` is a jittable
function of (tokens, PRNG key) applying the BERT 80/10/10 rule. The
reference's pipeline could only ship statically pre-masked rows; keyed JAX
PRNG gives every epoch fresh masks for free, with zero host-side cost and
fully replayable (seed, epoch, step) streams.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from ray_shuffling_data_loader_tpu import workloads
from ray_shuffling_data_loader_tpu.models.bert import IGNORE_ID
from ray_shuffling_data_loader_tpu.utils.logger import setup_custom_logger

logger = setup_custom_logger(__name__)

TOKENS_COLUMN = "input_ids"
LABEL_COLUMN = "label"
KEY_COLUMN = "key"

# Conventional special-token ids for the synthetic vocab: [PAD]=0, [CLS]=1,
# [SEP]=2, [MASK]=3; real corpora pass their own ids to mlm_mask.
PAD_ID = 0
CLS_ID = 1
SEP_ID = 2
MASK_ID = 3
NUM_SPECIAL_TOKENS = 4


def generate_file(file_index: int, global_row_index: int, num_rows: int,
                  data_dir: str, seq_len: int, vocab_size: int,
                  seed: int) -> Tuple[str, int]:
    """One Parquet shard of [CLS] body... [SEP] token rows; (path, nbytes)."""
    rng = np.random.default_rng(np.random.SeedSequence([seed, file_index]))
    tokens = rng.integers(NUM_SPECIAL_TOKENS, vocab_size,
                          size=(num_rows, seq_len), dtype=np.int32)
    tokens[:, 0] = CLS_ID
    tokens[:, -1] = SEP_ID
    table = pa.table({
        TOKENS_COLUMN: pa.FixedSizeListArray.from_arrays(
            pa.array(tokens.reshape(-1)), seq_len),
        LABEL_COLUMN: np.zeros(num_rows, dtype=np.int64),
        KEY_COLUMN: np.arange(global_row_index, global_row_index + num_rows,
                              dtype=np.int64),
    })
    filename = os.path.join(data_dir,
                            f"tokenized_shard_{file_index}.parquet.snappy")
    pq.write_table(table, filename, compression="snappy")
    return filename, table.nbytes


def generate_tokenized_parquet(num_sequences: int,
                               num_files: int,
                               data_dir: str,
                               seq_len: int = 128,
                               vocab_size: int = 30522,
                               seed: int = 0,
                               num_workers: Optional[int] = None
                               ) -> Tuple[List[str], int]:
    """Parallel synthetic pre-tokenized shards (seeded)."""
    os.makedirs(data_dir, exist_ok=True)

    def write_file(file_index: int, start: int, n: int) -> Tuple[str, int]:
        return generate_file(file_index, start, n, data_dir, seq_len,
                             vocab_size, seed)

    filenames, total_bytes = workloads.generate_shards(
        write_file, num_sequences, num_files, num_workers=num_workers,
        thread_name_prefix="rsdl-bertgen")
    logger.info("generated %d tokenized shards, %d sequences, %.1f MB",
                len(filenames), num_sequences, total_bytes / 1e6)
    return filenames, total_bytes


def mlm_mask(tokens,
             key,
             vocab_size: int,
             mask_prob: float = 0.15,
             mask_token_id: int = MASK_ID,
             num_special_tokens: int = NUM_SPECIAL_TOKENS):
    """Jittable dynamic MLM masking: (tokens, PRNG key) -> (inputs, targets).

    BERT recipe: select ``mask_prob`` of non-special positions; of those,
    80% become [MASK], 10% a uniform random token, 10% keep the original.
    ``targets`` holds the original token at selected positions and
    ``IGNORE_ID`` elsewhere — exactly what models/bert.py ``loss_fn`` eats.
    Runs under jit on device: masking costs no host time and the stream is
    replayable from (seed, epoch, step).
    """
    import jax
    import jax.numpy as jnp

    select_key, action_key, random_key = jax.random.split(key, 3)
    maskable = tokens >= num_special_tokens
    selected = (jax.random.uniform(select_key, tokens.shape) < mask_prob) \
        & maskable
    action = jax.random.uniform(action_key, tokens.shape)
    random_tokens = jax.random.randint(
        random_key, tokens.shape, num_special_tokens, vocab_size,
        dtype=tokens.dtype)
    inputs = jnp.where(
        selected & (action < 0.8), mask_token_id,
        jnp.where(selected & (action >= 0.9), random_tokens, tokens))
    targets = jnp.where(selected, tokens, IGNORE_ID)
    return inputs, targets


def bert_mlm_spec(seq_len: int) -> Dict[str, Any]:
    """``JaxShufflingDataset`` kwargs for the tokenized-sequence layout."""
    return {
        "feature_columns": [TOKENS_COLUMN],
        "feature_shapes": [(seq_len,)],
        "feature_types": [np.int32],
        "label_column": LABEL_COLUMN,
        "label_type": np.int32,
    }


if __name__ == "__main__":
    # Smoke driver (reference pattern: dataset.py:233-276): tokenized
    # shards -> shuffle -> on-device dynamic masking -> BERT train loop.
    import argparse
    import tempfile
    import timeit

    parser = argparse.ArgumentParser(description="BERT-MLM workload smoke")
    parser.add_argument("--num-sequences", type=int, default=4096)
    parser.add_argument("--num-files", type=int, default=4)
    parser.add_argument("--num-epochs", type=int, default=2)
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--seq-len", type=int, default=128)
    parser.add_argument("--vocab-size", type=int, default=8192)
    parser.add_argument("--hidden-dim", type=int, default=128)
    parser.add_argument("--num-layers", type=int, default=2)
    parser.add_argument("--num-heads", type=int, default=4)
    parser.add_argument("--ffn-dim", type=int, default=256)
    args = parser.parse_args()

    import jax
    import jax.numpy as jnp
    import optax

    from ray_shuffling_data_loader_tpu.jax_dataset import JaxShufflingDataset
    from ray_shuffling_data_loader_tpu.models import bert

    with tempfile.TemporaryDirectory() as tmpdir:
        filenames, _ = generate_tokenized_parquet(
            args.num_sequences, args.num_files, tmpdir,
            seq_len=args.seq_len, vocab_size=args.vocab_size)
        ds = JaxShufflingDataset(
            filenames, num_epochs=args.num_epochs, num_trainers=1,
            batch_size=args.batch_size, rank=0, drop_last=True,
            **bert_mlm_spec(args.seq_len))
        cfg = bert.BertConfig(vocab_size=args.vocab_size,
                              hidden_dim=args.hidden_dim,
                              num_layers=args.num_layers,
                              num_heads=args.num_heads,
                              ffn_dim=args.ffn_dim,
                              max_seq_len=args.seq_len)
        params = bert.init(cfg, jax.random.key(0))
        opt = optax.adam(1e-4)
        opt_state = opt.init(params)

        # Measured-best attention for this sequence length: Pallas flash
        # on-chip from S=1024 up, XLA's fused inline attention below.
        from ray_shuffling_data_loader_tpu.ops.flash_attention import (
            auto_attention_fn)
        attention_fn = auto_attention_fn(args.seq_len)

        @jax.jit
        def step(params, opt_state, tokens, key):
            inputs, targets = mlm_mask(tokens, key, args.vocab_size)
            loss, grads = jax.value_and_grad(
                lambda p: bert.loss_fn(cfg, p, inputs, targets,
                                       attention_fn=attention_fn))(params)
            updates, opt_state = opt.update(grads, opt_state)
            return optax.apply_updates(params, updates), opt_state, loss

        start = timeit.default_timer()
        rows = steps = 0
        from ray_shuffling_data_loader_tpu.plan import ir as plan_ir
        for epoch in plan_ir.epoch_range(0, args.num_epochs):
            ds.set_epoch(epoch)
            for (tokens,), _ in ds:
                params, opt_state, loss = step(params, opt_state, tokens,
                                               jax.random.key(steps))
                rows += tokens.shape[0]
                steps += 1
        jax.block_until_ready(loss)
        duration = timeit.default_timer() - start
        print(f"{rows} sequences / {steps} steps in {duration:.2f}s "
              f"({rows / duration:,.0f} seq/s), final loss "
              f"{float(loss):.4f}, stall "
              f"{ds.batch_wait_stats.summary()['total']:.2f}s")
