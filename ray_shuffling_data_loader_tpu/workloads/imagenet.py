"""ImageNet-on-Parquet workload: image decode inside shuffle reducers.

BASELINE config 3: "ResNet-50 on ImageNet Parquet shards (image decode
inside shuffle reducers)". The reference never ships an image path — its
shuffle moves opaque DataFrame rows (reference: shuffle.py:229-247) — so
this module defines the TPU-native recipe:

- Parquet rows hold **encoded** image bytes (PNG/JPEG) plus an int label
  and a unique ``key``. The map/partition/permute stages shuffle the small
  encoded payloads; only the reduce stage, which runs once per reducer per
  epoch on the host thread pool and overlaps training, pays the decode.
- :func:`decode_transform` is a ``ReduceTransform`` (shuffle.py) that
  replaces the encoded column with a ``FixedSizeListArray<uint8>`` of
  ``H*W*C`` pixels. Downstream, ``JaxShufflingDataset`` reshapes it to
  ``(batch, H, W, C)`` and DMAs it to HBM as uint8 — 4x less PCIe/DCN
  traffic than float32; the model casts on device (models/resnet.py).
- Images stay uint8 end-to-end on the host; normalization belongs in the
  first device op where it is fused by XLA.
- Trade-off knob: passing :func:`decode_transform` as ``map_transform``
  instead decodes ONCE per file (the decoded pixels then ride the file
  cache across epochs) at the cost of shuffling ~H*W*3 bytes/row instead
  of the compressed payload — better when epochs >> RAM pressure, worse
  when the corpus is large relative to host memory. The default
  (``reduce_transform``) shuffles compressed bytes and re-decodes per
  epoch on the reducer pool.
"""

from __future__ import annotations

import io
import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from ray_shuffling_data_loader_tpu import workloads
from ray_shuffling_data_loader_tpu.utils.logger import setup_custom_logger

logger = setup_custom_logger(__name__)

IMAGE_COLUMN = "image"
LABEL_COLUMN = "label"
KEY_COLUMN = "key"


def _synthetic_image(rng: np.random.Generator, height: int, width: int,
                     label: int, num_classes: int) -> np.ndarray:
    """A learnable synthetic image: class-dependent mean color + noise."""
    hue = np.array([
        128 + 127 * np.sin(2 * np.pi * label / max(1, num_classes)),
        128 + 127 * np.cos(2 * np.pi * label / max(1, num_classes)),
        255 * label / max(1, num_classes - 1) if num_classes > 1 else 128,
    ])
    noise = rng.integers(-40, 40, size=(height, width, 3))
    return np.clip(hue[None, None, :] + noise, 0, 255).astype(np.uint8)


def _encode(image: np.ndarray, image_format: str) -> bytes:
    from PIL import Image
    buf = io.BytesIO()
    Image.fromarray(image).save(buf, format=image_format)
    return buf.getvalue()


def generate_file(file_index: int, global_row_index: int, num_rows: int,
                  data_dir: str, height: int, width: int, num_classes: int,
                  seed: int, image_format: str) -> Tuple[str, int]:
    """Write one Parquet shard of encoded images; returns (path, nbytes)."""
    rng = np.random.default_rng(
        np.random.SeedSequence([seed, file_index]))
    labels = rng.integers(0, num_classes, size=num_rows, dtype=np.int64)
    payloads = [
        _encode(_synthetic_image(rng, height, width, int(lbl), num_classes),
                image_format) for lbl in labels
    ]
    table = pa.table({
        IMAGE_COLUMN: pa.array(payloads, type=pa.binary()),
        LABEL_COLUMN: labels,
        KEY_COLUMN: np.arange(global_row_index, global_row_index + num_rows,
                              dtype=np.int64),
    })
    filename = os.path.join(data_dir,
                            f"imagenet_shard_{file_index}.parquet.snappy")
    pq.write_table(table, filename, compression="snappy")
    return filename, table.nbytes


def generate_imagenet_parquet(num_images: int,
                              num_files: int,
                              data_dir: str,
                              height: int = 64,
                              width: int = 64,
                              num_classes: int = 1000,
                              seed: int = 0,
                              image_format: str = "png",
                              num_workers: Optional[int] = None
                              ) -> Tuple[List[str], int]:
    """Parallel synthetic ImageNet-style Parquet shards (seeded)."""
    os.makedirs(data_dir, exist_ok=True)

    def write_file(file_index: int, start: int, n: int) -> Tuple[str, int]:
        return generate_file(file_index, start, n, data_dir, height, width,
                             num_classes, seed, image_format)

    filenames, total_bytes = workloads.generate_shards(
        write_file, num_images, num_files, num_workers=num_workers,
        thread_name_prefix="rsdl-imagen")
    logger.info("generated %d image shards, %d images, %.1f MB",
                len(filenames), num_images, total_bytes / 1e6)
    return filenames, total_bytes


def decode_transform(height: int,
                     width: int,
                     channels: int = 3,
                     image_column: str = IMAGE_COLUMN,
                     resize: bool = False):
    """ReduceTransform: encoded-bytes column -> FixedSizeList<uint8> pixels.

    Runs inside each reduce task on its shuffled output (shuffle.py
    ``reduce_transform``), so decode cost is spread across the reducer pool
    and overlaps training.

    ``resize=False`` (synthetic/pre-sized shards): sources must decode to
    exactly (height, width, channels) — enforced loudly, fixed shapes are
    a TPU invariant — and the threaded C++ decoder (native/image.py) is
    used when available. ``resize=True`` (real ImageNet-style corpora with
    ragged source sizes): every image is bilinearly resized to the target
    shape via PIL.
    """
    expected_shape = (height, width, channels)
    flat_len = height * width * channels

    def decode_pil(payloads) -> np.ndarray:
        from PIL import Image
        out = np.empty((len(payloads), flat_len), dtype=np.uint8)
        for i, payload in enumerate(payloads):
            image = Image.open(io.BytesIO(payload))
            if channels == 3:
                image = image.convert("RGB")
            if resize and image.size != (width, height):
                image = image.resize((width, height), Image.BILINEAR)
            arr = np.asarray(image, dtype=np.uint8)
            if arr.shape != expected_shape:
                raise ValueError(
                    f"decoded image shape {arr.shape} != expected "
                    f"{expected_shape}; resize at generation time or pass "
                    "resize=True — the TPU pipeline requires fixed shapes")
            out[i] = arr.reshape(-1)
        return out

    def transform(table: pa.Table) -> pa.Table:
        from ray_shuffling_data_loader_tpu.native import image as native_image
        payloads = table.column(image_column).to_pylist()
        if not resize and channels == 3 and native_image.available():
            # Threaded libjpeg/libpng batch decode (C++); PIL otherwise.
            out = native_image.decode_batch(payloads, height, width)
        else:
            out = decode_pil(payloads)
        decoded = pa.FixedSizeListArray.from_arrays(
            pa.array(out.reshape(-1)), flat_len)
        index = table.schema.get_field_index(image_column)
        return table.set_column(index, image_column, decoded)

    return transform


def imagenet_spec(height: int,
                  width: int,
                  channels: int = 3,
                  resize: bool = False) -> Dict[str, Any]:
    """``JaxShufflingDataset`` kwargs for the decoded-image layout."""
    return {
        "feature_columns": [IMAGE_COLUMN],
        "feature_shapes": [(height, width, channels)],
        "feature_types": [np.uint8],
        "label_column": LABEL_COLUMN,
        "label_type": np.int32,
        "reduce_transform": decode_transform(height, width, channels,
                                             resize=resize),
    }


if __name__ == "__main__":
    # Smoke driver (reference pattern: dataset.py:233-276): generate
    # encoded shards, stream decoded batches through the shuffle into a
    # small ResNet train loop, report rows/s and stall time.
    import argparse
    import tempfile
    import timeit

    parser = argparse.ArgumentParser(description="ImageNet workload smoke")
    parser.add_argument("--num-images", type=int, default=2048)
    parser.add_argument("--num-files", type=int, default=4)
    parser.add_argument("--num-epochs", type=int, default=2)
    parser.add_argument("--batch-size", type=int, default=256)
    parser.add_argument("--height", type=int, default=32)
    parser.add_argument("--width", type=int, default=32)
    parser.add_argument("--num-classes", type=int, default=10)
    args = parser.parse_args()

    import jax
    import jax.numpy as jnp
    import optax

    from ray_shuffling_data_loader_tpu.jax_dataset import JaxShufflingDataset
    from ray_shuffling_data_loader_tpu.models import resnet

    with tempfile.TemporaryDirectory() as tmpdir:
        filenames, _ = generate_imagenet_parquet(
            args.num_images, args.num_files, tmpdir, height=args.height,
            width=args.width, num_classes=args.num_classes)
        ds = JaxShufflingDataset(
            filenames, num_epochs=args.num_epochs, num_trainers=1,
            batch_size=args.batch_size, rank=0, drop_last=False,
            **imagenet_spec(args.height, args.width))
        cfg = resnet.resnet18_cifar(num_classes=args.num_classes)
        params = resnet.init(cfg, jax.random.key(0))
        opt = optax.sgd(1e-2)
        opt_state = opt.init(params)

        @jax.jit
        def step(params, opt_state, images, labels):
            loss, grads = jax.value_and_grad(lambda p: resnet.loss_fn(
                cfg, p, images.astype(jnp.float32) / 255.0,
                labels))(params)
            updates, opt_state = opt.update(grads, opt_state)
            return optax.apply_updates(params, updates), opt_state, loss

        start = timeit.default_timer()
        rows = 0
        from ray_shuffling_data_loader_tpu.plan import ir as plan_ir
        for epoch in plan_ir.epoch_range(0, args.num_epochs):
            ds.set_epoch(epoch)
            for (images,), labels in ds:
                params, opt_state, loss = step(params, opt_state, images,
                                               labels)
                rows += images.shape[0]
        jax.block_until_ready(loss)
        duration = timeit.default_timer() - start
        print(f"{rows} images in {duration:.2f}s "
              f"({rows / duration:,.0f} img/s), final loss "
              f"{float(loss):.4f}, stall "
              f"{ds.batch_wait_stats.summary()['total']:.2f}s")
