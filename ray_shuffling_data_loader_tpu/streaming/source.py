"""Stream sources: where unbounded input comes from.

A :class:`StreamSource` yields :class:`StreamEvent` records — arriving
Parquet objects with a monotone discovery index and a *stream-time*
timestamp — under the same determinism discipline the rest of the
pipeline runs on: the sequence of events a source yields is a pure
function of its construction arguments plus its journal, so a recovered
source re-yields the **identical** sequence and window assembly
(``streaming/window.py``) re-derives the identical epochs. That is the
ingest half of the exactly-once proof; the delivery half (watermark
journals + seq replay) is PR 5 and applies unchanged.

Two implementations:

:class:`DirectoryTailSource`
    Tails an arriving-file directory over the PR 14 storage plane.
    Directory listing order is NOT stable across filesystems (or across
    a crash), so discovery order is journaled: every newly discovered
    file appends a manifest record (``checkpoint.StreamJournal``), and
    a recovered tail replays the manifest FIRST — the file sequence a
    resumed pipeline sees is the journaled one, bit-for-bit, no matter
    what the directory says today.

:class:`SyntheticEventSource`
    A seeded, hermetic arrival process for tests and the 1-CPU bench:
    arrival times and event order are pure functions of
    ``(seed, event_index)`` via sha256 — the
    :class:`storage.source.SimulatedObjectStore` contract — so a fixed
    seed reproduces the byte-identical event sequence on any host.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
from typing import List, Optional, Sequence

from ray_shuffling_data_loader_tpu.utils.logger import setup_custom_logger

logger = setup_custom_logger(__name__)


@dataclasses.dataclass(frozen=True)
class StreamEvent:
    """One arrived object: a Parquet file entering the stream.

    ``index`` is the monotone discovery index (the event's identity in
    every journal); ``timestamp`` is STREAM time — the event's arrival
    instant in the source's own clock (file mtime for a directory tail,
    the seeded arrival process for synthetic events) — which is what
    watermarks and lateness are measured in, never wall clock."""

    index: int
    path: str
    timestamp: float
    size_bytes: int


class StreamSource:
    """The contract: :meth:`poll` returns newly arrived events, in a
    stable deterministic order, each exactly once per source instance.

    A RECOVERED instance (same construction arguments, same journal)
    re-yields the identical prefix before any new discoveries — callers
    that already sealed a prefix into windows skip it by event index
    (``WindowAssembler`` resume). ``exhausted`` turns True when the
    source knows no further events will ever arrive (a bounded synthetic
    stream); a directory tail never exhausts on its own."""

    def poll(self, now: Optional[float] = None) -> List[StreamEvent]:
        """Newly arrived events since the last poll. ``now`` advances
        sources with their own clock (synthetic stream time); sources
        paced by the outside world ignore it."""
        raise NotImplementedError

    @property
    def exhausted(self) -> bool:
        return False

    def close(self) -> None:
        """Release journal handles. Idempotent."""


class DirectoryTailSource(StreamSource):
    """Tail an arriving-file directory with journaled discovery order.

    Each :meth:`poll` lists ``directory``, admits not-yet-known files
    matching ``suffix`` in lexicographic order (stable *within* one
    poll), assigns them the next discovery indices, and appends one
    durable manifest record per file to the journal. On construction the
    manifest is replayed: journaled files are re-yielded first, in
    journal order, with their journaled timestamps/sizes — so recovery
    re-discovers the identical file sequence even if the directory now
    lists differently (or a file was compacted away).

    Files are only admitted once they are stat-able and non-empty;
    half-written files should be staged elsewhere and renamed in (the
    standard arrival discipline — rename is atomic on POSIX).
    """

    def __init__(self, directory: str,
                 journal_path: Optional[str] = None,
                 suffix: str = ".parquet"):
        from ray_shuffling_data_loader_tpu import checkpoint as ckpt
        self._directory = directory
        self._suffix = suffix
        self._known = set()  # paths already yielded (journal + live)
        self._next_index = 0
        self._replay: List[StreamEvent] = []
        self._journal = None
        if journal_path:
            for entry in ckpt.StreamJournal.load(journal_path):
                if entry.get("kind") != "file":
                    continue
                event = StreamEvent(index=int(entry["n"]),
                                    path=str(entry["path"]),
                                    timestamp=float(entry["ts"]),
                                    size_bytes=int(entry["size"]))
                self._replay.append(event)
                self._known.add(event.path)
                self._next_index = max(self._next_index, event.index + 1)
            self._journal = ckpt.StreamJournal(journal_path)
            if self._replay:
                logger.info(
                    "directory tail %s: recovered %d journaled events "
                    "(next index %d)", directory, len(self._replay),
                    self._next_index)

    def poll(self, now: Optional[float] = None) -> List[StreamEvent]:
        events, self._replay = self._replay, []
        try:
            names = sorted(os.listdir(self._directory))
        except FileNotFoundError:
            names = []
        for name in names:
            if not name.endswith(self._suffix):
                continue
            path = os.path.join(self._directory, name)
            if path in self._known:
                continue
            try:
                stat = os.stat(path)
            except OSError:
                continue  # vanished between list and stat
            if stat.st_size == 0:
                continue  # still being written; next poll
            event = StreamEvent(index=self._next_index, path=path,
                                timestamp=float(stat.st_mtime),
                                size_bytes=int(stat.st_size))
            if self._journal is not None:
                self._journal.append({"kind": "file", "n": event.index,
                                      "path": event.path,
                                      "ts": event.timestamp,
                                      "size": event.size_bytes})
            self._known.add(path)
            self._next_index += 1
            events.append(event)
        return events

    def close(self) -> None:
        if self._journal is not None:
            self._journal.close()
            self._journal = None


class SyntheticEventSource(StreamSource):
    """A deterministic seeded arrival process over a fixed file pool.

    Event ``i`` references ``files[i % len(files)]`` and arrives at a
    stream time built from seeded inter-arrival draws: each gap is
    ``mean_interarrival_s`` scaled by a jittered factor drawn as a pure
    function of ``(seed, i)`` via sha256 (the ``SimulatedObjectStore``
    idiom — no RNG state, bit-reproducible on any host). ``poll(now)``
    releases every not-yet-yielded event whose arrival time is <= ``now``;
    ``poll()`` with no clock releases exactly the next event — the
    drive-by-count mode tests and the bench use.

    ``total_events`` bounds the stream (``exhausted`` turns True after
    the last event); ``None`` streams forever.
    """

    def __init__(self, files: Sequence[str], seed: int = 0,
                 mean_interarrival_s: float = 1.0,
                 jitter_pct: float = 25.0,
                 total_events: Optional[int] = None,
                 start_time: float = 0.0):
        if not files:
            raise ValueError("SyntheticEventSource needs at least one file")
        self._files = [str(f) for f in files]
        self.seed = int(seed)
        self.mean_interarrival_s = float(mean_interarrival_s)
        self.jitter_pct = float(jitter_pct)
        self.total_events = total_events
        self.start_time = float(start_time)
        self._cursor = 0  # next event index to yield
        self._sizes = {}  # path -> cached size
        self._arrivals: List[float] = []  # memoized cumulative stream time

    def _draw(self, event_index: int) -> float:
        """Uniform [0, 1) from a stable hash — the faults.py idiom."""
        digest = hashlib.sha256(
            f"{self.seed}:arrival:{event_index}".encode()).digest()
        return int.from_bytes(digest[:8], "big") / float(1 << 64)

    def _gap(self, event_index: int) -> float:
        jitter = 1.0 + (self.jitter_pct / 100.0) * (
            2.0 * self._draw(event_index) - 1.0)
        return self.mean_interarrival_s * max(0.0, jitter)

    def arrival_time(self, event_index: int) -> float:
        """Stream time event ``event_index`` arrives — a pure function
        of ``(seed, event_index)`` (the prefix sums are memoized, not
        state: two instances at the same seed agree exactly)."""
        while len(self._arrivals) <= event_index:
            prev = self._arrivals[-1] if self._arrivals else self.start_time
            self._arrivals.append(prev + self._gap(len(self._arrivals)))
        return self._arrivals[event_index]

    def _size(self, path: str) -> int:
        size = self._sizes.get(path)
        if size is None:
            try:
                size = os.path.getsize(path)
            except OSError:
                size = 0
            self._sizes[path] = size
        return size

    def event(self, event_index: int) -> StreamEvent:
        """Event ``event_index``, pure in ``(seed, event_index)``."""
        path = self._files[event_index % len(self._files)]
        return StreamEvent(index=event_index, path=path,
                           timestamp=self.arrival_time(event_index),
                           size_bytes=self._size(path))

    def poll(self, now: Optional[float] = None) -> List[StreamEvent]:
        events: List[StreamEvent] = []
        while not self.exhausted:
            nxt = self.event(self._cursor)
            if now is not None and nxt.timestamp > now:
                break
            events.append(nxt)
            self._cursor += 1
            if now is None:
                break  # un-clocked poll: exactly the next event
        return events

    @property
    def exhausted(self) -> bool:
        return (self.total_events is not None
                and self._cursor >= self.total_events)
