"""Windowed epoch assembly: events accumulate, windows close, epochs run.

The one design decision that makes streaming cheap here: **a window is
just an epoch**. The :class:`WindowAssembler` admits events from a
:class:`streaming.source.StreamSource`, seals a window when the first
policy bound trips (file count, payload bytes, or stream-time age —
``RSDL_STREAM_WINDOW_*``), and compiles each sealed window to a normal
:class:`plan.ir.EpochSpec` whose plan carries streaming provenance
(``EpochPlan.window``). Everything downstream — the plan scheduler,
speculation, chaos, lineage recovery, sharded serving, tiered cache,
prefetch, and the PR 5 exactly-once replay matrix — applies unchanged,
because none of it ever cared where an epoch's file list came from.

Watermarks: the **ingest watermark** is the maximum stream timestamp
sealed into any closed window — monotone by construction, journaled
durably (``checkpoint.StreamJournal``) beside the delivery watermarks
so the two ends of the pipe are comparable. An event arriving with a
timestamp *behind* the ingest watermark is **late**: under the
``admit`` policy it rolls into the currently-open window (bounded
disorder, nothing lost — the window boundary moved past it, the data
did not); under ``quarantine`` it is excluded into a structured report
(the ``on_bad_file`` idiom) and counted.

Recovery: window assembly is deterministic in the admitted-event
sequence. A recovered assembler replays the ingest journal to find how
many events were already sealed (``resume_events``), skips exactly that
prefix of the source's (identically re-yielded) event sequence, and
continues — re-closing the same windows at the same boundaries.
"""

from __future__ import annotations

import dataclasses
import timeit
from typing import Any, Dict, Iterator, List, Optional

from ray_shuffling_data_loader_tpu.plan import ir as plan_ir
from ray_shuffling_data_loader_tpu.runtime import metrics as rt_metrics
from ray_shuffling_data_loader_tpu.runtime import policy as rt_policy
from ray_shuffling_data_loader_tpu.runtime import telemetry as rt_telemetry
from ray_shuffling_data_loader_tpu.streaming.source import (StreamEvent,
                                                            StreamSource)
from ray_shuffling_data_loader_tpu.utils.logger import setup_custom_logger

logger = setup_custom_logger(__name__)

#: ``window_late_policy`` vocabulary.
LATE_POLICIES = ("admit", "quarantine")


@dataclasses.dataclass(frozen=True)
class WindowPolicy:
    """When a window seals and what happens to late arrivals.

    A window seals at the FIRST bound hit; a bound of 0 is disabled.
    With every bound disabled ``max_files`` falls back to 1 — a window
    must be closable or the stream would buffer forever."""

    max_files: int = 4
    max_bytes: int = 0
    max_wait_s: float = 0.0
    late_policy: str = "admit"

    def __post_init__(self):
        if self.late_policy not in LATE_POLICIES:
            raise ValueError(
                f"late_policy {self.late_policy!r} not in {LATE_POLICIES}")

    @classmethod
    def resolve(cls, max_files: Optional[int] = None,
                max_bytes: Optional[int] = None,
                max_wait_s: Optional[float] = None,
                late_policy: Optional[str] = None) -> "WindowPolicy":
        """Resolve through the policy registry (component ``stream``,
        env ``RSDL_STREAM_WINDOW_*``); kwargs override."""
        def res(key, override):
            return rt_policy.resolve("stream", key, override=override)
        max_files = int(res("window_max_files", max_files))
        max_bytes = int(res("window_max_bytes", max_bytes))
        max_wait_s = float(res("window_max_wait_s", max_wait_s))
        if max_files <= 0 and max_bytes <= 0 and max_wait_s <= 0:
            max_files = 1
        return cls(max_files=max_files, max_bytes=max_bytes,
                   max_wait_s=max_wait_s,
                   late_policy=str(res("window_late_policy", late_policy)))

    def as_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class Window:
    """One sealed window: the events it admitted and its watermark."""

    index: int
    events: List[StreamEvent]
    ingest_watermark: float
    late_events: int = 0

    @property
    def filenames(self) -> List[str]:
        return [e.path for e in self.events]

    @property
    def size_bytes(self) -> int:
        return sum(e.size_bytes for e in self.events)

    def meta(self, policy: WindowPolicy) -> Dict[str, Any]:
        """The provenance block stamped onto the window's epoch plan."""
        return {"index": self.index,
                "events": [e.index for e in self.events],
                "ingest_watermark": self.ingest_watermark,
                "late_events": self.late_events,
                "policy": policy.as_dict()}

    def to_epoch_spec(self, epoch: int,
                      policy: WindowPolicy) -> plan_ir.EpochSpec:
        return plan_ir.EpochSpec(epoch=epoch,
                                 filenames=tuple(self.filenames),
                                 window=self.meta(policy))


class WindowAssembler:
    """Admit events, seal windows, journal the ingest watermark.

    ``first_epoch`` maps window 0 to an epoch index (a resumed stream
    continues the epoch numbering it left off at). ``journal`` is a
    :class:`checkpoint.StreamJournal`; every sealed window appends one
    durable watermark record, so :func:`resume_state` can tell a
    restarted pipeline how many events are already inside sealed
    windows and which window/epoch comes next."""

    def __init__(self, policy: Optional[WindowPolicy] = None,
                 journal=None, first_epoch: int = 0,
                 first_window: int = 0):
        self.policy = policy or WindowPolicy.resolve()
        self._journal = journal
        self._first_epoch = first_epoch
        self._window_index = first_window
        self._pending: List[StreamEvent] = []
        self._pending_late = 0
        self._opened_at: Optional[float] = None  # wall clock, close timing
        self.ingest_watermark = float("-inf")
        self.events_sealed = 0
        self.quarantined: List[StreamEvent] = []
        self._late_total = 0
        self._gauge_window = rt_metrics.gauge(
            "rsdl_stream_window", "index of the currently-open window")
        self._gauge_ingest = rt_metrics.gauge(
            "rsdl_stream_ingest_watermark",
            "stream time sealed into closed windows")
        self._counter_closed = rt_metrics.counter(
            "rsdl_stream_windows_closed_total", "windows sealed")
        self._counter_admitted = rt_metrics.counter(
            "rsdl_stream_events_admitted_total",
            "events admitted into windows")
        self._hist_close = rt_metrics.histogram(
            "rsdl_stream_window_close_seconds",
            "wall time from a window's first event to its seal")

    @property
    def window_index(self) -> int:
        """Index of the currently-open window."""
        return self._window_index

    @property
    def next_epoch(self) -> int:
        return self._first_epoch + self._window_index

    @property
    def pending_events(self) -> int:
        return len(self._pending)

    @property
    def late_events(self) -> int:
        """Late arrivals observed so far (both policies)."""
        return self._late_total

    def admit(self, event: StreamEvent) -> bool:
        """Admit one event into the open window. Returns False when the
        event was quarantined instead (late + ``quarantine`` policy)."""
        late = event.timestamp < self.ingest_watermark
        if late:
            self._late_total += 1
            rt_metrics.counter(
                "rsdl_stream_late_events_total",
                "events arriving behind the ingest watermark",
                policy=self.policy.late_policy).inc()
            rt_telemetry.record("stream_late_event", index=event.index,
                                policy=self.policy.late_policy)
            if self.policy.late_policy == "quarantine":
                self.quarantined.append(event)
                return False
            self._pending_late += 1
        if self._opened_at is None:
            self._opened_at = timeit.default_timer()
        self._pending.append(event)
        self._counter_admitted.inc()
        self._gauge_window.set(self._window_index)
        return True

    def should_close(self) -> bool:
        if not self._pending:
            return False
        policy = self.policy
        if policy.max_files > 0 and len(self._pending) >= policy.max_files:
            return True
        if policy.max_bytes > 0 and sum(
                e.size_bytes for e in self._pending) >= policy.max_bytes:
            return True
        if policy.max_wait_s > 0:
            newest = max(e.timestamp for e in self._pending)
            oldest = min(e.timestamp for e in self._pending)
            if newest - oldest >= policy.max_wait_s:
                return True
        return False

    def close_window(self) -> Optional[Window]:
        """Seal the open window (regardless of bounds — the force-close
        path for stream end); None when nothing is pending."""
        if not self._pending:
            return None
        events, self._pending = self._pending, []
        late, self._pending_late = self._pending_late, 0
        # Monotone: a window of purely-late admitted events cannot move
        # the watermark backwards.
        watermark = max(self.ingest_watermark,
                        max(e.timestamp for e in events))
        window = Window(index=self._window_index, events=events,
                        ingest_watermark=watermark, late_events=late)
        self.ingest_watermark = watermark
        self.events_sealed += len(events)
        self._window_index += 1
        if self._opened_at is not None:
            self._hist_close.observe(
                timeit.default_timer() - self._opened_at)
            self._opened_at = None
        if self._journal is not None:
            self._journal.append({
                "kind": "watermark", "window": window.index,
                "events": self.events_sealed,
                "watermark": window.ingest_watermark,
                "late": window.late_events,
                "files": len(window.events)})
        self._counter_closed.inc()
        self._gauge_ingest.set(watermark)
        rt_telemetry.record("stream_window_closed", window=window.index,
                            files=len(window.events), late=late)
        return window

    def maybe_close(self) -> Optional[Window]:
        return self.close_window() if self.should_close() else None

    def specs(self, source: StreamSource,
              max_windows: Optional[int] = None,
              clock_step_s: Optional[float] = None,
              poll_interval_s: float = 0.05
              ) -> Iterator[plan_ir.EpochSpec]:
        """THE window iterator: poll ``source``, admit, seal, yield one
        :class:`plan.ir.EpochSpec` per sealed window — the iterator
        :func:`shuffle.shuffle_epochs` drives. Ends when the source
        exhausts (remainder force-closed) or after ``max_windows``.

        ``clock_step_s`` advances a self-clocked source by that much
        stream time per poll; ``None`` polls un-clocked (event-at-a-time
        for synthetic sources, arrival-paced for directory tails).
        ``poll_interval_s`` paces empty polls of a live source — this
        generator legitimately BLOCKS between arrivals; the shuffle
        pipeline behind it keeps draining launched epochs meanwhile."""
        import time as _time
        now = None
        produced = 0
        while max_windows is None or produced < max_windows:
            if clock_step_s is not None:
                now = clock_step_s if now is None else now + clock_step_s
            events = source.poll(now)
            for event in events:
                self.admit(event)
                window = self.maybe_close()
                if window is not None:
                    yield window.to_epoch_spec(
                        self._first_epoch + window.index, self.policy)
                    produced += 1
                    if max_windows is not None and produced >= max_windows:
                        return
            if not events:
                if source.exhausted:
                    window = self.close_window()
                    if window is not None:
                        yield window.to_epoch_spec(
                            self._first_epoch + window.index, self.policy)
                    return
                if clock_step_s is None and poll_interval_s > 0:
                    _time.sleep(poll_interval_s)


def resume_state(journal_path: str) -> Dict[str, Any]:
    """What a restarted stream learns from its ingest journal:
    ``next_window`` (first unsealed window index), ``events_sealed``
    (events already inside sealed windows — the prefix of the source's
    re-yielded sequence to skip), and the journaled monotone
    ``ingest_watermark``."""
    from ray_shuffling_data_loader_tpu import checkpoint as ckpt
    state = {"next_window": 0, "events_sealed": 0,
             "ingest_watermark": float("-inf")}
    for entry in ckpt.StreamJournal.load(journal_path):
        if entry.get("kind") != "watermark":
            continue
        state["next_window"] = max(state["next_window"],
                                   int(entry["window"]) + 1)
        state["events_sealed"] = max(state["events_sealed"],
                                     int(entry["events"]))
        state["ingest_watermark"] = max(state["ingest_watermark"],
                                        float(entry["watermark"]))
    return state


def freeze_schedule(source: StreamSource,
                    policy: Optional[WindowPolicy] = None,
                    max_windows: Optional[int] = None,
                    first_epoch: int = 0,
                    journal=None) -> List[plan_ir.EpochSpec]:
    """Drain a bounded source into a frozen window schedule — the
    explicit per-epoch file list a supervised queue-server child
    (``multiqueue_service.serve_pipeline``, ``config["epochs"]``)
    re-derives identically on every restart."""
    assembler = WindowAssembler(policy=policy, journal=journal,
                                first_epoch=first_epoch)
    return list(assembler.specs(source, max_windows=max_windows))


def specs_to_dicts(specs: List[plan_ir.EpochSpec]) -> List[Dict[str, Any]]:
    """JSON-safe form of a frozen schedule (the supervised-child config
    block). ``tenant_id`` rides along only when set — pre-tenancy
    configs stay byte-identical (the EpochPlan.to_dict contract)."""
    out = []
    for s in specs:
        d = {"epoch": s.epoch, "filenames": list(s.filenames),
             "window": s.window}
        if s.tenant_id is not None:
            d["tenant_id"] = s.tenant_id
        if s.num_reducers is not None:
            d["num_reducers"] = int(s.num_reducers)
        out.append(d)
    return out


def specs_from_dicts(data) -> List[plan_ir.EpochSpec]:
    return [plan_ir.EpochSpec(
                epoch=int(d["epoch"]),
                filenames=tuple(str(f) for f in d["filenames"]),
                window=(dict(d["window"])
                        if d.get("window") is not None else None),
                tenant_id=d.get("tenant_id"),
                num_reducers=(int(d["num_reducers"])
                              if d.get("num_reducers") is not None
                              else None))
            for d in data]
