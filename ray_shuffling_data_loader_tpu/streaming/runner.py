"""The streaming driver: windows in, pipelined epochs out.

:class:`StreamingShuffleRunner` wires a :class:`streaming.source` into
the generalized shuffle driver (:func:`shuffle.shuffle_epochs`): window
N+1 assembles and shuffles WHILE window N serves — that is just the
``max_concurrent_epochs`` throttle doing what it always did, because a
window is an epoch. The runner adds the streaming bookkeeping the
static driver never needed:

- the **serve watermark** (stream time fully handed to the serving
  plane) advanced from the driver's ``on_epoch_done`` hook, and the
  ``rsdl_stream_watermark_lag_seconds`` gauge the ``watermark_lag``
  health detector watches;
- the ingest journal (``checkpoint.StreamJournal``) threaded through
  the assembler so a restarted run resumes window/epoch numbering and
  skips the already-sealed event prefix;
- :meth:`server_config` — the frozen window schedule a supervised
  queue-server child (``multiqueue_service.serve_pipeline``) re-derives
  identically on every ``kill -9`` restart, which is what carries the
  PR 5 exactly-once matrix across window boundaries.

Online training consumes the served stream exactly like epoch training
does (``JaxShufflingDataset`` in unbounded mode, or a remote queue
client) — the trainer's checkpoint/ack machinery needs no streaming
awareness at all.
"""

from __future__ import annotations

import contextlib
import dataclasses
import importlib
import timeit
from typing import Any, Callable, Dict, Optional

from ray_shuffling_data_loader_tpu import executor as ex
from ray_shuffling_data_loader_tpu import tenancy as rt_tenancy
from ray_shuffling_data_loader_tpu.runtime import metrics as rt_metrics
from ray_shuffling_data_loader_tpu.streaming import window as win
from ray_shuffling_data_loader_tpu.streaming.source import StreamSource
from ray_shuffling_data_loader_tpu.utils.logger import setup_custom_logger

# Lazy: shuffle.py imports numpy/pyarrow; keep the module importable in
# tool contexts that only need server_config plumbing.
_sh = None


def _shuffle_mod():
    global _sh
    if _sh is None:
        _sh = importlib.import_module(
            "ray_shuffling_data_loader_tpu.shuffle")
    return _sh


logger = setup_custom_logger(__name__)


class StreamingShuffleRunner:
    """Drive an unbounded (or bounded) stream through the shuffle.

    ``batch_consumer`` has the exact static-driver contract
    (``batch_consumer(rank, epoch, refs_or_None)``); epoch indices are
    ``first_epoch + window_index``, so a consumer reading queue
    ``plan.ir.queue_index(epoch, rank, num_trainers)`` works unchanged.

    ``journal_path`` enables ingest journaling AND recovery: a runner
    constructed over the same journal resumes at the next unsealed
    window, skipping the source's already-sealed event prefix (the
    source re-yields the identical sequence — the manifest/seed
    contract), so the resumed run's epochs continue the original
    numbering with zero events missed or re-sealed."""

    def __init__(self, source: StreamSource,
                 batch_consumer,
                 num_reducers: int,
                 num_trainers: int,
                 seed: int = 0,
                 max_concurrent_epochs: int = 2,
                 policy: Optional[win.WindowPolicy] = None,
                 journal_path: Optional[str] = None,
                 first_epoch: int = 0,
                 num_workers: Optional[int] = None,
                 max_windows: Optional[int] = None,
                 clock_step_s: Optional[float] = None,
                 on_window_served: Optional[Callable[[int], None]] = None,
                 tenant=None,
                 membership=None):
        from ray_shuffling_data_loader_tpu import checkpoint as ckpt
        # The stream's owning tenant: every window spec this runner
        # emits is stamped with its id (plan IR threading) and the
        # whole drive runs under its tenant_scope so storage-plane
        # attribution lands on the right ledger. None = ambient.
        self.tenant = (rt_tenancy.resolve(tenant)
                       if tenant is not None else None)
        self.source = source
        self.batch_consumer = batch_consumer
        self.num_reducers = num_reducers
        self.num_trainers = num_trainers
        self.seed = seed
        self.max_concurrent_epochs = max_concurrent_epochs
        self.num_workers = num_workers
        self.max_windows = max_windows
        self.clock_step_s = clock_step_s
        self._on_window_served = on_window_served
        # Elastic membership (membership/): when given a
        # MembershipManager, the world is re-read at every window seal —
        # the window boundary IS the resize point. Each spec's
        # num_reducers is retopologized for the live view
        # (membership.reducers_for_view), and the view id/ranks are
        # stamped into the window meta for provenance. The base
        # (num_reducers, world-size) pair is captured once at
        # construction so retopology is a pure function of the view.
        self.membership = membership
        self._base_world = (len(membership.current_view().ranks)
                            if membership is not None else 0)
        journal = None
        resumed = {"next_window": 0, "events_sealed": 0,
                   "ingest_watermark": float("-inf")}
        if journal_path:
            resumed = win.resume_state(journal_path)
            journal = ckpt.StreamJournal(journal_path)
        self.resume_skip_events = resumed["events_sealed"]
        self.assembler = win.WindowAssembler(
            policy=policy, journal=journal, first_epoch=first_epoch,
            first_window=resumed["next_window"])
        if resumed["ingest_watermark"] != float("-inf"):
            self.assembler.ingest_watermark = resumed["ingest_watermark"]
        self.serve_watermark = float("-inf")
        self._window_meta: Dict[int, Dict[str, Any]] = {}
        self.windows_served = 0
        self._gauge_serve = rt_metrics.gauge(
            "rsdl_stream_serve_watermark",
            "stream time fully handed to the serving plane")
        self._gauge_lag = rt_metrics.gauge(
            "rsdl_stream_watermark_lag_seconds",
            "ingest watermark minus serve watermark, stream seconds")

    # -- watermark bookkeeping -----------------------------------------

    def _observe_lag(self) -> None:
        ingest = self.assembler.ingest_watermark
        serve = self.serve_watermark
        if ingest == float("-inf"):
            return
        lag = 0.0 if serve == float("-inf") else max(0.0, ingest - serve)
        if serve == float("-inf"):
            # Nothing served yet: everything sealed is lag.
            lag = max(0.0, ingest - min(
                m["ingest_watermark"] for m in self._window_meta.values()
            )) if self._window_meta else 0.0
        self._gauge_lag.set(lag)

    def _on_epoch_done(self, epoch: int) -> None:
        meta = self._window_meta.pop(epoch, None)
        if meta is None:
            return
        self.windows_served += 1
        watermark = meta.get("ingest_watermark")
        if watermark is not None:
            self.serve_watermark = max(self.serve_watermark,
                                       float(watermark))
            self._gauge_serve.set(self.serve_watermark)
        self._observe_lag()
        if self._on_window_served is not None:
            self._on_window_served(int(meta["index"]))

    def _apply_view(self, spec):
        """Window-boundary resize: consult the membership view (after
        giving ``member_crash`` chaos a chance to kill ranks at this
        boundary) and retopologize the sealed window's reducer count for
        the live world. Exactly-once stays per-``row_offset`` — a window
        shuffled with a different reducer count delivers the same rows,
        just partitioned differently — so a resize never loses or
        duplicates a row."""
        manager = self.membership
        for rank in list(manager.current_view().ranks):
            manager.maybe_crash(spec.epoch, rank)
        view = manager.current_view()
        from ray_shuffling_data_loader_tpu import membership as mem
        reducers = mem.reducers_for_view(self.num_reducers,
                                         self._base_world, view)
        window = spec.window
        if window is not None:
            window = dict(window)
            window["view_id"] = view.view_id
            window["view_ranks"] = list(view.ranks)
        if reducers != self.num_reducers:
            logger.warning(
                "window %s (epoch %d): world resized to %d rank(s) "
                "(view %d) — retopologized to %d reducers",
                window.get("index"), spec.epoch, len(view.ranks),
                view.view_id, reducers)
        return dataclasses.replace(spec, num_reducers=reducers,
                                   window=window)

    def _specs(self):
        skip = self.resume_skip_events
        for spec in self.assembler.specs(self.source,
                                         max_windows=self.max_windows,
                                         clock_step_s=self.clock_step_s):
            if self.tenant is not None and spec.tenant_id is None:
                spec = dataclasses.replace(
                    spec, tenant_id=self.tenant.tenant_id)
            if self.membership is not None:
                spec = self._apply_view(spec)
            if spec.window is not None:
                self._window_meta[spec.epoch] = dict(spec.window)
            self._observe_lag()
            yield spec
        if skip:
            # Diagnostics only (the skip itself happened in admit order).
            logger.info("stream resume: %d already-sealed events were "
                        "skipped before window %d", skip,
                        self.assembler.window_index)

    def _skip_sealed_prefix(self) -> None:
        """Drop the source's first ``resume_skip_events`` events — the
        prefix the journal says is already inside sealed windows. The
        source re-yields the identical sequence (manifest/seed
        determinism), so dropping by count is dropping by identity."""
        remaining = self.resume_skip_events
        while remaining > 0:
            events = self.source.poll()
            if not events:
                if self.source.exhausted:
                    break
                continue
            if len(events) > remaining:
                # Partial batch: re-admit the tail through the assembler.
                for event in events[remaining:]:
                    self.assembler.admit(event)
                remaining = 0
                break
            remaining -= len(events)

    # -- driving -------------------------------------------------------

    def run(self) -> Dict[str, Any]:
        """Run the stream to completion (bounded source or
        ``max_windows``) and return a summary dict. Unbounded streams
        run until the source exhausts — callers wanting detachment use
        :meth:`run_in_background`."""
        sh = _shuffle_mod()
        start = timeit.default_timer()
        self._skip_sealed_prefix()
        scope = (rt_tenancy.tenant_scope(self.tenant)
                 if self.tenant is not None
                 else contextlib.nullcontext())
        with scope:
            duration = sh.shuffle_epochs(
                self._specs(), self.batch_consumer, self.num_reducers,
                self.num_trainers,
                max_concurrent_epochs=self.max_concurrent_epochs,
                seed=self.seed, num_workers=self.num_workers,
                file_cache=None, epochs_hint=None,
                on_epoch_done=self._on_epoch_done)
        return {
            "duration_s": timeit.default_timer() - start,
            "shuffle_s": duration,
            "windows_closed": self.assembler.window_index,
            "windows_served": self.windows_served,
            "events_sealed": self.assembler.events_sealed,
            "late_events": self.assembler.late_events,
            "quarantined": len(self.assembler.quarantined),
            "ingest_watermark": self.assembler.ingest_watermark,
            "serve_watermark": self.serve_watermark,
        }

    def run_in_background(self) -> ex.TaskRef:
        """The :func:`shuffle.run_shuffle_in_background` idiom: the
        whole streaming drive on a dedicated single-worker executor."""
        driver_pool = ex.Executor(num_workers=1,
                                  thread_name_prefix="rsdl-stream")

        def _run():
            try:
                return self.run()
            finally:
                driver_pool.shutdown(wait_for_tasks=False)

        return driver_pool.submit(_run)

    def close(self) -> None:
        self.source.close()


def server_config(source: StreamSource,
                  num_trainers: int,
                  num_reducers: int,
                  journal_path: str,
                  seed: int = 0,
                  policy: Optional[win.WindowPolicy] = None,
                  max_windows: Optional[int] = None,
                  max_concurrent_epochs: int = 2,
                  ingest_journal_path: Optional[str] = None,
                  tenant_id: Optional[str] = None,
                  **extra: Any) -> Dict[str, Any]:
    """Build the supervised queue-server config for a BOUNDED stream:
    drain ``source`` into a frozen window schedule (journaling ingest
    watermarks when ``ingest_journal_path`` is given) and emit the
    ``multiqueue_service.serve_pipeline`` config whose ``epochs`` block
    carries it. The schedule is pure data, so every restarted
    incarnation re-derives the identical epochs — the streaming leg of
    the kill -9 matrix rides entirely on the PR 5 machinery."""
    from ray_shuffling_data_loader_tpu import checkpoint as ckpt
    journal = (ckpt.StreamJournal(ingest_journal_path)
               if ingest_journal_path else None)
    specs = win.freeze_schedule(source, policy=policy,
                                max_windows=max_windows, journal=journal)
    if journal is not None:
        journal.close()
    if tenant_id is not None:
        specs = [dataclasses.replace(s, tenant_id=tenant_id)
                 if s.tenant_id is None else s for s in specs]
    config = {
        "epochs": win.specs_to_dicts(specs),
        "num_trainers": int(num_trainers),
        "num_reducers": int(num_reducers),
        "seed": int(seed),
        "max_concurrent_epochs": int(max_concurrent_epochs),
        "journal_path": journal_path,
    }
    config.update(extra)
    return config
