"""streaming/: continuous ingestion and windowed shuffle over unbounded
input.

Everything built through PR 14 is epoch-over-static-file-list: one job,
one fixed set of Parquet files, epochs counted up front. This package
spends the substrate those PRs laid down — absolute ``row_offset``
accounting, delivered-watermark journals and exactly-once replay (PR 5),
the epoch-plan IR (PR 9), the sharded serving plane (PR 10) and the
storage plane (PR 14) — on the last missing production scenario:
unbounded input, watermark-driven epoch boundaries, and online training
on fresh data.

The design is deliberately thin: a **window is just an epoch**. Events
(arriving files) accumulate into a window (``window.py``); a closed
window compiles to a normal :class:`plan.ir.EpochPlan` with streaming
provenance stamped on it — so the scheduler, speculation, chaos,
lineage recovery, sharded serving, tiered cache and prefetch all apply
unchanged, and the PR 5 exactly-once matrix covers window boundaries
for free (the resume math is epoch-generic).

- :mod:`streaming.source` — where events come from: the
  :class:`StreamSource` contract, a manifest-journaled
  :class:`DirectoryTailSource`, and the hermetic seeded
  :class:`SyntheticEventSource`.
- :mod:`streaming.window` — window policies (count / byte / watermark
  bounds, ``RSDL_STREAM_WINDOW_*``), late-arrival handling
  (admit-to-next-window | quarantine), the journaled monotone ingest
  watermark, and compilation of closed windows to epoch specs.
- :mod:`streaming.runner` — :class:`StreamingShuffleRunner`: pipelines
  window N+1 assembly/shuffle under window N serving (the
  ``max_concurrent_epochs`` throttle, unchanged), plus the frozen-
  schedule config handed to supervised queue-server processes.
"""

from ray_shuffling_data_loader_tpu.streaming.source import (  # noqa: F401
    DirectoryTailSource, StreamEvent, StreamSource, SyntheticEventSource)
from ray_shuffling_data_loader_tpu.streaming.window import (  # noqa: F401
    Window, WindowAssembler, WindowPolicy, freeze_schedule,
    specs_from_dicts, specs_to_dicts)
from ray_shuffling_data_loader_tpu.streaming.runner import (  # noqa: F401
    StreamingShuffleRunner)
