"""Elastic world membership: journaled views, failure-detected shrink,
boundary grow, and generation fencing.

The reference (and this repo until now) froze the world at construction
time: ``num_reducers x num_trainers`` chosen at ``shuffle()`` time, and
``parallel/transport.py`` dialing a flat all-to-all over a fixed
``world = len(addresses)``. PR 5's leases let a *consumer* die without
wedging the server, but nothing let a rank leave, rejoin, or join — a
dead reducer host stalled the epoch until retry budgets exhausted.

This package makes world composition a first-class, journaled,
crash-recoverable input to the plan:

- :class:`MembershipView` — one immutable world composition:
  ``(view_id, ranks, incarnations)``. The *rank set* is the reducer
  hosts; the per-rank **incarnation** counts process generations (a
  rank that dies and rejoins comes back at incarnation+1, which is what
  lets the transport fence its zombie predecessor's frames).
- :func:`apply_event` — the ONE pure transition function. Every view is
  a fold of events over the bootstrap view, with no wall clock and no
  dict-order dependence, so a journal replays bit-identically.
- :class:`MembershipJournal` — the crc'd append-only JSONL discipline of
  ``checkpoint.WatermarkJournal`` (torn tails skipped, atomic compact)
  applied to view changes; :func:`replay` re-derives every journaled
  view through :func:`apply_event` and raises on any byte divergence —
  recovery and audit in one mechanism (the admission-journal recipe).
- :class:`MembershipManager` — the runtime hub: owns the current view,
  journals transitions, fans them out to listeners (elastic runners,
  the queue server's lease sweep, transports), and emits the
  ``member_*`` telemetry/metric vocabulary.

Resize semantics (consumed by ``membership/elastic.py`` and
``streaming/runner.py``): on ``member_down`` the CURRENT epoch completes
degraded — the dead rank's reducers are re-placed onto survivors
(``plan.ir.reduce_placement`` over the shrunken rank set) and their
outputs regenerated from ``(seed, epoch, reducer)`` lineage, exactly
once against the delivery ledger; on ``member_join`` the world grows at
the next epoch (batch) or window seal (streaming) — seal window N on the
old view, open N+1 on the new one, zero replay. Placement never changes
*content*: a reducer output is a pure function of its lineage key, so a
resized run's merged stream is bit-identical to the fixed-world run.
"""

from __future__ import annotations

import dataclasses
import os
import tempfile
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ray_shuffling_data_loader_tpu import checkpoint as ckpt
from ray_shuffling_data_loader_tpu.runtime import metrics as rt_metrics
from ray_shuffling_data_loader_tpu.runtime import telemetry as rt_telemetry
from ray_shuffling_data_loader_tpu.utils.logger import setup_custom_logger

logger = setup_custom_logger(__name__)

#: Journaled event kinds. ``bootstrap``/``snapshot`` carry a whole view
#: (journal base lines); ``down``/``join`` are the deltas folded over it.
EVENT_KINDS = ("bootstrap", "snapshot", "down", "join")


@dataclasses.dataclass(frozen=True)
class MembershipEvent:
    """One world transition. ``rank``/``incarnation`` are meaningful for
    ``down``/``join``; base records (``bootstrap``/``snapshot``) use
    rank -1. ``reason`` is free text for humans and telemetry (it is
    inside the crc'd line, so it replays byte-identically too)."""

    kind: str
    rank: int = -1
    incarnation: int = 0
    reason: str = ""

    def to_dict(self) -> dict:
        return {"kind": self.kind, "rank": self.rank,
                "incarnation": self.incarnation, "reason": self.reason}

    @classmethod
    def from_dict(cls, data: dict) -> "MembershipEvent":
        return cls(kind=data["kind"], rank=int(data["rank"]),
                   incarnation=int(data["incarnation"]),
                   reason=data.get("reason", ""))


@dataclasses.dataclass(frozen=True)
class MembershipView:
    """One immutable world composition.

    ``ranks`` is the sorted live rank set; ``incarnations`` maps EVERY
    rank ever seen (live or not) to its latest process generation —
    kept for departed ranks so a rejoin resumes at the next generation
    and the transport can fence the dead generation's frames.
    """

    view_id: int
    ranks: Tuple[int, ...]
    incarnations: Tuple[Tuple[int, int], ...]  # sorted (rank, incarnation)

    def live(self, rank: int) -> bool:
        return rank in self.ranks

    def incarnation(self, rank: int) -> int:
        for r, inc in self.incarnations:
            if r == rank:
                return inc
        return 0

    def to_dict(self) -> dict:
        return {"view_id": self.view_id, "ranks": list(self.ranks),
                "incarnations": [[r, i] for r, i in self.incarnations]}

    @classmethod
    def from_dict(cls, data: dict) -> "MembershipView":
        return cls(view_id=int(data["view_id"]),
                   ranks=tuple(int(r) for r in data["ranks"]),
                   incarnations=tuple((int(r), int(i))
                                      for r, i in data["incarnations"]))

    @classmethod
    def bootstrap(cls, ranks: Sequence[int],
                  incarnations: Optional[Dict[int, int]] = None
                  ) -> "MembershipView":
        ranks = tuple(sorted(set(int(r) for r in ranks)))
        incarnations = incarnations or {}
        pairs = tuple(sorted((r, int(incarnations.get(r, 0)))
                             for r in ranks))
        return cls(view_id=0, ranks=ranks, incarnations=pairs)


def apply_event(view: MembershipView,
                event: MembershipEvent) -> MembershipView:
    """THE pure view-transition function: ``(view, event) -> view``.

    No wall clock, no randomness, no dict-order dependence — a journal
    is a fold of its events over the bootstrap view, and :func:`replay`
    re-runs the fold to prove the journal. Events that would not change
    the world (downing an absent rank, a join that is not a newer
    generation of the rank) return ``view`` UNCHANGED — the manager
    never journals those, so replay never sees them either.
    """
    if event.kind not in EVENT_KINDS:
        raise ValueError(f"unknown membership event kind {event.kind!r}")
    if event.kind in ("bootstrap", "snapshot"):
        raise ValueError(
            f"{event.kind} records carry their own view; apply_event "
            "folds only down/join deltas")
    incarnations = dict(view.incarnations)
    if event.kind == "down":
        if event.rank not in view.ranks:
            return view
        ranks = tuple(r for r in view.ranks if r != event.rank)
        pairs = tuple(sorted(incarnations.items()))
        return MembershipView(view.view_id + 1, ranks, pairs)
    # join: only a strictly newer generation of a live rank (a restart
    # the detector never saw die), or any generation of an absent rank
    # at >= its last known incarnation, changes the world.
    known = incarnations.get(event.rank, -1) if event.rank in view.ranks \
        else incarnations.get(event.rank, 0) - 1
    if event.incarnation <= known:
        return view
    incarnations[event.rank] = event.incarnation
    ranks = tuple(sorted(set(view.ranks) | {event.rank}))
    pairs = tuple(sorted(incarnations.items()))
    return MembershipView(view.view_id + 1, ranks, pairs)


def next_incarnation(view: MembershipView, rank: int) -> int:
    """The generation a (re)joining ``rank`` must announce: one past its
    latest known incarnation (0 for a never-seen rank)."""
    for r, inc in view.incarnations:
        if r == rank:
            return inc + 1
    return 0


class MembershipJournal:
    """Crc'd append-only journal of membership view changes.

    Each line is ``{"event": ..., "view": ...}`` in the shared
    :func:`checkpoint.crc_line` discipline: the recorded view is the
    RESULT of folding the event over the previous line's view, which is
    what makes the file self-verifying — :func:`replay` re-runs the fold
    and any divergence (tamper, version skew, an unjournaled transition)
    raises. The first line is always a base record (``bootstrap``, or
    ``snapshot`` after :meth:`compact`) carrying the whole view, so
    replay needs no out-of-band initial state.

    ``path=None`` keeps the journal in memory (unit tests, ephemeral
    worlds); with a path every line is flushed + fsync'd before the
    transition is visible, so a crashed coordinator restarts into the
    exact world it last advertised.
    """

    def __init__(self, path: Optional[str] = None):
        self._path = path
        self._lock = threading.Lock()
        self._file = None
        self._lines: List[str] = []

    @property
    def path(self) -> Optional[str]:
        return self._path

    @staticmethod
    def encode(event: MembershipEvent, view: MembershipView) -> str:
        return ckpt.crc_line({"event": event.to_dict(),
                              "view": view.to_dict()})

    def record(self, event: MembershipEvent, view: MembershipView) -> None:
        line = self.encode(event, view)
        with self._lock:
            self._lines.append(line)
            if self._path is not None:
                if self._file is None:
                    directory = os.path.dirname(os.path.abspath(self._path))
                    os.makedirs(directory, exist_ok=True)
                    self._file = open(self._path, "a", encoding="utf-8")
                self._file.write(line + "\n")
                self._file.flush()
                os.fsync(self._file.fileno())

    def journal_bytes(self) -> bytes:
        """The journal as emitted (the replay-comparison target)."""
        with self._lock:
            return "".join(line + "\n" for line in self._lines).encode()

    @classmethod
    def load(cls, path: str) -> List[dict]:
        """Every intact ``{"event", "view"}`` record in append order; a
        torn TAIL line (crash mid-write) is skipped with a warning, but
        an unreadable line with intact lines after it is corruption and
        raises — an interior gap would silently rewrite history."""
        records: List[dict] = []
        bad: Optional[Tuple[int, str]] = None
        if not os.path.exists(path):
            return records
        with open(path, encoding="utf-8") as f:
            for lineno, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    entry = ckpt.parse_crc_line(line)
                    record = {"event": MembershipEvent.from_dict(
                                  entry["event"]),
                              "view": MembershipView.from_dict(
                                  entry["view"]),
                              "line": line}
                except (ValueError, KeyError, TypeError) as e:
                    if bad is not None:
                        raise ValueError(
                            f"membership journal {path}: multiple "
                            f"unreadable lines ({bad[0]}: {bad[1]}; "
                            f"{lineno}: {e}) — corruption, not a torn "
                            "tail")
                    bad = (lineno, str(e))
                    continue
                if bad is not None:
                    raise ValueError(
                        f"membership journal {path}: line {bad[0]} "
                        f"unreadable ({bad[1]}) but line {lineno} is "
                        "intact — interior corruption, not a torn tail")
                records.append(record)
        if bad is not None:
            logger.warning(
                "membership journal %s line %d unreadable (%s); skipping "
                "(torn tail from a crash is expected)", path, bad[0],
                bad[1])
        return records

    def compact(self) -> None:
        """Rewrite the journal as ONE snapshot record of the latest
        view — atomic tmp + fsync + rename (the WatermarkJournal
        discipline), run at coordinator restart so the append-only file
        cannot grow unboundedly across churn."""
        assert self._path is not None, "in-memory journals need no compact"
        records = self.load(self._path)
        if not records:
            return
        view = records[-1]["view"]
        line = self.encode(MembershipEvent(kind="snapshot",
                                           reason="compact"), view)
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None
            directory = os.path.dirname(os.path.abspath(self._path))
            os.makedirs(directory, exist_ok=True)
            fd, tmp_path = tempfile.mkstemp(dir=directory, suffix=".tmp")
            try:
                with os.fdopen(fd, "w") as f:
                    f.write(line + "\n")
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp_path, self._path)
                dir_fd = os.open(directory, os.O_RDONLY)
                try:
                    os.fsync(dir_fd)
                finally:
                    os.close(dir_fd)
            except BaseException:
                if os.path.exists(tmp_path):
                    os.remove(tmp_path)
                raise
            self._lines = [line]

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None


def replay(path: str) -> MembershipView:
    """Rebuild the latest view from a journal and PROVE the rebuild:
    every ``down``/``join`` record's view must equal
    ``apply_event(previous_view, event)`` — re-encoded byte-identically
    against the journaled line — and the journal must begin with a base
    record. Any divergence raises ``ValueError`` (tamper, corruption,
    or version skew in the transition function). Returns the verified
    latest view."""
    records = MembershipJournal.load(path)
    if not records:
        raise ValueError(f"membership journal {path} has no records")
    first = records[0]
    if first["event"].kind not in ("bootstrap", "snapshot"):
        raise ValueError(
            f"membership journal {path} does not begin with a "
            f"bootstrap/snapshot record (got {first['event'].kind!r})")
    view = first["view"]
    for index, record in enumerate(records[1:], 2):
        event = record["event"]
        if event.kind in ("bootstrap", "snapshot"):
            raise ValueError(
                f"membership journal {path} record {index}: base record "
                "after the journal head (history rewrite)")
        derived = apply_event(view, event)
        rederived = MembershipJournal.encode(event, derived)
        if rederived != record["line"]:
            raise ValueError(
                f"membership journal {path} record {index} diverged on "
                f"replay: event {event.to_dict()} over view "
                f"{view.view_id} re-derives view {derived.to_dict()}, "
                "journal disagrees (tamper, corruption, or transition "
                "version skew)")
        if derived == view:
            raise ValueError(
                f"membership journal {path} record {index}: journaled "
                f"no-op event {event.to_dict()} (the manager never "
                "journals unchanged views)")
        view = derived
    return view


class MembershipManager:
    """The runtime membership hub: current view + journal + fan-out.

    Transitions come from the failure detector (``member_down``), from
    join announcements (``member_join``), or from chaos
    (``member_crash`` via the runners). Each one folds through
    :func:`apply_event`, is journaled, emits telemetry + metrics, and is
    delivered to every listener ``cb(event, view)`` — the elastic
    runner's resize trigger, the queue server's view-aware lease sweep,
    and the transport's fence all hang off this one callback list.
    """

    def __init__(self, ranks: Sequence[int],
                 journal_path: Optional[str] = None,
                 incarnations: Optional[Dict[int, int]] = None):
        self._lock = threading.Lock()
        self._view = MembershipView.bootstrap(ranks, incarnations)
        self._journal = MembershipJournal(journal_path)
        self._listeners: List[Callable[[MembershipEvent, MembershipView],
                                       None]] = []
        self._journal.record(MembershipEvent(kind="bootstrap",
                                             reason="initial world"),
                             self._view)
        self._suspects: set = set()
        self._export(self._view)

    # -- state ---------------------------------------------------------

    def current_view(self) -> MembershipView:
        with self._lock:
            return self._view

    @property
    def journal(self) -> MembershipJournal:
        return self._journal

    def add_listener(self, callback: Callable[[MembershipEvent,
                                               MembershipView],
                                              None]) -> None:
        with self._lock:
            self._listeners.append(callback)

    # -- transitions ---------------------------------------------------

    def member_down(self, rank: int, reason: str = "") -> MembershipView:
        """A rank left the world (failure detector verdict, lease
        expiry, or an operator's drain). Idempotent: downing an absent
        rank is a no-op (the flapping-detector case)."""
        return self._transition(MembershipEvent(
            kind="down", rank=int(rank),
            incarnation=self.current_view().incarnation(rank),
            reason=reason))

    def member_join(self, rank: int, incarnation: Optional[int] = None,
                    reason: str = "") -> MembershipView:
        """A rank (re)joined. ``incarnation=None`` assigns the next
        generation for the rank — the number the joining process must
        announce on its transport so pre-death frames stay fenced."""
        with self._lock:
            view = self._view
        if incarnation is None:
            incarnation = next_incarnation(view, int(rank))
        return self._transition(MembershipEvent(
            kind="join", rank=int(rank), incarnation=int(incarnation),
            reason=reason))

    def member_suspect(self, rank: int, flap: bool = False) -> None:
        """Detector soft verdict: telemetry + gauge only — suspicion is
        not a view change (hysteresis lives in the detector)."""
        with self._lock:
            self._suspects.add(int(rank))
            count = len(self._suspects)
        if flap:
            rt_metrics.counter(
                "rsdl_member_flaps_total",
                "suspect->alive->suspect flaps absorbed by "
                "hysteresis").inc()
            rt_telemetry.record("member_flap", task=int(rank))
        else:
            rt_metrics.counter(
                "rsdl_member_suspects_total",
                "ranks marked suspect by the failure detector").inc()
            rt_telemetry.record("member_suspect", task=int(rank))
        rt_metrics.gauge("rsdl_member_suspect",
                         "ranks currently suspect").set(count)

    def member_alive(self, rank: int) -> None:
        """Detector cleared a suspicion (the rank's heartbeats resumed)."""
        with self._lock:
            self._suspects.discard(int(rank))
            count = len(self._suspects)
        rt_metrics.gauge("rsdl_member_suspect",
                         "ranks currently suspect").set(count)

    def maybe_crash(self, epoch: int, rank: int) -> bool:
        """The ``member_crash`` chaos site, checked by runners once per
        ``(epoch, rank)`` key: when the active spec matches, the rank is
        downed through the normal transition (so detection, journaling
        and resize all exercise their real paths) and the caller
        simulates the process death. Returns True when the crash fired."""
        from ray_shuffling_data_loader_tpu.runtime import faults as rt_faults
        try:
            rt_faults.inject("member_crash", epoch=epoch, task=rank)
        except rt_faults.InjectedFault as fault:
            self.member_down(rank, reason=f"member_crash chaos "
                                          f"({fault.rule})")
            return True
        return False

    def _transition(self, event: MembershipEvent) -> MembershipView:
        with self._lock:
            view = apply_event(self._view, event)
            if view == self._view:
                return view  # no-op: never journaled, never fanned out
            self._view = view
            self._journal.record(event, view)
            if event.kind == "down":
                self._suspects.discard(event.rank)
            listeners = list(self._listeners)
        logger.warning(
            "membership: %s rank %d (incarnation %d) -> view %d with "
            "ranks %s%s", event.kind, event.rank, event.incarnation,
            view.view_id, list(view.ranks),
            f" ({event.reason})" if event.reason else "")
        rt_telemetry.record(f"member_{event.kind}", task=event.rank,
                            view=view.view_id,
                            incarnation=event.incarnation,
                            reason=event.reason)
        rt_metrics.counter(
            "rsdl_member_transitions_total",
            "membership view transitions by kind",
            kind=event.kind).inc()
        if event.kind == "down":
            rt_metrics.counter("rsdl_member_downs_total",
                               "ranks removed from the world").inc()
        elif event.kind == "join":
            rt_metrics.counter("rsdl_member_joins_total",
                               "ranks added to the world").inc()
        self._export(view)
        for callback in listeners:
            callback(event, view)
        return view

    def _export(self, view: MembershipView) -> None:
        rt_metrics.gauge("rsdl_member_view_id",
                         "current membership view id").set(view.view_id)
        rt_metrics.gauge("rsdl_member_live",
                         "live ranks in the current view").set(
            len(view.ranks))
        for rank, inc in view.incarnations:
            rt_metrics.gauge("rsdl_member_incarnation",
                             "latest process generation per rank",
                             rank=str(rank)).set(inc)
        rt_metrics.gauge(
            "rsdl_member_last_transition_unixtime",
            "wall-clock time of the last view transition").set(
            time.time())

    def close(self) -> None:
        self._journal.close()


def reducers_for_view(base_reducers: int, base_world: int,
                      view: MembershipView) -> int:
    """The reducer count a streaming window opened on ``view`` should
    run: the bootstrap ratio ``base_reducers / base_world`` scaled to
    the live rank count (floor 1). Batch mode never calls this — there
    the reducer count is fixed and only *placement* moves, which is
    what keeps a resized batch run bit-identical; a streaming window is
    free to retopologize because exactly-once is per-``row_offset``,
    not per-reducer."""
    if base_world <= 0:
        raise ValueError("base_world must be > 0")
    per_rank = max(1, round(base_reducers / base_world))
    return max(1, per_rank * len(view.ranks))


__all__ = ["MembershipEvent", "MembershipView", "MembershipJournal",
           "MembershipManager", "apply_event", "next_incarnation",
           "replay", "reducers_for_view", "EVENT_KINDS"]
