"""Elastic shuffle execution: resize-as-plan-rewrite over a live view.

The fixed-world runner assumes every rank that started an epoch finishes
it. This runner makes world composition an *input*: each epoch opens by
reading the :class:`membership.MembershipManager`'s current view, places
the (fixed) reducer set over the live ranks with
``plan_ir.reduce_placement``, and runs one worker per live rank. Because
every reducer output is a pure function of ``(seed, epoch, reducer)``
(``shuffle.recompute_reducer_output`` — the same lineage contract the
spill tier's corruption recovery uses), moving a reducer to a different
rank moves *where* it is computed, never *what* it contains: an elastic
run's merged stream is bit-identical to the fixed-world run's.

Shrink (``member_down`` mid-epoch): the dead rank's undelivered reducers
are re-placed onto the survivors (deterministic ``route_slices``
rebalance) and recomputed from lineage. A driver-side **delivery
ledger** keyed ``(epoch, reducer)`` makes delivery exactly-once — a
reducer the dead rank already delivered is never recomputed, and a
racing duplicate is dropped, so the stream has zero missed and zero
duplicated rows. Grow (``member_join``): the joined rank takes effect at
the next epoch boundary — the current epoch's placement is immutable, so
a join never causes replay.

The ``member_crash`` chaos site fires here, through
``MembershipManager.maybe_crash``, at the moment a rank's worker picks
up its next reducer — the mid-epoch kill the dryrun and bench elastic
legs drive.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from ray_shuffling_data_loader_tpu.membership import MembershipManager
from ray_shuffling_data_loader_tpu.plan import ir as plan_ir
from ray_shuffling_data_loader_tpu.runtime import telemetry as rt_telemetry
from ray_shuffling_data_loader_tpu.utils.logger import setup_custom_logger

logger = setup_custom_logger(__name__)


class ElasticShuffleRunner:
    """Run shuffle epochs over an elastic world.

    Args:
        filenames: epoch input files (identical across epochs; per-epoch
            reshuffle comes from the seed/epoch lineage, as everywhere
            else in the repo).
        num_reducers: the FIXED reducer count — elasticity moves
            placement, not partitioning, which is what keeps the merged
            stream bit-identical across resizes.
        seed: shuffle seed (lineage root).
        manager: the membership manager whose journaled view drives
            placement. ``maybe_crash`` is consulted per pickup so a
            ``member_crash:rankN`` chaos rule kills that rank mid-epoch.
    """

    def __init__(self, filenames: Sequence[str], num_reducers: int,
                 seed: int, manager: MembershipManager,
                 map_transform: Optional[Callable] = None,
                 reduce_transform: Optional[Callable] = None,
                 on_bad_file: str = "raise"):
        if num_reducers < 1:
            raise ValueError("num_reducers must be >= 1")
        self.filenames = list(filenames)
        self.num_reducers = int(num_reducers)
        self.seed = int(seed)
        self.manager = manager
        self.map_transform = map_transform
        self.reduce_transform = reduce_transform
        self.on_bad_file = on_bad_file
        #: Stats of the most recent :meth:`run_epoch` — the bench
        #: elastic leg's raw numbers.
        self.last_stats: Dict[str, float] = {}

    # -- one epoch -----------------------------------------------------

    def run_epoch(self, epoch: int) -> List:
        """Run one epoch; returns reducer-indexed outputs (pa.Tables).

        Degraded completion: if a rank dies mid-epoch (detected here via
        the ``member_crash`` site, or already recorded in the view by an
        external failure detector), its undelivered reducers are
        rebalanced over the survivors and recomputed from lineage; the
        epoch completes with every reducer delivered exactly once.
        """
        view = self.manager.current_view()
        live = list(view.ranks)
        placement = plan_ir.reduce_placement(self.num_reducers, live)
        queues: Dict[int, collections.deque] = {
            rank: collections.deque() for rank in live}
        for reducer in range(self.num_reducers):
            queues[placement[reducer]].append(reducer)

        lock = threading.Lock()
        ledger: Dict[int, object] = {}       # reducer -> delivered table
        orphans: collections.deque = collections.deque()
        dead: set = set()
        death_times: List[float] = []
        stats = {"epoch": epoch, "view_id": view.view_id,
                 "live_ranks": len(live), "recomputed": 0,
                 "duplicates_dropped": 0, "resize_stall_ms": 0.0}

        # Late import: the package root re-exports a `shuffle` FUNCTION,
        # so the module must be imported by its dotted name.
        from ray_shuffling_data_loader_tpu.shuffle import (
            recompute_reducer_output)

        def compute(reducer: int):
            return recompute_reducer_output(
                self.filenames, self.num_reducers, self.seed, epoch,
                reducer, self.map_transform, self.reduce_transform,
                self.on_bad_file)

        def deliver(reducer: int, table) -> None:
            with lock:
                if reducer in ledger:
                    # Exactly-once: a racing recompute of a reducer the
                    # dead rank in fact delivered is dropped here.
                    stats["duplicates_dropped"] += 1
                    return
                ledger[reducer] = table

        def worker(rank: int) -> None:
            while True:
                with lock:
                    if rank in dead:
                        return
                    if queues[rank]:
                        reducer = queues[rank].popleft()
                        recovered = False
                    elif orphans:
                        reducer = orphans.popleft()
                        recovered = True
                    else:
                        return
                if self.manager.maybe_crash(epoch, rank):
                    # The rank died holding `reducer` undelivered: it
                    # goes back to the pool with the rest of the rank's
                    # queue for the survivors to drain.
                    with lock:
                        dead.add(rank)
                        orphans.append(reducer)
                        orphans.extend(queues[rank])
                        queues[rank].clear()
                        death_times.append(time.monotonic())
                    return
                deliver(reducer, compute(reducer))
                if recovered:
                    with lock:
                        stats["recomputed"] += 1

        start = time.monotonic()
        threads = [threading.Thread(target=worker, args=(rank,),
                                    daemon=True,
                                    name=f"rsdl-elastic-r{rank}")
                   for rank in live]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

        # Degraded completion backstop: every rank died (or died after
        # the survivors had already drained and exited). The driver
        # itself finishes the epoch from lineage — the epoch NEVER ends
        # with a hole.
        leftovers = list(orphans)
        for rank in live:
            leftovers.extend(queues[rank])
        missing = [r for r in range(self.num_reducers) if r not in ledger]
        for reducer in sorted(set(leftovers) | set(missing)):
            if reducer in ledger:
                continue
            deliver(reducer, compute(reducer))
            stats["recomputed"] += 1

        end = time.monotonic()
        stats["dur_s"] = end - start
        if death_times:
            # Tail latency attributable to the resize: from the first
            # death to epoch completion (the survivors' recompute tax).
            stats["resize_stall_ms"] = (end - min(death_times)) * 1000.0
        self.last_stats = stats
        if stats["recomputed"] or dead:
            rt_telemetry.record(
                "member_resize", epoch=epoch, view=view.view_id,
                recomputed=stats["recomputed"],
                dead=sorted(dead), dur_s=stats["dur_s"])
            logger.warning(
                "elastic epoch %d completed DEGRADED: ranks %s died, "
                "%d reducer(s) recomputed on survivors", epoch,
                sorted(dead), stats["recomputed"])
        assert len(ledger) == self.num_reducers
        return [ledger[r] for r in range(self.num_reducers)]

    def run(self, num_epochs: int) -> List[List]:
        """Run ``num_epochs`` epochs; view changes (shrink from chaos or
        detector verdicts, grow from ``member_join``) take effect at
        each epoch boundary."""
        return [self.run_epoch(e)
                for e in plan_ir.epoch_range(0, num_epochs)]


def trainer_streams(reducer_outputs: Sequence, num_trainers: int) -> List:
    """Slice reducer-indexed outputs into per-trainer streams with the
    same ``route_slices`` contract the queue plane uses — the trainer
    count never changes under elasticity, so queue math stays stable."""
    spans = plan_ir.route_slices(len(reducer_outputs), num_trainers)
    return [list(reducer_outputs[start:stop]) for start, stop in spans]


def total_rows(reducer_outputs: Sequence) -> int:
    """Summed row count over reducer outputs (the bench elastic leg's
    ``rows_lost`` check compares this against the fixed-world run)."""
    return sum(t.num_rows for t in reducer_outputs)


__all__ = ["ElasticShuffleRunner", "trainer_streams", "total_rows"]
