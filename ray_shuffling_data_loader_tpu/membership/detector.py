"""Phi-style failure detection over transport heartbeats.

Heartbeats arrive two ways: piggybacked on every data frame the
generation-fenced transport accepts (``TcpTransport.set_frame_observer``
feeds every accepted frame's ``src`` here), and from a dedicated prober
(:class:`HeartbeatProber`) that sends explicit heartbeat control frames
so idle links between epochs stay observable. The detector itself is a
pure state machine with an injectable clock — every verdict is a
function of the beat timeline, so tests drive it deterministically with
a fake clock and zero sleeps.

Suspicion is phi-style: the detector keeps a smoothed inter-arrival
interval per rank (floored at the configured heartbeat cadence) and
computes ``phi = silence / smoothed_interval``; crossing ``member_phi``
marks the rank SUSPECT (telemetry ``member_suspect``), and silence
reaching the hard ``member_suspect_s`` deadline declares it DOWN
(``member_down`` — the membership transition that triggers the resize).
A beat from a SUSPECT rank clears it back to ALIVE.

Hysteresis: one flapping link must fire once, not storm. After a
suspicion clears, a re-suspicion within one ``suspect_s`` window is
counted as a *flap* (``rsdl_member_flaps_total``, telemetry
``member_flap``) and suppressed from the suspect callback/telemetry;
the internal state still advances so a genuinely dying rank's DOWN
deadline is never delayed by its own flapping.

Knobs (``runtime/policy.py``): ``RSDL_MEMBER_HEARTBEAT_S``,
``RSDL_MEMBER_SUSPECT_S``, ``RSDL_MEMBER_PHI``.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Callable, Deque, Dict, Optional, Sequence

from ray_shuffling_data_loader_tpu.runtime import metrics as rt_metrics
from ray_shuffling_data_loader_tpu.runtime import policy as rt_policy
from ray_shuffling_data_loader_tpu.utils.logger import setup_custom_logger

logger = setup_custom_logger(__name__)

ALIVE, SUSPECT, DOWN = "alive", "suspect", "down"

#: Inter-arrival samples kept per rank for the smoothed interval.
_WINDOW = 16


class FailureDetector:
    """Per-rank beat bookkeeping -> alive/suspect/down verdicts.

    Callbacks fire from whichever thread calls :meth:`poll` (the prober,
    or a test): ``on_suspect(rank)`` once per suspicion episode (flaps
    suppressed), ``on_down(rank)`` once per down verdict, and
    ``on_alive(rank)`` when a suspect rank's beats resume. A DOWN rank
    stays down until :meth:`revive` (the join path) re-arms it.
    """

    def __init__(self, peers: Sequence[int],
                 heartbeat_s: Optional[float] = None,
                 suspect_s: Optional[float] = None,
                 phi_threshold: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic,
                 on_suspect: Optional[Callable[[int], None]] = None,
                 on_down: Optional[Callable[[int], None]] = None,
                 on_alive: Optional[Callable[[int], None]] = None):
        self.heartbeat_s = rt_policy.resolve("member", "member_heartbeat_s",
                                             override=heartbeat_s)
        self.suspect_s = rt_policy.resolve("member", "member_suspect_s",
                                           override=suspect_s)
        self.phi_threshold = rt_policy.resolve("member", "member_phi",
                                               override=phi_threshold)
        self._clock = clock
        self._on_suspect = on_suspect
        self._on_down = on_down
        self._on_alive = on_alive
        self._lock = threading.Lock()
        self._state: Dict[int, str] = {}
        self._last: Dict[int, float] = {}
        self._intervals: Dict[int, Deque[float]] = {}
        # End of each rank's flap-suppression window: a suspicion that
        # RE-fires before this instant is a flap, not a fresh episode.
        self._quiet_until: Dict[int, float] = {}
        now = self._clock()
        with self._lock:
            for rank in peers:
                self._arm(int(rank), now)

    def _arm(self, rank: int, now: float) -> None:
        # Every caller (init/beat/revive) already holds self._lock.
        # rsdl-lint: disable=lock-mutation
        self._state[rank] = ALIVE
        # rsdl-lint: disable=lock-mutation
        self._last[rank] = now
        self._intervals[rank] = collections.deque(maxlen=_WINDOW)
        self._quiet_until.pop(rank, None)

    # -- inputs --------------------------------------------------------

    def beat(self, rank: int, now: Optional[float] = None) -> None:
        """One heartbeat observation (data frame or probe reply)."""
        rank = int(rank)
        now = self._clock() if now is None else now
        cleared = False
        with self._lock:
            if self._state.get(rank) == DOWN:
                return  # a down verdict is final until revive()
            if rank not in self._state:
                self._arm(rank, now)
            else:
                self._intervals[rank].append(
                    max(0.0, now - self._last[rank]))
                self._last[rank] = now
            if self._state[rank] == SUSPECT:
                self._state[rank] = ALIVE
                # The hysteresis arm: a re-suspicion inside one
                # suspect_s window of this clear is a flap.
                self._quiet_until[rank] = now + self.suspect_s
                cleared = True
        rt_metrics.counter("rsdl_member_heartbeats_total",
                           "heartbeats observed by the failure "
                           "detector").inc()
        if cleared:
            logger.info("failure detector: rank %d suspect cleared "
                        "(beats resumed)", rank)
            if self._on_alive is not None:
                self._on_alive(rank)

    def revive(self, rank: int, now: Optional[float] = None) -> None:
        """Re-arm a DOWN rank (the member_join path)."""
        now = self._clock() if now is None else now
        with self._lock:
            self._arm(int(rank), now)

    def forget(self, rank: int) -> None:
        """Stop tracking a rank that left the world on purpose."""
        with self._lock:
            for table in (self._state, self._last, self._intervals,
                          self._quiet_until):
                table.pop(int(rank), None)

    # -- verdicts ------------------------------------------------------

    def phi(self, rank: int, now: Optional[float] = None) -> float:
        """Suspicion level: silence measured in smoothed inter-arrival
        units (0.0 for untracked/just-armed ranks)."""
        now = self._clock() if now is None else now
        with self._lock:
            return self._phi_locked(int(rank), now)

    def _phi_locked(self, rank: int, now: float) -> float:
        last = self._last.get(rank)
        if last is None:
            return 0.0
        intervals = self._intervals.get(rank)
        if intervals:
            smoothed = max(self.heartbeat_s,
                           sum(intervals) / len(intervals))
        else:
            smoothed = self.heartbeat_s
        return max(0.0, now - last) / smoothed

    def state(self, rank: int) -> str:
        with self._lock:
            return self._state.get(int(rank), DOWN)

    def poll(self, now: Optional[float] = None) -> Dict[int, str]:
        """Evaluate every tracked rank; fire transition callbacks.
        Returns ``{rank: transition}`` for ranks that changed state this
        poll (``suspect``/``down``; flap-suppressed suspicions appear as
        ``flap``)."""
        now = self._clock() if now is None else now
        transitions: Dict[int, str] = {}
        suspect_cbs, down_cbs, flap_cbs = [], [], []
        with self._lock:
            for rank, state in list(self._state.items()):
                if state == DOWN:
                    continue
                silence = now - self._last[rank]
                if silence >= self.suspect_s:
                    self._state[rank] = DOWN
                    transitions[rank] = DOWN
                    down_cbs.append(rank)
                    continue
                if state == ALIVE and \
                        self._phi_locked(rank, now) >= self.phi_threshold:
                    self._state[rank] = SUSPECT
                    if now < self._quiet_until.get(rank, 0.0):
                        transitions[rank] = "flap"
                        flap_cbs.append(rank)
                    else:
                        transitions[rank] = SUSPECT
                        suspect_cbs.append(rank)
        for rank in flap_cbs:
            logger.warning("failure detector: rank %d flapping "
                           "(re-suspected inside the hysteresis window; "
                           "suppressed)", rank)
        for rank in suspect_cbs:
            logger.warning("failure detector: rank %d SUSPECT "
                           "(phi >= %.1f)", rank, self.phi_threshold)
            if self._on_suspect is not None:
                self._on_suspect(rank)
        for rank in down_cbs:
            logger.error("failure detector: rank %d DOWN (silent for "
                         ">= %.1fs)", rank, self.suspect_s)
            if self._on_down is not None:
                self._on_down(rank)
        return transitions


class HeartbeatProber:
    """The dedicated prober thread: every ``heartbeat_s`` it sends one
    heartbeat control frame to each live peer on the transport (so idle
    links stay observed) and polls the detector. The ``member_flap``
    chaos site fires here — a matched ``(epoch=None, task=peer)`` key
    swallows that peer's probe for the round, starving the detector
    exactly the way a flapping link would."""

    def __init__(self, transport, detector: FailureDetector,
                 interval_s: Optional[float] = None):
        self._transport = transport
        self._detector = detector
        self._interval_s = (detector.heartbeat_s if interval_s is None
                            else interval_s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "HeartbeatProber":
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"rsdl-member-prober-{self._transport.host_id}")
        self._thread.start()
        return self

    def _run(self) -> None:
        from ray_shuffling_data_loader_tpu.runtime import faults as rt_faults
        from ray_shuffling_data_loader_tpu.runtime import telemetry as \
            rt_telemetry
        while not self._stop.wait(self._interval_s):
            for peer in list(self._transport.known_peers()):
                try:
                    rt_faults.inject("member_flap", task=peer)
                except rt_faults.InjectedFault:
                    # Telemetry twin: the dropped probe is observable.
                    rt_telemetry.record("member_flap", task=peer,
                                        fault="probe_dropped")
                    continue
                self._transport.send_heartbeat(peer)
            self._detector.poll()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)


__all__ = ["FailureDetector", "HeartbeatProber", "ALIVE", "SUSPECT",
           "DOWN"]
