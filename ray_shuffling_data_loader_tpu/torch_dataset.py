"""Torch binding: shuffled batches as ``(List[Tensor], Tensor)``.

Capability parity with the reference's L4 Torch layer (reference:
torch_dataset.py:12-143): a ``torch.utils.data.IterableDataset`` over the
shuffling pipeline whose column spec (features/shapes/dtypes + label) is
normalized with the reference's rules and converted per column with
``torch.as_tensor`` + reshape to ``(-1, *shape)`` / ``(-1, 1)``.

This exists for drop-in migration from the reference; the TPU-native path
is ``JaxShufflingDataset`` (jax_dataset.py), which lands batches in device
memory instead of host torch tensors. Conversion reuses the same
Arrow->NumPy column path, so object/list-column handling is identical
across both bindings.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

import numpy as np
import torch
from torch.utils.data import IterableDataset

from ray_shuffling_data_loader_tpu.dataset import ShufflingDataset
from ray_shuffling_data_loader_tpu.jax_dataset import _column_to_numpy
from ray_shuffling_data_loader_tpu.utils.logger import setup_custom_logger

logger = setup_custom_logger(__name__)

# np dtype equivalents for the reference's torch dtype map
# (reference: torch_dataset.py:269-281).
_TORCH_TO_NUMPY = {
    torch.float16: np.float16,
    torch.float32: np.float32,
    torch.float64: np.float64,
    torch.int8: np.int8,
    torch.int16: np.int16,
    torch.int32: np.int32,
    torch.int64: np.int64,
    torch.uint8: np.uint8,
    torch.bool: np.bool_,
}


def _normalize_torch_data_spec(feature_columns=None,
                               feature_shapes=None,
                               feature_types=None,
                               label_column=None,
                               label_shape=None,
                               label_type=None):
    """Reference rules (reference: torch_dataset.py:146-204): scalars ->
    lists, shape/type lists must match the feature count, dtypes default to
    ``torch.float``."""
    if not isinstance(feature_columns, list):
        feature_columns = [feature_columns]
    if feature_shapes:
        if not isinstance(feature_shapes, list):
            feature_shapes = [feature_shapes]
        if len(feature_columns) != len(feature_shapes):
            raise ValueError(
                "The feature_shapes size must match the feature_columns")
        feature_shapes = [
            tuple(s) if isinstance(s, (list, tuple))
            else (None if s is None else (s,))
            for s in feature_shapes
        ]
    else:
        feature_shapes = [None] * len(feature_columns)
    if feature_types:
        if not isinstance(feature_types, list):
            feature_types = [feature_types]
        if len(feature_columns) != len(feature_types):
            raise ValueError(
                "The feature_types size must match the feature_columns")
        for dtype in feature_types:
            if not isinstance(dtype, torch.dtype):
                raise TypeError(
                    "All values in feature_types should be torch.dtype "
                    f"instances, got {type(dtype)}")
            if dtype not in _TORCH_TO_NUMPY:
                raise ValueError(
                    f"Unsupported feature dtype {dtype}; supported: "
                    f"{sorted(map(str, _TORCH_TO_NUMPY))}")
    else:
        feature_types = [torch.float] * len(feature_columns)
    if not label_type:
        label_type = torch.float
    if label_type not in _TORCH_TO_NUMPY:
        raise ValueError(
            f"Unsupported label dtype {label_type}; supported: "
            f"{sorted(map(str, _TORCH_TO_NUMPY))}")
    return (feature_columns, feature_shapes, feature_types, label_column,
            label_shape, label_type)


def convert_to_tensor(table, feature_columns: List[Any],
                      feature_shapes: List[Any],
                      feature_types: List[torch.dtype], label_column: Any,
                      label_shape: Optional[int], label_type: torch.dtype):
    """Arrow batch -> (List[Tensor], Tensor)
    (reference: torch_dataset.py:206-238)."""
    feature_tensor = []
    for col, shape, dtype in zip(feature_columns, feature_shapes,
                                 feature_types):
        arr = _column_to_numpy(table.column(col),
                               np.dtype(_TORCH_TO_NUMPY[dtype]))
        t = torch.as_tensor(arr, dtype=dtype)
        if shape is not None:
            t = t.view(*(-1, *shape))
        else:
            t = t.view(-1, 1)
        feature_tensor.append(t)
    label_arr = _column_to_numpy(table.column(label_column),
                                 np.dtype(_TORCH_TO_NUMPY[label_type]))
    label_tensor = torch.as_tensor(label_arr, dtype=label_type)
    if label_shape:
        label_tensor = label_tensor.view(-1, label_shape)
    else:
        label_tensor = label_tensor.view(-1, 1)
    return feature_tensor, label_tensor


class TorchShufflingDataset(IterableDataset):
    """IterableDataset over the shuffling pipeline
    (reference: torch_dataset.py:12-94)."""

    def __init__(self,
                 filenames: Sequence[str],
                 num_epochs: int,
                 num_trainers: int,
                 batch_size: int,
                 rank: int,
                 feature_columns: List[Any] = None,
                 feature_shapes: Optional[List[Any]] = None,
                 feature_types: Optional[List[torch.dtype]] = None,
                 label_column: Any = None,
                 label_shape: Optional[int] = None,
                 label_type: Optional[torch.dtype] = None,
                 drop_last: bool = False,
                 num_reducers: Optional[int] = None,
                 max_concurrent_epochs: int = 2,
                 batch_queue=None,
                 shuffle_result=None,
                 max_batch_queue_size: int = 0,
                 seed: int = 0,
                 num_workers: Optional[int] = None,
                 queue_name: str = "MultiQueue",
                 file_cache="auto",
                 max_inflight_bytes: Optional[int] = None,
                 spill_dir: Optional[str] = None):
        super().__init__()
        self._dataset = ShufflingDataset(
            filenames, num_epochs, num_trainers, batch_size, rank,
            drop_last=drop_last, num_reducers=num_reducers,
            max_concurrent_epochs=max_concurrent_epochs,
            batch_queue=batch_queue, shuffle_result=shuffle_result,
            max_batch_queue_size=max_batch_queue_size, seed=seed,
            num_workers=num_workers, queue_name=queue_name,
            file_cache=file_cache, max_inflight_bytes=max_inflight_bytes,
            spill_dir=spill_dir)
        spec = _normalize_torch_data_spec(feature_columns, feature_shapes,
                                          feature_types, label_column,
                                          label_shape, label_type)
        self._spec = spec

    def set_epoch(self, epoch: int, skip_batches: int = 0) -> None:
        """Declare the epoch about to be iterated. ``skip_batches`` drops
        the first N batches as zero-copy Arrow slices — checkpoint resume
        for migrated trainers (possible here because the shuffle is seeded;
        the reference's unseeded epochs are not replayable)."""
        self._dataset.set_epoch(epoch, skip_batches=skip_batches)

    def __iter__(self):
        for table in self._dataset:
            yield convert_to_tensor(table, *self._spec)


if __name__ == "__main__":
    # Smoke driver through the Torch path with the full DATA_SPEC column
    # spec (reference: torch_dataset.py:241-310).
    import argparse
    import tempfile
    import timeit

    from ray_shuffling_data_loader_tpu import data_generation as dg

    parser = argparse.ArgumentParser(
        description="TorchShufflingDataset smoke run")
    parser.add_argument("--num-rows", type=int, default=10**6)
    parser.add_argument("--num-files", type=int, default=10)
    parser.add_argument("--num-epochs", type=int, default=4)
    parser.add_argument("--num-reducers", type=int, default=8)
    parser.add_argument("--batch-size", type=int, default=50_000)
    args = parser.parse_args()

    with tempfile.TemporaryDirectory() as tmpdir:
        print(f"Generating {args.num_rows} rows over {args.num_files} files.")
        filenames, _ = dg.generate_data_local(args.num_rows, args.num_files,
                                              1, 0.0, tmpdir)
        feature_columns = list(dg.FEATURE_COLUMNS)
        start = timeit.default_timer()
        ds = TorchShufflingDataset(
            filenames,
            args.num_epochs,
            num_trainers=1,
            batch_size=args.batch_size,
            rank=0,
            num_reducers=args.num_reducers,
            feature_columns=feature_columns,
            feature_types=[torch.long] * len(feature_columns),
            label_column=dg.LABEL_COLUMN,
            label_type=torch.double)
        from ray_shuffling_data_loader_tpu.plan import ir as plan_ir
        for epoch in plan_ir.epoch_range(0, args.num_epochs):
            ds.set_epoch(epoch)
            rows = batches = 0
            for features, label in ds:
                assert len(features) == len(feature_columns)
                batches += 1
                rows += label.shape[0]
            assert rows == args.num_rows, (rows, args.num_rows)
            print(f"epoch {epoch}: {batches} batches, {rows} rows")
        duration = timeit.default_timer() - start
        total = args.num_epochs * args.num_rows
        print(f"Done: {total} rows in {duration:.2f}s "
              f"({total / duration:,.0f} rows/s)")
