"""Stage-span tracing bridged into the JAX/XLA profiler.

The reference's only tracing is wall-clock spans recorded into a stats
actor (reference: shuffle.py:204-263, stats.py:68-246); device time is
invisible to it. Here every hot stage (map, reduce, consume, convert,
transfer, train step) is wrapped in a ``jax.profiler.TraceAnnotation`` so
a captured trace shows the host pipeline stages on the same timeline as
XLA device ops — the stall analysis the reference can't do: you SEE
whether the device waits on the loader or vice versa.

Zero-cost by default: annotations are no-ops until a trace is active.
Capture is explicit (:func:`profile_trace`) or env-driven
(``RSDL_PROFILE_DIR=/tmp/trace python ...`` via :func:`maybe_profile`);
view with TensorBoard's profile plugin or Perfetto.
"""

from __future__ import annotations

import contextlib
import os
from typing import Iterator, Optional

_trace_annotation = None


def _get_trace_annotation():
    """Lazy import: keep jax out of pure-host code paths until needed."""
    global _trace_annotation
    if _trace_annotation is None:
        try:
            from jax.profiler import TraceAnnotation
            _trace_annotation = TraceAnnotation
        except ImportError:  # pragma: no cover - jax is a hard dep in CI
            _trace_annotation = False
    return _trace_annotation


@contextlib.contextmanager
def trace_span(name: str, kind: Optional[str] = None,
               epoch: Optional[int] = None, task: Optional[int] = None,
               batch: Optional[int] = None) -> Iterator[None]:
    """Named host span, visible in captured profiler traces. No-op cheap
    when no trace is active; safe to call from worker threads.

    With ``kind`` set, the span is ALSO recorded as a structured
    flight-recorder event (runtime/telemetry.py) carrying the given
    correlation ids — one annotation, two consumers: the XLA profiler
    timeline and the online bottleneck attribution.
    """
    annotation = _get_trace_annotation()
    if kind is not None:
        from ray_shuffling_data_loader_tpu.runtime import telemetry
        if not annotation:
            with telemetry.span(kind, epoch=epoch, task=task, batch=batch):
                yield
            return
        with telemetry.span(kind, epoch=epoch, task=task, batch=batch):
            with annotation(name):
                yield
        return
    if not annotation:
        yield
        return
    with annotation(name):
        yield


def step_span(step: int):
    """Train-step marker: lets the profiler group device ops per step.
    Returns a context manager."""
    try:
        from jax.profiler import StepTraceAnnotation
    except ImportError:  # pragma: no cover
        return contextlib.nullcontext()
    return StepTraceAnnotation("train", step_num=step)


@contextlib.contextmanager
def profile_trace(log_dir: str) -> Iterator[None]:
    """Capture a JAX profiler trace (host spans + device timeline) into
    ``log_dir`` for the duration of the block."""
    import jax
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def maybe_profile(env_var: str = "RSDL_PROFILE_DIR") -> Iterator[None]:
    """Capture a trace iff the env var names a directory — the zero-code
    way to profile any run: ``RSDL_PROFILE_DIR=/tmp/tr python bench.py``."""
    log_dir: Optional[str] = os.environ.get(env_var)
    if not log_dir:
        yield
        return
    with profile_trace(log_dir):
        yield
