"""Logging setup for the TPU shuffling data loader.

Capability parity with the reference's ``logger.py`` (reference:
ray_shuffling_data_loader/logger.py:4-13): a per-module stream logger with a
module/function format string. Differences: level is configurable via the
``RSDL_TPU_LOG_LEVEL`` environment variable (the reference hardcodes DEBUG),
and handlers are installed only once per logger name.
"""

from __future__ import annotations

import logging
import os
import sys

_FORMAT = "%(asctime)s %(levelname)s %(name)s %(module)s.%(funcName)s:%(lineno)d -- %(message)s"


def setup_custom_logger(name: str) -> logging.Logger:
    """Return a configured logger for ``name``.

    Idempotent: calling twice with the same name does not duplicate handlers.
    """
    logger = logging.getLogger(name)
    if getattr(logger, "_rsdl_tpu_configured", False):
        return logger
    level_name = os.environ.get("RSDL_TPU_LOG_LEVEL", "INFO").upper()
    level = getattr(logging, level_name, logging.INFO)
    logger.setLevel(level)
    handler = logging.StreamHandler(stream=sys.stderr)
    handler.setFormatter(logging.Formatter(_FORMAT))
    logger.addHandler(handler)
    logger.propagate = False
    logger._rsdl_tpu_configured = True  # type: ignore[attr-defined]
    return logger
