"""Configuration surface for the TPU shuffling data loader.

The reference exposes configuration as constructor kwargs plus module
constants (reference: ray_shuffling_data_loader/dataset.py:11-12,75-86).
We keep the kwargs surface and add a small dataclass so programmatic
configuration is explicit and testable.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

# Fraction of host cores given to reducers when num_reducers is not set.
# Mirrors REDUCER_CLUSTER_CORE_SHARE = 0.6 (reference: dataset.py:12) but
# scoped to the local TPU-VM host rather than a Ray cluster.
REDUCER_HOST_CORE_SHARE = 0.6

# Default number of epochs whose shuffles may be in flight concurrently.
DEFAULT_MAX_CONCURRENT_EPOCHS = 2


def default_num_reducers(num_trainers: int, num_cpus: Optional[int] = None) -> int:
    """Default reducer count: num_trainers * host_cpus * REDUCER_HOST_CORE_SHARE.

    Mirrors the reference's formula (reference: dataset.py:87-89) with the
    TPU-VM host's CPU count in place of the Ray cluster master's.
    """
    if num_cpus is None:
        num_cpus = os.cpu_count() or 1
    return max(1, int(num_trainers * num_cpus * REDUCER_HOST_CORE_SHARE))


@dataclasses.dataclass(frozen=True)
class ShuffleConfig:
    """Static configuration for a multi-epoch shuffle.

    Mirrors the kwargs of the reference's ``shuffle()`` entrypoint
    (reference: shuffle.py:79-85) plus a deterministic ``seed`` (the
    reference uses unseeded np.random — see SURVEY.md §5 — so its epochs
    are not reproducible; ours are).
    """

    num_epochs: int
    num_reducers: int
    num_trainers: int
    max_concurrent_epochs: int = DEFAULT_MAX_CONCURRENT_EPOCHS
    seed: int = 0
    # Number of worker threads for map/reduce tasks; None = os.cpu_count().
    num_workers: Optional[int] = None
    collect_stats: bool = True

    def __post_init__(self) -> None:
        if self.num_epochs < 1:
            raise ValueError(f"num_epochs must be >= 1, got {self.num_epochs}")
        if self.num_reducers < 1:
            raise ValueError(f"num_reducers must be >= 1, got {self.num_reducers}")
        if self.num_trainers < 1:
            raise ValueError(f"num_trainers must be >= 1, got {self.num_trainers}")
        if self.max_concurrent_epochs < 1:
            raise ValueError(
                f"max_concurrent_epochs must be >= 1, got {self.max_concurrent_epochs}")
