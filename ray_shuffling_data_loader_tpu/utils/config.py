"""Configuration surface for the TPU shuffling data loader.

The reference exposes configuration as constructor kwargs plus module
constants (reference: ray_shuffling_data_loader/dataset.py:11-12,75-86).
We keep the same kwargs surface; the module constants live here.
"""

from __future__ import annotations

import os
from typing import Optional

# Fraction of host cores given to reducers when num_reducers is not set.
# Mirrors REDUCER_CLUSTER_CORE_SHARE = 0.6 (reference: dataset.py:12) but
# scoped to the local TPU-VM host rather than a Ray cluster.
REDUCER_HOST_CORE_SHARE = 0.6

# Default number of epochs whose shuffles may be in flight concurrently.
DEFAULT_MAX_CONCURRENT_EPOCHS = 2


def default_num_reducers(num_trainers: int, num_cpus: Optional[int] = None) -> int:
    """Default reducer count: num_trainers * host_cpus * REDUCER_HOST_CORE_SHARE.

    Mirrors the reference's formula (reference: dataset.py:87-89) with the
    TPU-VM host's CPU count in place of the Ray cluster master's.
    """
    if num_cpus is None:
        num_cpus = os.cpu_count() or 1
    return max(1, int(num_trainers * num_cpus * REDUCER_HOST_CORE_SHARE))
