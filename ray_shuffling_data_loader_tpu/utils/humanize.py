"""Human-readable formatting helpers.

Capability parity with the reference's humanizers (reference:
ray_shuffling_data_loader/stats.py:580-595).
"""

from __future__ import annotations

_BIG_NUM_SUFFIXES = [
    (1e12, "T"),
    (1e9, "B"),
    (1e6, "M"),
    (1e3, "K"),
]

_SIZE_UNITS = ["B", "KiB", "MiB", "GiB", "TiB", "PiB"]


def human_readable_big_num(num: float) -> str:
    """1_500_000 -> '1.5M'; small numbers are returned unadorned."""
    for threshold, suffix in _BIG_NUM_SUFFIXES:
        if abs(num) >= threshold:
            value = num / threshold
            if value == int(value):
                return f"{int(value)}{suffix}"
            return f"{value:.1f}{suffix}"
    if num == int(num):
        return str(int(num))
    return f"{num:.1f}"


def human_readable_size(num_bytes: float) -> str:
    """1536 -> '1.5 KiB'."""
    size = float(num_bytes)
    for unit in _SIZE_UNITS:
        if abs(size) < 1024.0 or unit == _SIZE_UNITS[-1]:
            return f"{size:.1f} {unit}"
        size /= 1024.0
    return f"{size:.1f} PiB"
