"""Filesystem-spanning Parquet IO: local paths and remote URIs.

The reference reads and writes Parquet through ``smart_open`` so corpora can
live in S3/GCS (reference: ray_shuffling_data_loader/shuffle.py:7,208 and
data_generation.py:60-66; dependency at setup.py:18). On TPU-VMs the corpus
typically lives in GCS. Here the same capability rides pyarrow's C++
filesystems (``gs://``, ``s3://``, ``hdfs://`` — zero-copy into Arrow
buffers, no Python byte shuffling like smart_open) with an fsspec fallback
for any other scheme (``memory://`` is what the tests use — no network).

Plain paths (and ``file://`` URIs) stay on the local-path fast path.
"""

from __future__ import annotations

import os
from typing import List, Optional, Tuple

import pyarrow as pa
import pyarrow.parquet as pq


def parse_uri(path: str) -> Tuple[Optional["pa.fs.FileSystem"], str]:
    """Resolve ``path`` to ``(filesystem, path_within_fs)``.

    Returns ``(None, local_path)`` for plain local paths and ``file://``
    URIs; otherwise a pyarrow FileSystem (native where pyarrow has one,
    fsspec-wrapped for schemes it doesn't know).
    """
    if "://" not in path:
        return None, path
    scheme = path.split("://", 1)[0]
    if scheme == "file":
        return None, path.split("://", 1)[1]
    import pyarrow.fs as pafs
    try:
        fs, inner = pafs.FileSystem.from_uri(path)
        return fs, inner
    except (pa.ArrowInvalid, pa.ArrowNotImplementedError, ValueError):
        pass
    import fsspec
    fs, inner = fsspec.core.url_to_fs(path)
    return pafs.PyFileSystem(pafs.FSSpecHandler(fs)), inner


def read_parquet(path: str) -> pa.Table:
    """Read one Parquet file from a local path or any supported URI.

    Decode knobs are explicit: ``use_threads=True`` decodes row groups /
    columns in parallel on many-core hosts, ``pre_buffer=True`` coalesces
    column-chunk IO into large reads (the win on object stores), and local
    files are memory-mapped so the compressed bytes are paged in rather
    than copied through a read() buffer."""
    fs, inner = parse_uri(path)
    if fs is None:
        return pq.read_table(inner, use_threads=True, pre_buffer=True,
                             memory_map=True)
    return pq.read_table(inner, filesystem=fs, use_threads=True,
                         pre_buffer=True)


def write_parquet(table: pa.Table, path: str, **kwargs) -> None:
    """Write one Parquet file to a local path or any supported URI."""
    fs, inner = parse_uri(path)
    if fs is None:
        pq.write_table(table, inner, **kwargs)
        return
    pq.write_table(table, inner, filesystem=fs, **kwargs)


def makedirs(path: str) -> None:
    """mkdir -p across filesystems (no-op where directories are virtual,
    e.g. object stores)."""
    fs, inner = parse_uri(path)
    if fs is None:
        os.makedirs(inner, exist_ok=True)
        return
    try:
        fs.create_dir(inner, recursive=True)
    except (pa.ArrowNotImplementedError, OSError):
        pass  # object stores have no real directories


def join(base: str, *parts: str) -> str:
    """Path join that keeps URI separators ('/') for remote schemes."""
    if "://" not in base:
        return os.path.join(base, *parts)
    return "/".join([base.rstrip("/"), *parts])


def listdir(path: str) -> List[str]:
    """List files under a directory/prefix, returned with the same scheme
    as ``path`` so results round-trip through :func:`read_parquet`."""
    fs, inner = parse_uri(path)
    if fs is None:
        return sorted(
            os.path.join(inner, name) for name in os.listdir(inner))
    import pyarrow.fs as pafs
    scheme = path.split("://", 1)[0]
    infos = fs.get_file_info(pafs.FileSelector(inner, recursive=False))
    # info.path has no scheme; fsspec-backed filesystems report it with a
    # leading '/', native ones (gs/s3) as 'bucket/key' — normalize both.
    return sorted(f"{scheme}://{info.path.lstrip('/')}" for info in infos
                  if info.type == pafs.FileType.File)


def file_size(path: str) -> int:
    """Size in bytes of ``path``, 0 if it does not exist."""
    fs, inner = parse_uri(path)
    if fs is None:
        return os.path.getsize(inner) if os.path.exists(inner) else 0
    import pyarrow.fs as pafs
    info = fs.get_file_info(inner)
    return info.size if info.type == pafs.FileType.File else 0


class _RemoteTextFile:
    """Buffered text writer for remote URIs.

    Object stores have no append, so ``mode='a'`` reads any existing
    object first and re-uploads the concatenation on close — fine for
    the CSV reports this backs (the reference appended to s3 CSVs via
    smart_open the same rewrite-on-close way,
    reference: stats.py:283-287)."""

    def __init__(self, fs, inner: str, mode: str):
        import io
        self._fs = fs
        self._inner = inner
        self._buf = io.StringIO()
        self._closed = False
        if "a" in mode:
            import pyarrow.fs as pafs
            if fs.get_file_info(inner).type == pafs.FileType.File:
                with fs.open_input_stream(inner) as f:
                    self._buf.write(f.read().decode())

    def write(self, text: str) -> int:
        return self._buf.write(text)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        with self._fs.open_output_stream(self._inner) as f:
            f.write(self._buf.getvalue().encode())

    def __enter__(self) -> "_RemoteTextFile":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def open_text(path: str, mode: str = "w"):
    """Open a text file for writing on any filesystem.

    ``mode`` is ``'w'``/``'a'`` (a trailing ``'+'`` is tolerated and
    ignored — the CSV writers never read back through the handle)."""
    fs, inner = parse_uri(path)
    if fs is None:
        return open(inner, mode.replace("+", ""), newline="")
    return _RemoteTextFile(fs, inner, mode)
