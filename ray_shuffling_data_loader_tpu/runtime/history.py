"""Bounded time-series history of metrics-registry snapshots.

The registry (runtime/metrics.py) answers "what are the totals NOW";
nothing in the process can answer "what were they ten seconds ago" —
which is the question every online health judgment (throughput drooped?
ledger creeping? queue saturating?) actually asks. This module keeps a
fixed-memory ring of periodic registry snapshots, ticked from the
watchdog monitor thread (runtime/watchdog.py ``every()``), with the
derived views detectors and reports consume:

- :meth:`HistoryRing.series` — a gauge/counter's value over time,
  summed over the label children matching a filter;
- :meth:`HistoryRing.rate` — counter deltas over a smoothing window of
  ticks, as events/s (negative deltas clamp to 0 across restarts);
- :meth:`HistoryRing.slice` — a JSON-serializable window of the ring
  (what incident capsules embed), loadable by :func:`load_slice` and
  **mergeable across pids** by :func:`merged_series` (per-pid slices
  align on wall-clock buckets and sum — the federation story of
  runtime/metrics.py, extended through time).

Each tick also refreshes the process-resource gauges
(``rsdl_process_rss_bytes``, ``rsdl_ledger_bytes_in_use``) so leak
detectors have a series to judge; both reads are best-effort (no /proc,
no native ledger — the gauge just stays absent).

Memory bound: ``history_capacity`` snapshots (default 600 — ten minutes
at the default 1 s ``history_interval_s``), each holding one parsed
sample dict; the deque drops the oldest on overflow.

Stdlib-only (the runtime/ contract).
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

# Loadable standalone by file path (tools/rsdl_incident.py /
# tools/rsdl_report.py on hosts without numpy): the package imports are
# optional — only live capture (tick) needs them; slice loading and the
# series math are pure stdlib.
try:
    from ray_shuffling_data_loader_tpu.runtime import metrics
except ImportError:  # pragma: no cover - stripped-host standalone load
    metrics = None
try:
    from ray_shuffling_data_loader_tpu.utils.logger import (
        setup_custom_logger)
    logger = setup_custom_logger(__name__)
except ImportError:  # pragma: no cover - stripped-host standalone load
    import logging
    logger = logging.getLogger(__name__)

_PAGE_SIZE = os.sysconf("SC_PAGESIZE") if hasattr(os, "sysconf") else 4096
_ledger_unavailable = False


def _rss_bytes() -> Optional[int]:
    """Resident set size from /proc (None off-Linux)."""
    try:
        with open("/proc/self/statm", "rb") as f:
            return int(f.read().split()[1]) * _PAGE_SIZE
    except (OSError, ValueError, IndexError):
        return None


def _ledger_bytes() -> Optional[int]:
    """In-use bytes of the native buffer ledger (None when the native
    layer / numpy are not importable — history must stay stdlib-clean)."""
    global _ledger_unavailable
    if _ledger_unavailable:
        return None
    try:
        from ray_shuffling_data_loader_tpu import native
        return int(native.buffer_ledger().bytes_in_use())
    except Exception:  # noqa: BLE001 - any import/ABI failure: no series
        _ledger_unavailable = True
        return None


def _labels_key(labels: Tuple[Tuple[str, str], ...]) -> str:
    """JSON-object key for a label tuple (stable, round-trippable)."""
    return json.dumps(list(labels))


def _labels_from_key(key: str) -> Tuple[Tuple[str, str], ...]:
    return tuple((str(k), str(v)) for k, v in json.loads(key))


class HistoryRing:
    """Fixed-capacity ring of ``{t, t_unix, samples}`` snapshots."""

    def __init__(self, capacity: Optional[int] = None,
                 interval_s: Optional[float] = None):
        if capacity is None or interval_s is None:
            # Policy is consulted only for unset knobs, so slice loading
            # (both always given) stays package-free for the tools.
            from ray_shuffling_data_loader_tpu.runtime import policy
            if capacity is None:
                capacity = policy.resolve("history", "history_capacity")
            if interval_s is None:
                interval_s = policy.resolve("history", "history_interval_s")
        self.capacity = int(capacity)
        self.interval_s = float(interval_s)
        self._snaps: "collections.deque" = collections.deque(
            maxlen=max(2, self.capacity))
        self._types: Dict[str, str] = {}
        self._listeners: List[Callable[["HistoryRing"], None]] = []
        self._lock = threading.Lock()
        self.ticks = 0

    # -- capture -------------------------------------------------------------

    def tick(self) -> Dict[str, Any]:
        """Snapshot the process registry (refreshing the resource gauges
        first) and notify listeners (the health engine). Runs on the
        watchdog monitor thread; must never raise."""
        if metrics is None:
            raise RuntimeError("live history capture needs the package "
                               "(standalone loads may only read slices)")
        rss = _rss_bytes()
        if rss is not None:
            metrics.gauge("rsdl_process_rss_bytes",
                          "resident set size sampled at history ticks"
                          ).set(rss)
        ledger = _ledger_bytes()
        if ledger is not None:
            metrics.gauge("rsdl_ledger_bytes_in_use",
                          "native buffer-ledger bytes sampled at history "
                          "ticks").set(ledger)
        samples, types = metrics.parse_exposition_typed(metrics.render())
        snap = {
            # t is monotonic (interval math); t_unix is SERIALIZED only —
            # the cross-pid alignment key of merged_series.
            "t": time.monotonic(),
            "t_unix": time.time(),
            "samples": samples,
        }
        with self._lock:
            self._types.update(types)
            self._snaps.append(snap)
            self.ticks += 1
            listeners = list(self._listeners)
        for listener in listeners:
            try:
                listener(self)
            except Exception:  # noqa: BLE001 - observers must not kill ticks
                logger.exception("history listener failed")
        return snap

    def append_snapshot(self, snap: Dict[str, Any]) -> None:
        """Append a pre-built snapshot (synthetic-series tests, slice
        loading). Listeners fire exactly as for a live tick."""
        with self._lock:
            self._snaps.append(snap)
            self.ticks += 1
            listeners = list(self._listeners)
        for listener in listeners:
            listener(self)

    def add_listener(self, fn: Callable[["HistoryRing"], None]) -> None:
        """Run ``fn(ring)`` after every tick — ordered AFTER the snapshot
        is appended, which is what lets the health engine evaluate the
        tick it was woken for instead of lagging one interval."""
        with self._lock:
            self._listeners.append(fn)

    def remove_listener(self, fn: Callable[["HistoryRing"], None]) -> None:
        with self._lock:
            try:
                self._listeners.remove(fn)
            except ValueError:
                pass

    # -- views ---------------------------------------------------------------

    def snapshots(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._snaps)

    @staticmethod
    def _sample_value(snap: Dict[str, Any], name: str,
                      labels: Optional[Dict[str, str]]) -> Optional[float]:
        series = snap["samples"].get(name)
        if series is None:
            return None
        if labels is None:
            return sum(series.values())
        total = None
        for sample_labels, value in series.items():
            d = dict(sample_labels)
            if all(d.get(k) == str(v) for k, v in labels.items()):
                total = (total or 0.0) + value
        return total

    def series(self, name: str, labels: Optional[Dict[str, str]] = None
               ) -> List[Tuple[float, float]]:
        """``[(t_mono, value)]`` of a metric over the retained window,
        summed across label children matching the ``labels`` filter
        (None = all children). Snapshots predating the metric are
        skipped, not zero-filled."""
        out = []
        for snap in self.snapshots():
            value = self._sample_value(snap, name, labels)
            if value is not None:
                out.append((snap["t"], value))
        return out

    def rate(self, name: str, labels: Optional[Dict[str, str]] = None,
             window_ticks: int = 1) -> List[Tuple[float, float]]:
        """``[(t_mono, per-second rate)]`` from counter deltas over a
        smoothing window of ``window_ticks`` snapshots. Window > 1 is the
        droop detector's view: epoch-bursty counters (a process-backend
        epoch completes its maps all at once) smooth into a judgeable
        rate. Negative deltas (counter reset across a registry swap)
        clamp to zero."""
        pts = self.series(name, labels)
        window_ticks = max(1, int(window_ticks))
        out = []
        for i in range(window_ticks, len(pts)):
            t0, v0 = pts[i - window_ticks]
            t1, v1 = pts[i]
            dt = t1 - t0
            if dt <= 0:
                continue
            out.append((t1, max(0.0, v1 - v0) / dt))
        return out

    # -- serialization -------------------------------------------------------

    def slice(self, last_s: Optional[float] = None) -> Dict[str, Any]:
        """JSON-serializable window of the ring (newest ``last_s``
        seconds; None = everything retained) — what incident capsules
        embed and what :func:`merged_series` merges across pids."""
        snaps = self.snapshots()
        if last_s is not None and snaps:
            horizon = snaps[-1]["t"] - last_s
            snaps = [s for s in snaps if s["t"] >= horizon]
        return {
            "schema": "rsdl-history-v1",
            "pid": os.getpid(),
            "interval_s": self.interval_s,
            "capacity": self.capacity,
            "types": dict(self._types),
            "snapshots": [{
                "t": s["t"],
                "t_unix": s["t_unix"],
                "samples": {
                    name: {_labels_key(labels): value
                           for labels, value in series.items()}
                    for name, series in s["samples"].items()
                },
            } for s in snaps],
        }


def downsample_slice(data: Dict[str, Any],
                     max_snapshots: int = 120) -> Dict[str, Any]:
    """Bound a :meth:`HistoryRing.slice` payload to ``max_snapshots``
    snapshots by even-stride decimation that always keeps the newest
    snapshot (the one incident reviews start from) and the oldest (the
    pre-incident baseline). Capsule writers call this so a long-lived
    ring cannot balloon a forensic capsule; the result is still a valid
    ``rsdl-history-v1`` slice."""
    snaps = data.get("snapshots", [])
    if max_snapshots < 2 or len(snaps) <= max_snapshots:
        return data
    stride = (len(snaps) - 1) / float(max_snapshots - 1)
    keep = sorted({round(i * stride) for i in range(max_snapshots)}
                  | {0, len(snaps) - 1})
    out = dict(data)
    out["snapshots"] = [snaps[i] for i in keep if i < len(snaps)]
    return out


def load_slice(data: Dict[str, Any]) -> HistoryRing:
    """Rebuild a ring from :meth:`HistoryRing.slice` output."""
    if data.get("schema") != "rsdl-history-v1":
        raise ValueError(
            f"not an rsdl history slice (schema={data.get('schema')!r})")
    ring = HistoryRing(capacity=max(2, len(data.get("snapshots", []))),
                       interval_s=data.get("interval_s", 1.0))
    ring._types.update(data.get("types", {}))
    for s in data["snapshots"]:
        ring.append_snapshot({
            "t": s["t"],
            "t_unix": s["t_unix"],
            "samples": {
                name: {_labels_from_key(key): value
                       for key, value in series.items()}
                for name, series in s["samples"].items()
            },
        })
    return ring


def merged_series(slices: List[Dict[str, Any]], name: str,
                  labels: Optional[Dict[str, str]] = None
                  ) -> List[Tuple[float, float]]:
    """Cross-pid series: each slice's series aligns onto wall-clock
    buckets (the coarsest slice interval) with forward-fill, then the
    per-pid values SUM per bucket — counters and additive gauges both
    merge this way, mirroring :func:`metrics.merge_series` through time.
    Returns ``[(t_unix_bucket, value)]``."""
    if not slices:
        return []
    bucket_s = max(float(s.get("interval_s", 1.0)) for s in slices)
    per_slice: List[List[Tuple[float, float]]] = []
    for data in slices:
        ring = data if isinstance(data, HistoryRing) else load_slice(data)
        pts = []
        for snap in ring.snapshots():
            value = HistoryRing._sample_value(snap, name, labels)
            if value is not None:
                pts.append((snap["t_unix"], value))
        if pts:
            per_slice.append(pts)
    if not per_slice:
        return []
    buckets = sorted({round(t / bucket_s) * bucket_s
                      for pts in per_slice for t, _ in pts})
    out = []
    for bucket in buckets:
        total = 0.0
        seen = False
        for pts in per_slice:
            last = None
            for t, value in pts:
                if t <= bucket + bucket_s / 2:
                    last = value
                else:
                    break
            if last is not None:
                total += last
                seen = True
        if seen:
            out.append((bucket, total))
    return out


# ---------------------------------------------------------------------------
# Process-wide wiring: ONE ring ticked from the watchdog monitor thread
# ---------------------------------------------------------------------------

_global_lock = threading.Lock()
_global: Optional[HistoryRing] = None
_periodic = None


def get_history() -> Optional[HistoryRing]:
    """The process-wide ring (None until :func:`start`)."""
    with _global_lock:
        return _global


def start(interval_s: Optional[float] = None,
          capacity: Optional[int] = None) -> HistoryRing:
    """Start (or restart with fresh state) the process-wide history
    ring, ticked by the watchdog's periodic facility. Returns the ring."""
    from ray_shuffling_data_loader_tpu.runtime import watchdog
    global _global, _periodic
    ring = HistoryRing(capacity=capacity, interval_s=interval_s)
    wd = watchdog.get_watchdog()
    with _global_lock:
        if _periodic is not None:
            wd.cancel(_periodic)
        _global = ring
        _periodic = wd.every(ring.interval_s, ring.tick,
                             name="history-tick")
    return ring


def stop() -> None:
    from ray_shuffling_data_loader_tpu.runtime import watchdog
    global _global, _periodic
    with _global_lock:
        if _periodic is not None:
            watchdog.get_watchdog().cancel(_periodic)
            _periodic = None
        _global = None
