"""Continuous sampling profiler: folded stacks + per-thread CPU billing.

The JAX profiler bridge (utils/tracing.py) answers "what is the DEVICE
doing"; the flight recorder answers "which stage is slow". What neither
answers is "which PYTHON FRAMES are burning the host CPU the producer
bound is made of" (BENCH_r05: ``host_cpus: 1``, stall 97.4%). This
module is the stdlib answer, always available in production:

- **Stack sampling** — a daemon thread walks ``sys._current_frames()``
  on a fixed interval and folds each named thread's stack into
  ``thread;outer;...;leaf`` lines with sample counts: the exact input
  ``flamegraph.pl`` / speedscope / inferno consume. Sampling is
  cooperative with the GIL, which is precisely what makes the numbers
  honest for this pipeline: a frame that holds the GIL is a frame that
  blocks the pipeline.
- **Stage attribution** — each sample is also billed to the pipeline
  stage whose telemetry span the thread currently has open
  (``telemetry.active_kinds()``), so the folded view and the flight
  recorder agree on vocabulary.
- **Executor-worker CPU attribution** — on Linux, per-native-thread
  CPU seconds from ``/proc/self/task/<tid>/stat`` (utime+stime delta
  over the profiled window) are reported per thread name: how much of
  the box each ``rsdl-worker_N`` actually used, GIL or not.

Zero overhead when off (no thread is started); overhead when on is one
frames snapshot per interval. ``maybe_sample()`` is the env-driven
bench/driver entry: profiling engages when the ``profiler`` policy key
(``RSDL_PROFILER=1``) or ``RSDL_PROFILE_FOLDED=<path>`` is set, and the
folded output lands at that path.

Stdlib-only (the runtime/ contract).
"""

from __future__ import annotations

import contextlib
import os
import sys
import threading
import time
from typing import Any, Dict, Iterator, List, Optional, Tuple

from ray_shuffling_data_loader_tpu.utils.logger import setup_custom_logger

logger = setup_custom_logger(__name__)

_CLK_TCK = os.sysconf("SC_CLK_TCK") if hasattr(os, "sysconf") else 100


def _thread_cpu_seconds() -> Dict[int, float]:
    """native tid -> CPU seconds (utime+stime) from /proc; {} elsewhere."""
    out: Dict[int, float] = {}
    task_dir = "/proc/self/task"
    if not os.path.isdir(task_dir):
        return out
    try:
        tids = os.listdir(task_dir)
    except OSError:
        return out
    for tid in tids:
        try:
            with open(f"{task_dir}/{tid}/stat", "rb") as f:
                stat = f.read().decode("ascii", "replace")
        except OSError:
            continue  # thread exited between listdir and open
        # utime/stime are fields 14/15, counted AFTER the parenthesized
        # comm field (which may itself contain spaces).
        rest = stat.rsplit(")", 1)[-1].split()
        if len(rest) >= 13:
            try:
                out[int(tid)] = (int(rest[11]) + int(rest[12])) / _CLK_TCK
            except ValueError:
                continue
    return out


class SamplingProfiler:
    """Fold stacks of named threads on an interval; bill samples to
    threads and to open telemetry span kinds; attribute per-thread CPU
    over the profiled window."""

    def __init__(self, interval_s: Optional[float] = None,
                 thread_prefixes: Optional[Tuple[str, ...]] = None):
        from ray_shuffling_data_loader_tpu.runtime import policy
        self.interval_s = policy.resolve("telemetry", "profiler_interval_s",
                                         override=interval_s)
        #: None = sample every thread; otherwise only names matching a
        #: prefix (e.g. ("rsdl-", "dryrun-") to isolate pipeline work).
        self.thread_prefixes = thread_prefixes
        self._folded: Dict[str, int] = {}
        self._by_stage: Dict[str, int] = {}
        self._by_thread: Dict[str, int] = {}
        self.samples = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._cpu_start: Dict[int, float] = {}
        self._cpu_delta: Dict[str, float] = {}
        self._t_start = 0.0
        self.duration_s = 0.0

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "SamplingProfiler":
        if self._thread is not None:
            return self
        self._cpu_start = _thread_cpu_seconds()
        self._t_start = time.monotonic()
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="rsdl-profiler")
        self._thread.start()
        return self

    def stop(self) -> "SamplingProfiler":
        if self._thread is None:
            return self
        self._stop.set()
        self._thread.join(timeout=5.0)
        self._thread = None
        self.duration_s = time.monotonic() - self._t_start
        cpu_end = _thread_cpu_seconds()
        names = {t.native_id: t.name for t in threading.enumerate()
                 if getattr(t, "native_id", None) is not None}
        deltas: Dict[str, float] = {}
        for tid, end in cpu_end.items():
            delta = end - self._cpu_start.get(tid, 0.0)
            if delta <= 0:
                continue
            name = names.get(tid, f"tid-{tid}")
            deltas[name] = deltas.get(name, 0.0) + delta
        self._cpu_delta = deltas
        return self

    def __enter__(self) -> "SamplingProfiler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- sampling loop -------------------------------------------------------

    def _loop(self) -> None:
        from ray_shuffling_data_loader_tpu.runtime import telemetry
        own_ident = threading.get_ident()
        while not self._stop.wait(self.interval_s):
            frames = sys._current_frames()
            by_ident = {t.ident: t.name for t in threading.enumerate()}
            kinds = telemetry.active_kinds()
            with self._lock:
                self.samples += 1
                for ident, frame in frames.items():
                    if ident == own_ident:
                        continue
                    name = by_ident.get(ident, f"ident-{ident}")
                    if self.thread_prefixes is not None and not any(
                            name.startswith(p) for p in
                            self.thread_prefixes):
                        continue
                    stack: List[str] = []
                    depth = 0
                    while frame is not None and depth < 64:
                        code = frame.f_code
                        module = code.co_filename.rsplit(os.sep, 1)[-1]
                        stack.append(f"{module}:{code.co_name}")
                        frame = frame.f_back
                        depth += 1
                    stack.reverse()
                    key = ";".join([name] + stack)
                    self._folded[key] = self._folded.get(key, 0) + 1
                    self._by_thread[name] = self._by_thread.get(name, 0) + 1
                    stage = kinds.get(ident)
                    if stage is not None:
                        self._by_stage[stage] = \
                            self._by_stage.get(stage, 0) + 1

    # -- results -------------------------------------------------------------

    def folded(self) -> Dict[str, int]:
        """``thread;frame;...;leaf`` -> sample count (flamegraph input)."""
        with self._lock:
            return dict(self._folded)

    def by_stage(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._by_stage)

    def cpu_by_thread(self) -> Dict[str, float]:
        """thread name -> CPU seconds used over the profiled window."""
        return dict(self._cpu_delta)

    def write_folded(self, path: str) -> str:
        folded = self.folded()
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, "w", encoding="utf-8") as f:
            for key in sorted(folded):
                f.write(f"{key} {folded[key]}\n")
        return path

    def summary(self, top: int = 5) -> Dict[str, Any]:
        """Compact report for the bench record: sample counts, stage
        billing, busiest threads by samples and by CPU seconds."""
        folded = self.folded()
        hot = sorted(folded.items(), key=lambda kv: -kv[1])[:top]
        cpu = sorted(self._cpu_delta.items(), key=lambda kv: -kv[1])[:top]
        return {
            "samples": self.samples,
            "interval_s": self.interval_s,
            "duration_s": round(self.duration_s, 3),
            "by_stage": self.by_stage(),
            "threads_by_samples": dict(
                sorted(self._by_thread.items(),
                       key=lambda kv: -kv[1])[:top]),
            "cpu_s_by_thread": {k: round(v, 3) for k, v in cpu},
            "hottest_stacks": [
                {"stack": k.split(";")[-1], "thread": k.split(";")[0],
                 "samples": v} for k, v in hot
            ],
        }


@contextlib.contextmanager
def maybe_sample(folded_env: str = "RSDL_PROFILE_FOLDED"
                 ) -> Iterator[Optional[SamplingProfiler]]:
    """Profile the block iff profiling is switched on: the ``profiler``
    policy key (``RSDL_PROFILER=1``) or a folded-output path in
    ``RSDL_PROFILE_FOLDED``. Yields the profiler (or None when off);
    on exit writes the folded stacks when a path was given. The JAX
    device-side twin stays ``utils.tracing.maybe_profile`` — run both
    to see host frames and device ops over the same window."""
    from ray_shuffling_data_loader_tpu.runtime import policy
    folded_path = os.environ.get(folded_env) or None
    if not folded_path and not policy.resolve("telemetry", "profiler"):
        yield None
        return
    profiler = SamplingProfiler()
    profiler.start()
    try:
        yield profiler
    finally:
        profiler.stop()
        if folded_path:
            try:
                profiler.write_folded(folded_path)
                logger.info("sampling profile: %d samples -> %s",
                            profiler.samples, folded_path)
            except OSError:
                logger.exception("folded-stack write failed")
