"""Runtime lock sanitizer: the dynamic half of the concurrency pass.

The static lock-order analysis (``analysis/locksets.py``) proves what
the source *says*; this module records what a live process actually
*does*. Opt-in (``RSDL_LOCKSAN=1`` before the package allocates its
locks — tests/conftest.py wires it), :func:`install` monkeypatches the
``threading.Lock`` / ``RLock`` / ``Condition`` factories so that every
lock **allocated from package code** is wrapped in a recording proxy.
Locks allocated elsewhere (stdlib internals, third-party code, test
files) pass through untouched — the proxy tax is paid only where the
contract applies.

Each proxy knows its allocation site as ``path:line`` relative to the
repo root — the exact key ``locksets.LockDecl`` uses for the same
construction site, which is what makes the static and dynamic order
graphs directly comparable (:func:`crosscheck`). Recorded per process:

- **acquisition-order edges**: acquiring B while holding A adds
  ``A -> B`` (with a ``same_instance`` flag when one allocation site
  serves several runtime instances — orderings the static pass
  declines to judge);
- **held-while-blocking events**: a ``Condition.wait`` entered while
  holding *other* package locks, or a contended acquire that stalled
  past ``RSDL_LOCKSAN_SLOW_MS`` (default 50) while holding locks.

:func:`dump` writes the order-graph JSON artifact
(``RSDL_LOCKSAN_OUT``, default ``.rsdl-locksan-graph.json``);
``rsdl-lint --concurrency --locksan-graph <file>`` cross-checks it:
dynamic edges the static graph lacks are findings, static cycles
confirmed dynamically are hard failures.

Overhead is one dict update per acquisition under a dedicated real
lock — fine for tests and chaos soaks, not meant for production runs.
Stdlib-only, like everything else in ``runtime/``.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock
_REAL_CONDITION = threading.Condition

#: Package whose allocation sites get wrapped (path prefix under root).
_DEFAULT_INCLUDE = ("ray_shuffling_data_loader_tpu/",)

_installed = False
_root: str = ""
_include: Tuple[str, ...] = _DEFAULT_INCLUDE
_slow_ms: float = 50.0

_guard = _REAL_LOCK()          # protects the shared tables below
_edges: Dict[Tuple[str, str], Dict[str, Any]] = {}
_events: List[Dict[str, Any]] = []
_sites: Dict[str, str] = {}    # site -> kind
_tls = threading.local()

_MODULE_FILE = os.path.abspath(__file__)


def _held_stack() -> List["_SanLock"]:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def _alloc_site() -> Optional[str]:
    """``path:line`` of the nearest caller frame inside the package."""
    frame = sys._getframe(2)
    while frame is not None and \
            os.path.abspath(frame.f_code.co_filename) == _MODULE_FILE:
        frame = frame.f_back
    if frame is None:
        return None
    filename = os.path.abspath(frame.f_code.co_filename)
    rel = os.path.relpath(filename, _root).replace(os.sep, "/")
    if rel.startswith("..") or not rel.startswith(_include):
        return None
    return f"{rel}:{frame.f_lineno}"


def _record_acquired(proxy: "_SanLock", waited_s: float,
                     reentered: bool) -> None:
    stack = _held_stack()
    if not reentered:
        with _guard:
            for held in stack:
                if held is proxy:
                    continue
                key = (held.site, proxy.site)
                entry = _edges.get(key)
                if entry is None:
                    entry = _edges[key] = {
                        "src": held.site, "dst": proxy.site, "count": 0,
                        "same_instance": False}
                entry["count"] += 1
                if held.site == proxy.site:
                    entry["same_instance"] = True
            if stack and waited_s * 1000.0 >= _slow_ms:
                _events.append({
                    "type": "contended-acquire-while-holding",
                    "site": proxy.site,
                    "held": [h.site for h in stack],
                    "waited_ms": round(waited_s * 1000.0, 3),
                    "thread": threading.current_thread().name,
                })
    stack.append(proxy)


def _record_released(proxy: "_SanLock") -> None:
    stack = _held_stack()
    for i in range(len(stack) - 1, -1, -1):
        if stack[i] is proxy:
            del stack[i]
            return


class _SanLock:
    """Recording proxy over a real lock/rlock primitive."""

    __slots__ = ("_real", "site", "reentrant")

    def __init__(self, real: Any, site: str, reentrant: bool):
        self._real = real
        self.site = site
        self.reentrant = reentrant

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        reentered = self.reentrant and self in _held_stack()
        start = time.monotonic()
        got = self._real.acquire(blocking, timeout)
        if got:
            _record_acquired(self, time.monotonic() - start, reentered)
        return got

    def release(self) -> None:
        self._real.release()
        _record_released(self)

    def locked(self) -> bool:
        return self._real.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    # Condition() interrogates its lock for these; delegate so a
    # proxied RLock keeps its reentrancy bookkeeping intact.
    def _release_save(self):
        inner = getattr(self._real, "_release_save", None)
        state = inner() if inner is not None else self._real.release()
        _record_released(self)
        return state

    def _acquire_restore(self, state) -> None:
        start = time.monotonic()
        inner = getattr(self._real, "_acquire_restore", None)
        if inner is not None:
            inner(state)
        else:
            self._real.acquire()
        _record_acquired(self, time.monotonic() - start, reentered=False)

    def _is_owned(self) -> bool:
        inner = getattr(self._real, "_is_owned", None)
        if inner is not None:
            return inner()
        if self._real.acquire(False):
            self._real.release()
            return False
        return True

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<locksan {self._real!r} @ {self.site}>"


class _SanCondition:
    """Recording proxy over a real Condition bound to a _SanLock."""

    __slots__ = ("_real", "_lock", "site")

    def __init__(self, real: Any, lock: _SanLock, site: str):
        self._real = real
        self._lock = lock
        self.site = site

    def acquire(self, *args, **kwargs) -> bool:
        return self._real.acquire(*args, **kwargs)

    def release(self) -> None:
        self._real.release()

    def __enter__(self):
        return self._real.__enter__()

    def __exit__(self, *exc):
        return self._real.__exit__(*exc)

    def _note_blocking_wait(self) -> None:
        others = [h.site for h in _held_stack() if h is not self._lock]
        if not others:
            return
        frame = sys._getframe(2)
        where = "?"
        if frame is not None:
            rel = os.path.relpath(
                os.path.abspath(frame.f_code.co_filename),
                _root).replace(os.sep, "/")
            where = f"{rel}:{frame.f_lineno}"
        with _guard:
            _events.append({
                "type": "held-while-blocking",
                "site": self.site,
                "held": others,
                "where": where,
                "thread": threading.current_thread().name,
            })

    def wait(self, timeout: Optional[float] = None) -> bool:
        self._note_blocking_wait()
        return self._real.wait(timeout)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        self._note_blocking_wait()
        return self._real.wait_for(predicate, timeout)

    def notify(self, n: int = 1) -> None:
        self._real.notify(n)

    def notify_all(self) -> None:
        self._real.notify_all()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<locksan {self._real!r} @ {self.site}>"


def _lock_factory():
    site = _alloc_site()
    real = _REAL_LOCK()
    if site is None:
        return real
    with _guard:
        _sites.setdefault(site, "Lock")
    return _SanLock(real, site, reentrant=False)


def _rlock_factory():
    site = _alloc_site()
    real = _REAL_RLOCK()
    if site is None:
        return real
    with _guard:
        _sites.setdefault(site, "RLock")
    return _SanLock(real, site, reentrant=True)


def _condition_factory(lock=None):
    site = _alloc_site()
    if site is None:
        return _REAL_CONDITION(lock)
    if lock is None:
        # Same default as the real Condition, but the inner RLock must
        # be OUR proxy so acquisitions through the condition record.
        lock = _SanLock(_REAL_RLOCK(), site, reentrant=True)
    elif not isinstance(lock, _SanLock):
        lock = _SanLock(lock, site, reentrant=True)
    with _guard:
        _sites.setdefault(lock.site, "Condition")
    real = _REAL_CONDITION(lock)
    return _SanCondition(real, lock, lock.site)


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------


def enabled_by_env() -> bool:
    return os.environ.get("RSDL_LOCKSAN", "") == "1"


def installed() -> bool:
    return _installed


def install(root: Optional[str] = None,
            include: Tuple[str, ...] = _DEFAULT_INCLUDE) -> None:
    """Patch the threading factories. Must run BEFORE the package
    modules allocate their module-level locks to see those sites;
    idempotent. ``root`` is the repo root the static analyzer runs
    from (default: the checkout containing this file)."""
    global _installed, _root, _include, _slow_ms
    _root = os.path.abspath(root) if root else os.path.dirname(
        os.path.dirname(os.path.dirname(_MODULE_FILE)))
    _include = tuple(include)
    _slow_ms = float(os.environ.get("RSDL_LOCKSAN_SLOW_MS", "50"))
    if _installed:
        return
    threading.Lock = _lock_factory
    threading.RLock = _rlock_factory
    threading.Condition = _condition_factory
    _installed = True


def uninstall() -> None:
    """Restore the real factories (existing proxies keep recording)."""
    global _installed
    threading.Lock = _REAL_LOCK
    threading.RLock = _REAL_RLOCK
    threading.Condition = _REAL_CONDITION
    _installed = False


def reset() -> None:
    """Drop recorded edges/events/sites (tests)."""
    with _guard:
        _edges.clear()
        _events.clear()
        _sites.clear()


def graph() -> Dict[str, Any]:
    """The dynamic order graph in the same JSON shape as the static
    one (``locksets.LockAnalysis.static_graph``)."""
    with _guard:
        return {
            "kind": "rsdl-lock-order-graph",
            "source": "dynamic",
            "nodes": [{"key": site, "kind": kind}
                      for site, kind in sorted(_sites.items())],
            "edges": [dict(e) for _, e in sorted(_edges.items())],
            "events": [dict(e) for e in _events],
        }


def cycles(order_graph: Optional[Dict[str, Any]] = None
           ) -> List[List[str]]:
    """Distinct-site cycles in the (dynamic) order graph — a non-empty
    result means two threads actually interleaved opposing acquisition
    orders in this process."""
    g = order_graph if order_graph is not None else graph()
    adj: Dict[str, List[str]] = {}
    for e in g.get("edges", []):
        if e["src"] != e["dst"]:
            adj.setdefault(e["src"], []).append(e["dst"])
    # Iterative DFS cycle collection over SCCs (no recursion limits).
    from ray_shuffling_data_loader_tpu.analysis.locksets import (
        _cycle_path, _tarjan)
    out: List[List[str]] = []
    for scc in _tarjan(adj):
        if len(scc) >= 2:
            out.append(_cycle_path(adj, scc))
    return out


def crosscheck(static_graph: Dict[str, Any],
               dynamic_graph: Optional[Dict[str, Any]] = None
               ) -> Dict[str, Any]:
    """Static<->dynamic comparison (see ``locksets.crosscheck``)."""
    from ray_shuffling_data_loader_tpu.analysis import locksets
    g = dynamic_graph if dynamic_graph is not None else graph()
    return locksets.crosscheck(static_graph, g)


def dump(path: Optional[str] = None) -> str:
    """Write the order-graph artifact; returns the path written."""
    path = path or os.environ.get("RSDL_LOCKSAN_OUT",
                                  ".rsdl-locksan-graph.json")
    payload = graph()
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")
    return path
