"""Causal cross-process trace analysis over flight-recorder dumps.

The PR 4 telemetry spine can say *which stage* is slow inside one
process; it cannot say which **tasks on which process** form an epoch's
critical path, nor quantify what fixing a stage would buy. This module
closes that gap, in the spirit of the critical-path analyses the input-
pipeline literature runs offline (tf.data's analysis framework, Plumber's
what-if rates), but over this repo's own lineage vocabulary:

**Deterministic trace context.** Every pipeline task is already a pure
function of the lineage key ``(seed, epoch, task)`` — the PR 3
determinism contract. :func:`trace_id` / :func:`span_id` derive stable
identifiers from that key alone, so two processes that never exchanged
a tracing header still agree on the id of "epoch 3, reduce task 2":
the context does not need to be *carried* to be *shared*. What IS
carried across process boundaries:

- ``multiqueue_service`` wire-v2 frames append the producer task id
  (the reducer that built the payload, read from the table's
  ``rsdl.trace`` schema metadata stamped at reduce time), so the
  consumer's ``frame_recv`` events name the server-side span they
  causally follow;
- ``parallel/transport.py`` frames already carry ``(epoch, reducer,
  file)`` tags — both ends record them;
- supervised restarts (``runtime/supervisor.py``) inherit
  ``RSDL_TRACE_DIR``: every incarnation dumps its recorder there at
  exit, and the deterministic ids stitch the incarnations back into
  one causal story.

**Merge + DAG + critical path.** :func:`merge_dumps` aligns per-process
recorder JSONL dumps onto one clock (each dump anchors ``t_mono`` to
``time_unix`` at dump time — same-host alignment, the topology we
ship). :func:`analyze` then builds a per-epoch DAG ordered by the
pipeline's stage ranks (map -> reduce -> queue/transport -> fetch ->
convert -> device transfer -> train step) and walks the classic
backward critical path: from the last-finishing terminal span, each
step attributes the wall-clock segment its span was the blocker for,
then jumps to the latest-finishing upstream span. Out of that fall
``self_time_ms`` (per-stage busy-interval union), per-``(stage, task)``
straggler ranking, and the what-if attribution
("2x faster reduce => -X% epoch time") whose savings are monotone in
the speedup by construction (:func:`whatif_saving_pct`).

**Perfetto export.** :func:`to_perfetto` emits chrome-trace JSON
(``ph: "X"`` duration events with real pid/tid mapping plus process /
thread name metadata) loadable in ``ui.perfetto.dev`` or
``chrome://tracing`` — the multi-process timeline next to the verdict.

Stdlib-only AND standalone on purpose: ``tools/rsdl_trace.py`` loads
this file by path on hosts without numpy/pyarrow/jax (the rsdl_top
pattern), so nothing here may import the package.
"""

from __future__ import annotations

import hashlib
import json
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

#: Causal rank of each event kind the DAG orders on. Lower rank =
#: further upstream. Work stages keep the attribution-stage naming
#: (runtime/telemetry.py STAGE_BY_KIND); link kinds (queue/transport
#: hops) sit between the work stages they connect. Kinds absent here
#: (faults, watchdog, leases) are carried through merges and exports
#: but take no part in the critical path.
STAGE_RANK: Dict[str, int] = {
    "map_read": 0,
    "reduce": 10,
    "reduce_gather": 10,
    "spill_write": 15,
    "spill_read": 16,
    "queue_put": 20,
    "transport_send": 20,
    "transport_recv": 25,
    "queue_get": 30,
    "frame_recv": 30,
    "fetch": 35,
    "queue_fetch": 35,
    "queue_wait": 40,
    "convert": 50,
    "device_transfer": 60,
    "train_step": 70,
}

#: Kind -> canonical stage name (the telemetry attribution vocabulary).
CANONICAL_STAGE: Dict[str, str] = {
    "reduce_gather": "reduce",
    "queue_fetch": "fetch",
}

#: Pure wait kinds: symptoms, not work — excluded from straggler
#: ranking and what-if (speeding up "waiting" is not an action).
WAIT_KINDS = frozenset({"queue_wait", "batch_wait"})

_EPS = 1e-9


def trace_id(seed: int, epoch: int) -> str:
    """Deterministic 16-hex-digit trace id for one epoch of one run.

    Any process that knows the lineage key derives the same id — no
    header needs to cross the wire for two dumps to agree.
    """
    digest = hashlib.sha1(f"rsdl-trace:{seed}:{epoch}".encode()).hexdigest()
    return digest[:16]


def span_id(seed: int, epoch: int, kind: str, task: Optional[int]) -> str:
    """Deterministic 16-hex-digit span id for one task's stage span."""
    digest = hashlib.sha1(
        f"rsdl-span:{seed}:{epoch}:{kind}:{task}".encode()).hexdigest()
    return digest[:16]


# ---------------------------------------------------------------------------
# Dump loading + multi-process merge
# ---------------------------------------------------------------------------


def load_dump(path: str) -> Dict[str, Any]:
    """One recorder JSONL dump -> ``{"meta", "events", "threads"}``.

    Torn tails are tolerated (a dump written while the process died may
    end mid-line); ``threads`` maps thread ident -> name from the
    dump's ``thread_stack`` records.
    """
    meta: Dict[str, Any] = {}
    events: List[Dict[str, Any]] = []
    threads: Dict[int, str] = {}
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn tail: keep what parsed
            kind = rec.get("kind")
            if kind == "dump_meta":
                meta = rec
            elif kind == "thread_stack":
                ident = rec.get("ident")
                if ident is not None:
                    threads[int(ident)] = rec.get("thread", f"tid-{ident}")
            else:
                events.append(rec)
    meta.setdefault("pid", 0)
    meta.setdefault("path", path)
    return {"meta": meta, "events": events, "threads": threads}


def merge_dumps(paths: Sequence[str]) -> Dict[str, Any]:
    """Merge per-process dumps onto one clock.

    Keeps only the LATEST dump per pid (highest ``events_total``): the
    ring is cumulative, so a process's later dump supersedes its
    earlier one — two dumps from one pid would double-count every
    retained event. Event times are aligned by each dump's
    ``time_unix - t_mono`` anchor (same-host alignment); every merged
    event gains ``pid``, absolute ``t1``/``t0`` seconds, and the
    originating thread's name when known.
    """
    by_pid: Dict[int, Dict[str, Any]] = {}
    for path in paths:
        dump = load_dump(path)
        pid = dump["meta"]["pid"]
        prev = by_pid.get(pid)
        if prev is None or (dump["meta"].get("events_total", 0)
                            >= prev["meta"].get("events_total", 0)):
            by_pid[pid] = dump
    events: List[Dict[str, Any]] = []
    processes: List[Dict[str, Any]] = []
    threads: Dict[Tuple[int, int], str] = {}
    for pid, dump in sorted(by_pid.items()):
        meta = dump["meta"]
        processes.append(meta)
        anchor = meta.get("time_unix", 0.0) - meta.get("t_mono", 0.0)
        for ident, name in dump["threads"].items():
            threads[(pid, ident)] = name
        for raw in dump["events"]:
            ev = dict(raw)
            ev["pid"] = pid
            t_mono = float(ev.get("t_mono", 0.0))
            dur = float(ev.get("dur_s") or 0.0)
            ev["t1"] = anchor + t_mono
            ev["t0"] = ev["t1"] - dur
            tid = ev.get("tid")
            if tid is not None and (pid, tid) in threads:
                ev["thread"] = threads[(pid, tid)]
            events.append(ev)
    events.sort(key=lambda e: e["t1"])
    return {"processes": processes, "events": events, "threads": threads}


def _normalize_in_process(events: Iterable[Dict[str, Any]], pid: int = 0
                          ) -> List[Dict[str, Any]]:
    """Recorder ``events()`` dicts (single process, monotonic clock) ->
    the merged-event shape :func:`analyze` consumes."""
    out = []
    for raw in events:
        ev = dict(raw)
        ev.setdefault("pid", pid)
        t_mono = float(ev.get("t_mono", 0.0))
        dur = float(ev.get("dur_s") or 0.0)
        ev["t1"] = t_mono
        ev["t0"] = t_mono - dur
        out.append(ev)
    out.sort(key=lambda e: e["t1"])
    return out


# ---------------------------------------------------------------------------
# DAG + critical path
# ---------------------------------------------------------------------------


def _spans(events: Sequence[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Durational stage/link spans (the DAG's nodes). Speculative
    duplicate attempts (``spec`` attr, plan/scheduler.py) are excluded:
    they share the original's lineage key by construction, and counting
    both would double-bill the stage."""
    return [e for e in events
            if e.get("dur_s") and e.get("kind") in STAGE_RANK
            and not e.get("fault") and not e.get("spec")]


def _epoch_windows(spans: Sequence[Dict[str, Any]]
                   ) -> Dict[int, Tuple[float, float]]:
    windows: Dict[int, List[float]] = {}
    for s in spans:
        epoch = s.get("epoch")
        if epoch is None:
            continue
        w = windows.setdefault(int(epoch), [s["t0"], s["t1"]])
        w[0] = min(w[0], s["t0"])
        w[1] = max(w[1], s["t1"])
    return {e: (w[0], w[1]) for e, w in windows.items()}


def assign_epochs(spans: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Give epoch-less spans (e.g. ``device_transfer`` attempt sequences)
    the epoch whose window contains their midpoint, so per-epoch DAGs
    see the whole pipeline. Spans matching no window stay epoch-less."""
    windows = _epoch_windows(spans)
    if not windows:
        return spans
    for s in spans:
        if s.get("epoch") is not None:
            continue
        mid = (s["t0"] + s["t1"]) / 2.0
        for epoch, (lo, hi) in windows.items():
            if lo - _EPS <= mid <= hi + _EPS:
                s["epoch"] = epoch
                break
    return spans


def _union_length(intervals: List[Tuple[float, float]]) -> float:
    """Total covered length of possibly-overlapping intervals (parallel
    tasks of one stage are not double-billed)."""
    if not intervals:
        return 0.0
    intervals.sort()
    total = 0.0
    cur_lo, cur_hi = intervals[0]
    for lo, hi in intervals[1:]:
        if lo > cur_hi:
            total += cur_hi - cur_lo
            cur_lo, cur_hi = lo, hi
        else:
            cur_hi = max(cur_hi, hi)
    return total + (cur_hi - cur_lo)


def _critical_path_epoch(spans: List[Dict[str, Any]],
                         window: Tuple[float, float]
                         ) -> List[Dict[str, Any]]:
    """Backward critical-path walk over one epoch's spans.

    From the latest-finishing span of the most-downstream stage
    present, repeatedly: attribute the segment where the current span
    was the blocker (down to the latest-finishing upstream span's end),
    then continue from that predecessor. Returns segments in causal
    (start-to-finish) order: ``{stage, kind, task, pid, t0, t1}``.
    """
    if not spans:
        return []
    t_begin = window[0]
    max_rank = max(STAGE_RANK[s["kind"]] for s in spans)
    terminal = max((s for s in spans if STAGE_RANK[s["kind"]] == max_rank),
                   key=lambda s: s["t1"])
    segments: List[Dict[str, Any]] = []
    visited = {id(terminal)}
    cur = terminal
    cursor = terminal["t1"]
    # Each iteration either consumes one span or stops; bounded by the
    # span count even in pathological clock configurations.
    for _ in range(len(spans) + 1):
        lo = max(cur["t0"], t_begin)
        pred = None
        pred_t1 = -float("inf")
        cur_rank = STAGE_RANK[cur["kind"]]
        for s in spans:
            if id(s) in visited or STAGE_RANK[s["kind"]] > cur_rank:
                continue
            if s["t1"] <= cursor + _EPS and s["t1"] > pred_t1:
                pred, pred_t1 = s, s["t1"]
        seg_lo = max(lo, pred_t1) if pred is not None else lo
        if cursor - seg_lo > _EPS:
            segments.append({
                "stage": CANONICAL_STAGE.get(cur["kind"], cur["kind"]),
                "kind": cur["kind"],
                "task": cur.get("task"),
                "pid": cur.get("pid"),
                "t0": seg_lo,
                "t1": cursor,
            })
        if pred is None or pred_t1 <= t_begin + _EPS:
            break
        visited.add(id(pred))
        cur = pred
        cursor = min(pred_t1, seg_lo)
    segments.reverse()
    return segments


def whatif_saving_pct(cp_ms: float, wall_ms: float,
                      speedup: float) -> float:
    """Epoch-time % saved if the stage ran ``speedup``x faster, by the
    critical-path attribution: only the stage's time ON the path can
    shrink the epoch, and it shrinks by ``1 - 1/speedup`` of itself.
    Monotone (non-decreasing) in ``speedup`` by construction."""
    if wall_ms <= 0 or speedup <= 0:
        return 0.0
    saved = cp_ms * (1.0 - 1.0 / speedup)
    return max(0.0, 100.0 * saved / wall_ms)


def analyze(events: Sequence[Dict[str, Any]],
            epoch: Optional[int] = None,
            whatif_speedup: float = 2.0) -> Dict[str, Any]:
    """Full causal analysis over merged (or in-process recorder) events.

    Returns::

        {
          "epochs": [ids analyzed],
          "wall_ms": total epoch-window wall,
          "critical_path": [{"stage", "cp_ms", "pct"} ... desc by cp_ms],
          "path_segments": causal segment walk (per epoch, flattened),
          "self_time_ms": {stage: busy-union ms},
          "stragglers": [{"stage", "task", "self_ms", "cp_ms"} ...],
          "whatif": {stage: {"speedup", "epoch_time_saved_pct"}},
        }
    """
    if events and "t1" not in events[0]:
        events = _normalize_in_process(events)
    spans = assign_epochs(_spans(events))
    windows = _epoch_windows(spans)
    epochs = sorted(windows) if epoch is None else \
        [e for e in sorted(windows) if e == epoch]
    wall_s = sum(windows[e][1] - windows[e][0] for e in epochs)
    cp_by_stage: Dict[str, float] = {}
    cp_by_task: Dict[Tuple[str, Any], float] = {}
    all_segments: List[Dict[str, Any]] = []
    self_intervals: Dict[str, List[Tuple[float, float]]] = {}
    self_by_task: Dict[Tuple[str, Any], float] = {}
    for e in epochs:
        epoch_spans = [s for s in spans if s.get("epoch") == e]
        for s in epoch_spans:
            stage = CANONICAL_STAGE.get(s["kind"], s["kind"])
            self_intervals.setdefault(stage, []).append((s["t0"], s["t1"]))
            if s["kind"] not in WAIT_KINDS:
                key = (stage, s.get("task"))
                self_by_task[key] = self_by_task.get(key, 0.0) \
                    + (s["t1"] - s["t0"])
        for seg in _critical_path_epoch(epoch_spans, windows[e]):
            seg["epoch"] = e
            all_segments.append(seg)
            dur = seg["t1"] - seg["t0"]
            cp_by_stage[seg["stage"]] = cp_by_stage.get(seg["stage"], 0.0) \
                + dur
            if seg["kind"] not in WAIT_KINDS:
                key = (seg["stage"], seg["task"])
                cp_by_task[key] = cp_by_task.get(key, 0.0) + dur
    wall_ms = wall_s * 1e3
    critical_path = sorted(
        ({"stage": stage, "cp_ms": round(ms * 1e3, 3),
          "pct": round(100.0 * ms / wall_s, 2) if wall_s > 0 else 0.0}
         for stage, ms in cp_by_stage.items()),
        key=lambda d: -d["cp_ms"])
    stragglers = sorted(
        ({"stage": stage, "task": task,
          "self_ms": round(self_by_task.get((stage, task), 0.0) * 1e3, 3),
          "cp_ms": round(cp_by_task.get((stage, task), 0.0) * 1e3, 3)}
         for stage, task in
         set(cp_by_task) | set(self_by_task)),
        key=lambda d: (-d["cp_ms"], -d["self_ms"]))
    whatif = {
        stage: {
            "speedup": whatif_speedup,
            "epoch_time_saved_pct": round(
                whatif_saving_pct(ms * 1e3, wall_ms, whatif_speedup), 2),
        }
        for stage, ms in cp_by_stage.items()
        if stage not in WAIT_KINDS
    }
    return {
        "epochs": epochs,
        "wall_ms": round(wall_ms, 3),
        "critical_path": critical_path,
        "path_segments": all_segments,
        "self_time_ms": {
            stage: round(_union_length(iv) * 1e3, 3)
            for stage, iv in self_intervals.items()
        },
        "stragglers": stragglers,
        "whatif": whatif,
    }


def stage_table(analysis: Dict[str, Any]) -> Dict[str, Dict[str, float]]:
    """Per-stage summary of one :func:`analyze` result, normalized per
    epoch so two runs with different epoch counts compare directly:
    ``{stage: {cp_ms, cp_ms_per_epoch, pct, self_ms}}``. The epoch
    normalization is what lets ``runtime/regress.py`` align stages
    across rounds by ``(kind, epoch-normalized rank)`` instead of raw
    wall totals."""
    n_epochs = max(1, len(analysis.get("epochs") or []))
    self_ms = analysis.get("self_time_ms", {})
    table: Dict[str, Dict[str, float]] = {}
    for entry in analysis.get("critical_path", []):
        stage = entry["stage"]
        table[stage] = {
            "cp_ms": entry["cp_ms"],
            "cp_ms_per_epoch": round(entry["cp_ms"] / n_epochs, 3),
            "pct": entry["pct"],
            "self_ms": self_ms.get(stage, 0.0),
        }
    # Stages with self time but no critical-path presence still appear
    # (cp 0): a stage ENTERING the path between two rounds needs its
    # baseline row to diff against.
    for stage, ms in self_ms.items():
        table.setdefault(stage, {
            "cp_ms": 0.0, "cp_ms_per_epoch": 0.0, "pct": 0.0,
            "self_ms": ms,
        })
    return table


def bench_fields(events: Sequence[Dict[str, Any]],
                 whatif_speedup: float = 2.0) -> Dict[str, Any]:
    """The bench-record slice of :func:`analyze`: compact
    ``critical_path`` / ``self_time_ms`` / ``whatif`` / straggler
    fields over the recorder's retained window (ring overwrite means
    *recent* epochs — exactly the steady state a bench wants)."""
    analysis = analyze(events, whatif_speedup=whatif_speedup)
    stragglers = [s for s in analysis["stragglers"] if s["cp_ms"] > 0]
    return {
        "critical_path": analysis["critical_path"][:8],
        "self_time_ms": analysis["self_time_ms"],
        "whatif": analysis["whatif"],
        "trace_straggler": stragglers[0] if stragglers else None,
        "trace_epochs_analyzed": len(analysis["epochs"]),
    }


# ---------------------------------------------------------------------------
# Perfetto / chrome-trace export
# ---------------------------------------------------------------------------


def to_perfetto(merged: Dict[str, Any], seed: int = 0) -> Dict[str, Any]:
    """Merged trace -> chrome-trace JSON (``ui.perfetto.dev`` /
    ``chrome://tracing``). Duration events get real pid/tid, lineage
    args, and deterministic trace/span ids; zero-duration events export
    as instants; process/thread name metadata rides along."""
    events = merged["events"] if isinstance(merged, dict) else \
        _normalize_in_process(merged)
    processes = merged.get("processes", []) if isinstance(merged, dict) \
        else []
    threads = merged.get("threads", {}) if isinstance(merged, dict) else {}
    if not events:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    base = min(e["t0"] for e in events)
    out: List[Dict[str, Any]] = []
    for meta in processes:
        out.append({"ph": "M", "name": "process_name",
                    "pid": meta["pid"], "tid": 0,
                    "args": {"name": meta.get("role",
                                              f"pid {meta['pid']}")}})
    for (pid, tid), name in threads.items():
        out.append({"ph": "M", "name": "thread_name", "pid": pid,
                    "tid": tid, "args": {"name": name}})
    for e in events:
        pid = int(e.get("pid") or 0)
        tid = int(e.get("tid") or pid)
        epoch = e.get("epoch")
        task = e.get("task")
        args: Dict[str, Any] = {
            k: v for k, v in e.items()
            if k not in ("t_mono", "t0", "t1", "pid", "tid", "kind",
                         "dur_s", "thread")
        }
        if epoch is not None:
            args["trace_id"] = trace_id(seed, int(epoch))
            args["span_id"] = span_id(seed, int(epoch), e["kind"], task)
        record = {
            "name": e["kind"],
            "cat": CANONICAL_STAGE.get(e["kind"], e["kind"]),
            "pid": pid,
            "tid": tid,
            "ts": round((e["t0"] - base) * 1e6, 3),
            "args": args,
        }
        if e.get("dur_s"):
            record["ph"] = "X"
            record["dur"] = round(float(e["dur_s"]) * 1e6, 3)
        else:
            record["ph"] = "i"
            record["s"] = "t"
        out.append(record)
    return {"traceEvents": out, "displayTimeUnit": "ms"}
