"""Structured-event flight recorder + per-batch bottleneck attribution.

The pipeline's observability used to be three disconnected islands —
``stats.py`` wall-clock collectors, ``utils/tracing.py`` profiler spans,
and the watchdog/fault snapshot dicts — with no shared identity for an
event: "epoch 3 stalled" could not be joined against "reducer 2 retried
a fetch" without log scraping. This module is the spine they all report
through:

**Flight recorder** — a lock-cheap, fixed-size ring of structured
events ``(t_mono, kind, epoch, task, batch, dur_s, attrs)`` emitted
from every pipeline stage (shuffle map read, reduce gather, queue
put/get/fetch, transport send/recv, spill write/read, device transfer,
convert, batch wait, train step) plus watchdog stalls and fault
injections/retries/recomputes. Event ``kind`` reuses the 10 fault-site
names from :mod:`runtime.faults` wherever a stage has a fault site, so
a chaos run's fault events and its telemetry events correlate by
``(kind, epoch, task)`` BY CONSTRUCTION. The ring is dumpable as JSONL
on demand (:func:`dump`), on watchdog escalation (runtime/watchdog.py),
and on ``SIGUSR1`` (:func:`install_signal_dump`) together with
named-thread stack traces.

**Bottleneck attribution** — the one question a production loader must
answer online, the way tf.data's analysis framework and Plumber answer
it for TensorFlow input pipelines: *is the device waiting on the
loader, and on which stage?* Stage-kind events feed per-epoch
fixed-bucket histograms (mergeable — :mod:`runtime.metrics`), and
:meth:`StageAttribution.epoch_verdict` decomposes each epoch into
``{bottleneck_stage, stall_pct, p50/p95/p99 per stage}``: when the
consumer's batch-wait share of wall clock exceeds the policy threshold
the verdict names the busiest producer stage; otherwise the pipeline
keeps up and the verdict is ``train_step`` (compute-bound — the goal
state). The verdict lands in bench JSON, the trial CSV, and a human
one-liner logged at each epoch's completion.

Every event also feeds the metrics registry (``rsdl_events_total`` by
kind, ``rsdl_stage_seconds`` by stage), so the exposition endpoint and
``tools/rsdl_top.py`` see the same truth as the recorder.

Overhead: disabled, ``record()`` is one global load (the
:mod:`runtime.faults` fast-path pattern). Enabled, it is one
``monotonic()`` read, one tuple, and two lock round-trips — measured
by :func:`measure_record_overhead` and reported by bench.py as
``telemetry_overhead_pct`` (contract: <= 2% of the ingest path).

Stdlib-only (importable before jax/pyarrow and from the native layer).
"""

from __future__ import annotations

import contextlib
import io
import json
import os
import signal
import sys
import threading
import time
import traceback
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

from ray_shuffling_data_loader_tpu.runtime import metrics
from ray_shuffling_data_loader_tpu.utils.logger import setup_custom_logger

logger = setup_custom_logger(__name__)

#: Event kind -> attribution stage. Kinds reuse the fault-site
#: vocabulary (runtime/faults.py) wherever the stage has a fault site,
#: so chaos and telemetry events join on (kind, epoch, task). Kinds not
#: in this table (queue_put, queue_get, transport_send/recv,
#: spill_write/read, watchdog_stall, fault bookkeeping) are recorded and
#: exported but are not latency-decomposition stages: queue_get's wait
#: is owned by the dataset layer's epoch-tagged ``queue_wait`` event
#: (counting both would double-bill the same blocked time).
STAGE_BY_KIND: Dict[str, str] = {
    "map_read": "map_read",
    "reduce_gather": "reduce",
    "queue_wait": "queue_wait",
    "queue_fetch": "fetch",
    "convert": "convert",
    "device_transfer": "device_transfer",
    "train_step": "train_step",
}

#: The decomposition's stage order (CSV columns, bench JSON, rsdl_top).
STAGES: Tuple[str, ...] = ("map_read", "reduce", "queue_wait", "fetch",
                           "convert", "device_transfer", "train_step")

#: Stages that do WORK (bottleneck candidates). Wait stages are
#: symptoms: a consumer blocked in queue_wait means an upstream work
#: stage is slow, and the verdict should name that stage.
_WORK_STAGES: Tuple[str, ...] = ("map_read", "reduce", "fetch", "convert",
                                 "device_transfer")

Event = Tuple[float, str, Optional[int], Optional[int], Optional[int],
              Optional[float], Optional[int], Optional[dict]]


class FlightRecorder:
    """Fixed-size ring buffer of structured events.

    Overwrite semantics: the ring holds the most recent ``capacity``
    events; ``total_recorded`` keeps counting past the wrap so readers
    can tell how much history was shed.
    """

    def __init__(self, capacity: int = 4096):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._buf: List[Optional[Event]] = [None] * capacity
        self._idx = 0
        self._lock = threading.Lock()

    def record(self, event: Event) -> None:
        with self._lock:
            self._buf[self._idx % self.capacity] = event
            self._idx += 1

    @property
    def total_recorded(self) -> int:
        with self._lock:
            return self._idx

    def events(self) -> List[Dict[str, Any]]:
        """Retained events, oldest first, as dicts (None fields elided)."""
        with self._lock:
            idx = self._idx
            if idx <= self.capacity:
                raw = self._buf[:idx]
            else:
                pivot = idx % self.capacity
                raw = self._buf[pivot:] + self._buf[:pivot]
        out = []
        for ev in raw:
            if ev is None:
                continue
            t_mono, kind, epoch, task, batch, dur_s, tid, attrs = ev
            d: Dict[str, Any] = {"t_mono": t_mono, "kind": kind}
            if epoch is not None:
                d["epoch"] = epoch
            if task is not None:
                d["task"] = task
            if batch is not None:
                d["batch"] = batch
            if dur_s is not None:
                d["dur_s"] = dur_s
            if tid is not None:
                d["tid"] = tid
            if attrs:
                d.update(attrs)
            out.append(d)
        return out


class StageAttribution:
    """Online per-epoch latency decomposition over stage events.

    Bounded state: per (epoch, stage) one fixed-bucket histogram plus
    totals, pruned to the most recent ``max_epochs`` epochs. Epoch-less
    stage events (e.g. a bare queue drained outside any dataset epoch)
    land in the run aggregate only.
    """

    _MAX_EPOCHS = 64

    def __init__(self, stall_threshold_pct: float = 10.0):
        self._lock = threading.Lock()
        self.stall_threshold_pct = stall_threshold_pct
        # epoch -> stage -> Histogram (epoch None = unattributed)
        self._hists: Dict[Optional[int], Dict[str, metrics.Histogram]] = {}
        # epoch -> (batch_wait_total_s, batch_count)
        self._waits: Dict[Optional[int], List[float]] = {}
        # epoch -> [first_t, last_t] monotonic bounds (wall clock of epoch)
        self._bounds: Dict[Optional[int], List[float]] = {}
        self._verdict_logged: set = set()

    def observe(self, stage: str, epoch: Optional[int], dur_s: float,
                t: float) -> None:
        with self._lock:
            per_epoch = self._hists.setdefault(epoch, {})
            hist = per_epoch.get(stage)
            if hist is None:
                hist = per_epoch[stage] = metrics.Histogram()
            bounds = self._bounds.setdefault(epoch, [t - dur_s, t])
            bounds[0] = min(bounds[0], t - dur_s)
            bounds[1] = max(bounds[1], t)
            if epoch is not None and len(self._hists) > self._MAX_EPOCHS:
                self._prune_locked()
        hist.observe(dur_s)

    def observe_wait(self, epoch: Optional[int], dur_s: float,
                     t: float) -> None:
        with self._lock:
            wait = self._waits.setdefault(epoch, [0.0, 0])
            wait[0] += dur_s
            wait[1] += 1
            bounds = self._bounds.setdefault(epoch, [t - dur_s, t])
            bounds[0] = min(bounds[0], t - dur_s)
            bounds[1] = max(bounds[1], t)

    def _prune_locked(self) -> None:
        real = sorted(e for e in self._hists if e is not None)
        for stale in real[:len(real) - self._MAX_EPOCHS]:
            self._hists.pop(stale, None)
            self._waits.pop(stale, None)
            self._bounds.pop(stale, None)

    def _verdict_locked(self, epochs: List[Optional[int]]
                        ) -> Optional[Dict[str, Any]]:
        merged: Dict[str, metrics.Histogram] = {}
        wait_total = 0.0
        wait_count = 0
        wall = 0.0
        seen = False
        for epoch in epochs:
            for stage, hist in self._hists.get(epoch, {}).items():
                seen = True
                agg = merged.get(stage)
                if agg is None:
                    agg = merged[stage] = metrics.Histogram(hist.bounds)
                agg.merge(hist)
            if epoch in self._waits:
                seen = True
                wait_total += self._waits[epoch][0]
                wait_count += int(self._waits[epoch][1])
            if epoch in self._bounds:
                lo, hi = self._bounds[epoch]
                wall += max(0.0, hi - lo)
        if not seen:
            return None
        stall_pct = 100.0 * wait_total / wall if wall > 0 else 0.0
        stages = {}
        for stage in STAGES:
            hist = merged.get(stage)
            if hist is None or hist.count == 0:
                continue
            stages[stage] = {
                "count": hist.count,
                "total_s": round(hist.sum, 6),
                "p50_ms": round(hist.percentile(0.50) * 1e3, 3),
                "p95_ms": round(hist.percentile(0.95) * 1e3, 3),
                "p99_ms": round(hist.percentile(0.99) * 1e3, 3),
            }
        work = {s: d["total_s"] for s, d in stages.items()
                if s in _WORK_STAGES}
        if stall_pct <= self.stall_threshold_pct:
            # The consumer rarely waited: the pipeline keeps up and the
            # trainer's own step is the bottleneck — the goal state.
            bottleneck = "train_step"
        elif work:
            bottleneck = max(work, key=work.get)
        else:
            bottleneck = "queue_wait" if "queue_wait" in stages else "unknown"
        return {
            "bottleneck_stage": bottleneck,
            "stall_pct": round(stall_pct, 3),
            "batch_wait_s": round(wait_total, 6),
            "batches_waited": wait_count,
            "wall_s": round(wall, 6),
            "stages": stages,
        }

    def epoch_verdict(self, epoch: int) -> Optional[Dict[str, Any]]:
        with self._lock:
            return self._verdict_locked([epoch])

    def run_summary(self) -> Optional[Dict[str, Any]]:
        """Verdict over every retained epoch (plus unattributed events)."""
        with self._lock:
            return self._verdict_locked(list(self._hists)
                                        + [e for e in self._waits
                                           if e not in self._hists])

    def epoch_complete(self, epoch: int, source: str = "") -> None:
        """Log the epoch's one-line verdict (once per epoch per process;
        the dataset layer and the JAX binding both call this and the
        first completion wins)."""
        with self._lock:
            if epoch in self._verdict_logged:
                return
            self._verdict_logged.add(epoch)
            verdict = self._verdict_locked([epoch])
        if verdict is None:
            return
        busiest = verdict["stages"].get(verdict["bottleneck_stage"], {})
        logger.info(
            "epoch %d bottleneck=%s stall=%.1f%% (wait %.2fs over %.2fs"
            "%s); %s p95=%.1fms over %d events",
            epoch, verdict["bottleneck_stage"], verdict["stall_pct"],
            verdict["batch_wait_s"], verdict["wall_s"],
            f", {source}" if source else "",
            verdict["bottleneck_stage"], busiest.get("p95_ms", 0.0),
            busiest.get("count", 0))


# ---------------------------------------------------------------------------
# Process-wide wiring (the runtime/faults.py fast-path pattern: the
# disabled case is one global load, no env lookup, no lock)
# ---------------------------------------------------------------------------

_ENABLED = True
_lock = threading.Lock()
_recorder: Optional[FlightRecorder] = None
_attribution: Optional[StageAttribution] = None
_events_counter_cache: Dict[str, metrics.Counter] = {}
_stage_hist_cache: Dict[str, metrics.Histogram] = {}
#: thread ident -> currently-open span kind (the sampling profiler
#: reads this to bill stack samples to pipeline stages). Plain-dict
#: writes are GIL-atomic; no lock on the hot path.
_active_kinds: Dict[int, str] = {}
#: Lineage seed of the run this process participates in (trace.py's
#: deterministic trace/span ids derive from it). Stamped into dumps.
_trace_seed: Optional[int] = None
_exit_dump_registered = False


def _apply_enabled_locked() -> None:
    """Swap the public entry points between the real implementations and
    no-ops: the RSDL_TELEMETRY=0 hard-off fast path. Every caller uses
    module-attribute access (``rt_telemetry.record(...)``), so the swap
    takes effect process-wide; the disabled cost is one no-op call
    (bench proves it via :func:`measure_disabled_overhead`)."""
    g = globals()
    if _ENABLED:
        g["record"] = _record_impl
        g["span"] = _span_impl
        g["span_begin"] = _span_begin_impl
        g["span_end"] = _span_end_impl
        g["stamp"] = _stamp_impl
    else:
        g["record"] = _noop_record
        g["span"] = _noop_span
        g["span_begin"] = _noop_span_begin
        g["span_end"] = _noop_span_end
        g["stamp"] = _noop_stamp


def _register_exit_dump_locked() -> None:
    """With a trace dir configured (RSDL_TRACE_DIR), every process dumps
    its recorder there at interpreter exit — the per-process half of the
    multi-process merge contract (tools/rsdl_trace.py). The dir is
    re-resolved at fire time so a scene that unsets the env after its
    run leaves no stray dump."""
    global _exit_dump_registered
    if _exit_dump_registered:
        return
    _exit_dump_registered = True
    import atexit

    def _exit_dump() -> None:
        from ray_shuffling_data_loader_tpu.runtime import policy
        if not policy.resolve("telemetry", "trace_dir"):
            return
        try:
            dump(reason="atexit")
        except OSError:
            logger.exception("telemetry exit dump failed")

    atexit.register(_exit_dump)


def _init_locked() -> None:
    global _recorder, _attribution, _ENABLED
    if _recorder is not None:
        return
    from ray_shuffling_data_loader_tpu.runtime import policy
    _ENABLED = policy.resolve("telemetry", "telemetry")
    _recorder = FlightRecorder(
        capacity=int(policy.resolve("telemetry", "telemetry_capacity")))
    _attribution = StageAttribution(stall_threshold_pct=policy.resolve(
        "telemetry", "bottleneck_stall_threshold_pct"))
    _apply_enabled_locked()
    if policy.resolve("telemetry", "trace_dir"):
        _register_exit_dump_locked()


def recorder() -> FlightRecorder:
    """THE process-wide flight recorder."""
    with _lock:
        _init_locked()
        return _recorder


def attribution() -> StageAttribution:
    """THE process-wide bottleneck attributor."""
    with _lock:
        _init_locked()
        return _attribution


def enabled() -> bool:
    with _lock:
        _init_locked()
    return _ENABLED


def configure(enabled_flag: Optional[bool] = None,
              capacity: Optional[int] = None) -> None:
    """Reconfigure in place (tests, bench): a fresh ring / attributor,
    resolving unset arguments from the policy registry."""
    global _ENABLED, _recorder, _attribution
    from ray_shuffling_data_loader_tpu.runtime import policy
    with _lock:
        _ENABLED = (policy.resolve("telemetry", "telemetry")
                    if enabled_flag is None else bool(enabled_flag))
        _recorder = FlightRecorder(capacity=int(
            policy.resolve("telemetry", "telemetry_capacity",
                           override=capacity)))
        _attribution = StageAttribution(stall_threshold_pct=policy.resolve(
            "telemetry", "bottleneck_stall_threshold_pct"))
        _apply_enabled_locked()
        if policy.resolve("telemetry", "trace_dir"):
            _register_exit_dump_locked()


def set_trace_seed(seed: int) -> None:
    """Declare the lineage seed this process's run derives from. The
    deterministic trace/span ids (runtime/trace.py) are functions of
    ``(seed, epoch, task)``; stamping the seed here puts it into every
    dump's meta so offline merges can re-derive the same ids the other
    processes used. Recorded once per distinct seed."""
    global _trace_seed
    if _trace_seed == seed:
        return
    _trace_seed = seed
    record("trace_meta", seed=seed)


def trace_seed() -> Optional[int]:
    return _trace_seed


#: Thread-local marker set while a SPECULATIVE backup attempt runs
#: (plan/scheduler.py first-completion-wins duplicates). Events recorded
#: under it carry a ``spec`` attr and skip the attribution/histogram
#: observation, so a duplicated attempt can never double-count a stage
#: in trace merge or bottleneck attribution — the original attempt owns
#: the canonical span for its lineage key.
_speculative = threading.local()


@contextlib.contextmanager
def speculative(attempt: int = 1) -> Iterator[None]:
    """Mark the enclosed work as a speculative duplicate attempt."""
    prev = getattr(_speculative, "attempt", 0)
    _speculative.attempt = attempt
    try:
        yield
    finally:
        _speculative.attempt = prev


def speculative_attempt() -> int:
    """The calling thread's active speculative attempt (0 = original)."""
    return getattr(_speculative, "attempt", 0)


def _record_impl(kind: str, epoch: Optional[int] = None,
                 task: Optional[int] = None, batch: Optional[int] = None,
                 dur_s: Optional[float] = None, t: Optional[float] = None,
                 **attrs: Any) -> None:
    """Record one structured event (free when telemetry is disabled).

    ``t`` is the event's END in ``time.monotonic()`` terms (defaults to
    now); events with ``dur_s`` therefore span ``[t - dur_s, t]``. The
    recording thread's ident rides along so multi-thread traces export
    with real tids (Perfetto pid/tid mapping).
    """
    if not _ENABLED:
        return
    rec = _recorder
    if rec is None:
        rec = recorder()
        if not _ENABLED:
            return
    spec = getattr(_speculative, "attempt", 0)
    if spec:
        attrs = {**attrs, "spec": spec}
    now = time.monotonic() if t is None else t
    rec.record((now, kind, epoch, task, batch, dur_s,
                threading.get_ident(), attrs or None))
    if spec:
        # Ring-only: the duplicate attempt is visible evidence (joined to
        # the original by its lineage key) but must not double-count the
        # stage in counters, histograms or bottleneck attribution.
        return
    events_counter = _events_counter_cache.get(kind)
    if events_counter is None:
        events_counter = _events_counter_cache[kind] = metrics.counter(
            "rsdl_events_total", "flight-recorder events by kind",
            kind=kind)
    events_counter.inc()
    if dur_s is None:
        return
    if kind == "batch_wait":
        attribution().observe_wait(epoch, dur_s, now)
        hist = _stage_hist_cache.get("batch_wait")
        if hist is None:
            hist = _stage_hist_cache["batch_wait"] = metrics.histogram(
                "rsdl_batch_wait_seconds",
                "consumer time blocked waiting on the next batch")
        hist.observe(dur_s)
        return
    stage = STAGE_BY_KIND.get(kind)
    if stage is None:
        return
    attribution().observe(stage, epoch, dur_s, now)
    hist = _stage_hist_cache.get(stage)
    if hist is None:
        hist = _stage_hist_cache[stage] = metrics.histogram(
            "rsdl_stage_seconds", "per-event stage latency", stage=stage)
    hist.observe(dur_s)


@contextlib.contextmanager
def _span_impl(kind: str, epoch: Optional[int] = None,
               task: Optional[int] = None, batch: Optional[int] = None,
               **attrs: Any) -> Iterator[None]:
    """Record the enclosed block as one duration event (disabled: the
    overhead is the generator frame alone). While open, the thread's
    active kind is published for the sampling profiler's stage
    attribution (runtime/profiler.py)."""
    if not _ENABLED:
        yield
        return
    ident = threading.get_ident()
    prev = _active_kinds.get(ident)
    _active_kinds[ident] = kind
    start = time.monotonic()
    try:
        yield
    finally:
        end = time.monotonic()
        if prev is None:
            _active_kinds.pop(ident, None)
        else:
            _active_kinds[ident] = prev
        record(kind, epoch=epoch, task=task, batch=batch,
               dur_s=end - start, t=end, **attrs)


def _span_begin_impl(kind: str, epoch: Optional[int] = None,
                     task: Optional[int] = None,
                     batch: Optional[int] = None,
                     **attrs: Any) -> Optional[tuple]:
    """Open a span that cannot be a ``with`` block (a wait measured
    across loop iterations, a handoff between threads). Returns an
    opaque token for :func:`span_end` — which MUST run on all exit
    paths (``finally``); the ``span-unbalanced`` rsdl-lint rule enforces
    the shape."""
    if not _ENABLED:
        return None
    ident = threading.get_ident()
    prev = _active_kinds.get(ident)
    _active_kinds[ident] = kind
    return (kind, epoch, task, batch, attrs, time.monotonic(), prev, ident)


def _span_end_impl(token: Optional[tuple], **late_attrs: Any) -> None:
    """Close a :func:`span_begin` token, recording the duration event.
    ``None`` tokens (telemetry disabled at begin time) are a no-op, so
    callers never need to guard."""
    if token is None:
        return
    kind, epoch, task, batch, attrs, start, prev, ident = token
    if prev is None:
        _active_kinds.pop(ident, None)
    else:
        _active_kinds[ident] = prev
    end = time.monotonic()
    if late_attrs:
        attrs = {**attrs, **late_attrs}
    record(kind, epoch=epoch, task=task, batch=batch,
           dur_s=end - start, t=end, **attrs)


def active_kinds() -> Dict[int, str]:
    """Snapshot of thread ident -> currently-open span kind."""
    return dict(_active_kinds)


def observe_stage(kind: str, epoch: Optional[int] = None,
                  task: Optional[int] = None, dur_s: float = 0.0) -> None:
    """Feed the bottleneck attribution + stage histograms with a duration
    measured in ANOTHER process.

    The process-pool workers (procpool.py) record the real ``map_read`` /
    ``reduce_gather`` events in their own flight recorders (dumped via
    ``RSDL_TRACE_DIR``); re-recording them in the driver's ring would
    double-count the spans when the per-process dumps are merged
    (tools/rsdl_trace.py). This entry point updates only the driver-side
    attribution state and latency histograms — no ring event.
    """
    if not _ENABLED:
        return
    stage = STAGE_BY_KIND.get(kind)
    if stage is None:
        return
    attribution().observe(stage, epoch, dur_s, time.monotonic())
    hist = _stage_hist_cache.get(stage)
    if hist is None:
        hist = _stage_hist_cache[stage] = metrics.histogram(
            "rsdl_stage_seconds", "per-event stage latency", stage=stage)
    hist.observe(dur_s)


# -- RSDL_TELEMETRY=0 hard-off fast path: the public names rebind to
# these no-ops (one call frame, no env lookup, no branch chain).

def _noop_record(kind: str, *args: Any, **kwargs: Any) -> None:
    return None


_NULL_SPAN = contextlib.nullcontext()


def _noop_span(kind: str, *args: Any, **kwargs: Any):
    return _NULL_SPAN


def _noop_span_begin(*args: Any, **kwargs: Any) -> None:
    return None


def _noop_span_end(token: Any = None, **kwargs: Any) -> None:
    return None


def _stamp_impl() -> float:
    """Clock read for hot-path duration measurement (``time.monotonic``).

    PRs 4-6 put two clock reads on every queue put/get and wire frame —
    true per-item fast paths. Under the hard-off rebind this name becomes
    a constant-return no-op, so RSDL_TELEMETRY=0 strips the clock reads
    along with the record calls (the r03->r05 hot-path audit, ISSUE 7):
    ``start = stamp(); ...; record(kind, dur_s=stamp() - start)`` costs
    two no-op calls when telemetry is off.
    """
    return time.monotonic()


def _noop_stamp() -> float:
    return 0.0


# Public entry points (swapped by _apply_enabled_locked when policy
# resolves telemetry off).
record = _record_impl
span = _span_impl
span_begin = _span_begin_impl
span_end = _span_end_impl
stamp = _stamp_impl


def _update_trace_gauges(epoch: int) -> None:
    """Per-epoch critical-path exposition (tools/rsdl_top.py's
    critical-path line): run the trace analyzer over the recorder's
    retained events for this epoch and publish per-stage critical-path
    seconds plus the top straggler. Best-effort — exposition must never
    take down the pipeline."""
    try:
        from ray_shuffling_data_loader_tpu.runtime import trace as rt_trace
        analysis = rt_trace.analyze(recorder().events(), epoch=epoch)
        for entry in analysis["critical_path"]:
            metrics.gauge(
                "rsdl_trace_cp_seconds",
                "critical-path seconds attributed to the stage "
                "(latest analyzed epoch)",
                stage=entry["stage"]).set(entry["cp_ms"] / 1e3)
        stragglers = [s for s in analysis["stragglers"]
                      if s["cp_ms"] > 0 and s["task"] is not None]
        if stragglers:
            top = stragglers[0]
            metrics.gauge(
                "rsdl_trace_straggler_task",
                "task id of the current critical-path straggler",
                stage=top["stage"]).set(float(top["task"]))
            metrics.gauge(
                "rsdl_trace_straggler_seconds",
                "critical-path seconds of the current straggler task",
                stage=top["stage"]).set(top["cp_ms"] / 1e3)
    except Exception:  # noqa: BLE001 - observability stays best-effort
        logger.exception("trace gauge update failed (epoch %d)", epoch)


def epoch_complete(epoch: int, source: str = "") -> None:
    """Epoch-end hook for dataset layers: logs the one-line verdict and
    refreshes the critical-path exposition gauges."""
    if not _ENABLED:
        return
    attribution().epoch_complete(epoch, source=source)
    _update_trace_gauges(epoch)


# ---------------------------------------------------------------------------
# Dumps: JSONL events + named-thread stacks (on demand / watchdog / SIGUSR1)
# ---------------------------------------------------------------------------


def _thread_stacks() -> List[Dict[str, Any]]:
    frames = sys._current_frames()
    by_ident = {t.ident: t for t in threading.enumerate()}
    out = []
    for ident, frame in frames.items():
        thread = by_ident.get(ident)
        buf = io.StringIO()
        traceback.print_stack(frame, file=buf)
        out.append({
            "kind": "thread_stack",
            "thread": thread.name if thread else f"ident-{ident}",
            "ident": ident,
            "daemon": bool(thread.daemon) if thread else None,
            "stack": buf.getvalue().rstrip().splitlines(),
        })
    return out


_dump_seq = 0


def dump(path: Optional[str] = None, reason: str = "on-demand") -> str:
    """Write the flight recorder + thread stacks as JSONL; returns the
    path. Default location: ``telemetry_dump_dir`` policy key
    (``RSDL_TELEMETRY_DUMP_DIR``), else the system temp dir."""
    global _dump_seq
    if path is None:
        from ray_shuffling_data_loader_tpu.runtime import policy
        import tempfile
        directory = (policy.resolve("telemetry", "trace_dir")
                     or policy.resolve("telemetry", "telemetry_dump_dir")
                     or tempfile.gettempdir())
        os.makedirs(directory, exist_ok=True)
        with _lock:
            _dump_seq += 1
            seq = _dump_seq
        path = os.path.join(
            directory, f"rsdl-telemetry-{os.getpid()}-{seq}.jsonl")
    rec = recorder()
    with open(path, "w", encoding="utf-8") as f:
        # time.time() here is a SERIALIZED timestamp (never used in
        # interval math): it anchors t_mono offsets to wall clock for
        # whoever reads the dump — the cross-process clock alignment
        # runtime/trace.py merges on.
        f.write(json.dumps({
            "kind": "dump_meta", "reason": reason, "pid": os.getpid(),
            "time_unix": time.time(), "t_mono": time.monotonic(),
            "events_total": rec.total_recorded,
            "events_retained": min(rec.total_recorded, rec.capacity),
            "trace_seed": _trace_seed,
            "role": os.path.basename(sys.argv[0]) or "python",
        }) + "\n")
        for event in rec.events():
            f.write(json.dumps(event) + "\n")
        for stack in _thread_stacks():
            f.write(json.dumps(stack) + "\n")
    logger.warning("telemetry dump (%s): %s", reason, path)
    return path


def install_signal_dump(signum: int = signal.SIGUSR1) -> bool:
    """Install a SIGUSR1 (by default) handler that writes a flight
    recorder dump. Returns False (no-op) off the main thread or on
    platforms without the signal — callers need not guard."""
    if threading.current_thread() is not threading.main_thread():
        return False

    def _handler(_signum, _frame):
        try:
            dump(reason=f"signal {_signum}")
        except OSError:
            logger.exception("telemetry signal dump failed")

    try:
        signal.signal(signum, _handler)
    except (ValueError, OSError, AttributeError):
        return False
    return True


# ---------------------------------------------------------------------------
# Overhead self-measurement (bench.py's telemetry_overhead_pct evidence)
# ---------------------------------------------------------------------------


def measure_record_overhead(samples: int = 2000) -> float:
    """Seconds per ENABLED ``record()`` call, measured against throwaway
    doubles of everything the real path touches — ring, events counter,
    stage histogram, attribution observe — so the number is the full
    per-event cost, not just the ring append (the live recorder is not
    polluted). Bench multiplies this by the events recorded in its
    timed window: the self-measured ``telemetry_overhead_pct``."""
    probe = FlightRecorder(capacity=256)
    probe_counter = metrics.Counter()
    probe_attr = StageAttribution()
    probe_hist = metrics.Histogram()
    start = time.perf_counter()
    for i in range(samples):
        now = time.monotonic()
        probe.record((now, "probe", 0, i, None, 1e-6,
                      threading.get_ident(), None))
        probe_counter.inc()
        probe_attr.observe("map_read", 0, 1e-6, now)
        probe_hist.observe(1e-6)
    elapsed = time.perf_counter() - start
    return elapsed / samples


def measure_disabled_overhead(samples: int = 2000) -> float:
    """Seconds per call of the RSDL_TELEMETRY=0 hard-off fast path (the
    no-op ``record`` the public name rebinds to). Bench reports it as
    ``telemetry_overhead_off_pct`` — the proof the off switch is ~free."""
    start = time.perf_counter()
    for i in range(samples):
        _noop_record("probe", epoch=0, task=i, dur_s=1e-6)
    elapsed = time.perf_counter() - start
    return elapsed / samples


# Honor env-driven SIGUSR1 installation at import: RSDL_TELEMETRY_SIGUSR1=1
# makes any driver dumpable with `kill -USR1 <pid>`, zero code.
if os.environ.get("RSDL_TELEMETRY_SIGUSR1", "").strip().lower() in (
        "1", "true", "yes", "on"):
    install_signal_dump()
