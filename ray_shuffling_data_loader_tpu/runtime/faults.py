"""Deterministic fault injection for the shuffle pipeline.

Ray users test failure handling by killing raylets; this repo's tasks
are host threads, so failure testing needs its own plane. This module
is a seeded, policy-configured registry of **named fault sites**
threaded through the pipeline's hot paths:

===================  ======================================================
site                 where it fires
===================  ======================================================
``map_read``         the Parquet read in ``shuffle.shuffle_map``
``reduce_gather``    the map-output gather in ``shuffle._reduce_task``
``queue_put``        ``multiqueue.MultiQueue.put``
``queue_get``        ``multiqueue.MultiQueue.get``
``queue_fetch``      ``multiqueue_service.RemoteQueue._fetch_batch``
``transport_send``   ``parallel.transport.TcpTransport.send`` (per frame)
``transport_recv``   ``parallel.transport.TcpTransport.recv``
``spill_write``      ``spill.SpillManager.maybe_spill``
``spill_read``       ``spill.SpilledTable.load``
``device_transfer``  the ``jax.device_put`` in ``jax_dataset``
``queue_server_crash``  ``QueueServer`` GET handling — the whole server
                     process dies (``os._exit`` in dedicated-server
                     mode; in-process servers close) and the supervisor
                     must restart it from the watermark journal
``conn_reset_midframe``  ``QueueServer`` response writing — a torn frame
                     then a hard close, the reset-mid-response shape the
                     v2 replay protocol recovers
``frame_corrupt``    ``QueueServer`` response writing — one payload byte
                     flipped ON THE WIRE (replay buffer keeps the good
                     copy); the consumer CRC-rejects and NACKs
``ack_lost``         ``RemoteQueue`` request sending — one GET's ack
                     watermark suppressed; harmless by design (acks are
                     cumulative)
``storage_read``     the ``storage`` source fetch (``storage.read_table``
                     / ``storage.open_parquet``) — the remote-object-GET
                     failure shape, surfaced before the in-place IO retry
``storage_stall``    same boundary, but with ``:delayN`` — a slow remote
                     first byte (latency, not loss); without a delay it
                     behaves like ``storage_read``
``member_crash``     ``membership.MembershipManager.maybe_crash`` — the
                     named rank dies at the epoch/window boundary check
                     (``member_crash:rank2`` — ``rankN`` is sugar for
                     ``taskN``) and the world shrinks around it
``member_partition`` ``TcpTransport.send``/``send_heartbeat`` — frames
                     to the matched dest rank vanish silently (a
                     blackholing link, not an error), starving the
                     failure detector
``member_flap``      ``membership.detector.HeartbeatProber`` — one probe
                     round to the matched rank is dropped, driving the
                     detector's flap hysteresis
===================  ======================================================

A chaos spec (``RSDL_CHAOS_SPEC`` env var, or :func:`install`) is a
comma-separated list of rules::

    rule := site[@rate][:epochN][:taskN|fileN][:afterN][:xN][:delayN]

    map_read:epoch1:file2      fail epoch 1's read of file 2, once
    reduce_gather:task0        fail reducer 0's gather once per epoch
    queue_get:task1:after2     fail queue 1's third get
    map_read:file0:x5          fail file 0's read 5 times per epoch
                               (exhausts a <5-attempt recovery budget)
    transport_send@0.01        fail ~1% of (epoch, reducer) send keys
    reduce_gather:delay50      SLOW epoch's reduce gathers by 50 ms
                               (once per (site, epoch, task) key; no
                               fault raised — a latency, not a loss)

Rules fire **per distinct (site, epoch, task) key**: the first matching
call for a key raises :class:`InjectedFault`; the retry/recompute of
the same key passes — which is exactly what makes recovery machinery
provable (the recomputed task succeeds and its output can be asserted
bit-identical). ``afterN`` skips the key's first N calls; ``xN`` fails
N consecutive calls per key (to force recovery exhaustion). Rate rules
draw from a hash of ``(seed, site, epoch, task)`` — the same seed
reproduces the same failures every run, on any host.

:class:`InjectedFault` deliberately does NOT subclass ``OSError``: it
represents a *task-level* fault and must surface through the recovery
machinery under test, not be absorbed by an in-place IO retry.

Stdlib-only (importable before jax/pyarrow and from the native layer).
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from ray_shuffling_data_loader_tpu.utils.logger import setup_custom_logger

logger = setup_custom_logger(__name__)

#: The registered site names; a spec naming anything else is rejected at
#: parse time (a typo'd site must fail loudly, not silently never fire).
SITES = frozenset({
    "map_read", "reduce_gather", "queue_put", "queue_get", "queue_fetch",
    "transport_send", "transport_recv", "spill_write", "spill_read",
    "device_transfer",
    # Process-level sites (PR 5): the cross-process queue topology.
    "queue_server_crash", "conn_reset_midframe", "frame_corrupt",
    "ack_lost",
    # Storage plane (storage/): the remote-object fetch boundary.
    "storage_read", "storage_stall",
    # Membership plane (membership/): elastic-world failure shapes.
    # member_crash kills a rank (``:rankN`` — sugar for taskN) at an
    # epoch/window boundary check; member_partition blackholes transport
    # frames to a dest rank; member_flap starves one probe round.
    "member_crash", "member_partition", "member_flap",
    # Rebalance plane (rebalance/): live queue migration phases. Each
    # site models the whole process dying at that exact phase — source
    # mid-PREPARE, target mid-COMMIT, driver mid-decision — keyed by
    # (epoch = the move's target placement generation, task = rank).
    "rebalance_prepare", "rebalance_commit", "rebalance_abort",
})

_SPEC_ENVS = ("RSDL_CHAOS_SPEC", "RSDL_FAULTS_SPEC")
_SEED_ENVS = ("RSDL_CHAOS_SEED", "RSDL_FAULTS_SEED")


class InjectedFault(RuntimeError):
    """Raised by a fault site matched by the active chaos spec."""

    def __init__(self, site: str, epoch: Optional[int],
                 task: Optional[int], rule: str):
        super().__init__(
            f"injected fault at site {site!r} "
            f"(epoch={epoch}, task={task}, rule={rule!r})")
        self.site = site
        self.epoch = epoch
        self.task = task
        self.rule = rule


@dataclasses.dataclass
class QuarantinedFile:
    """Structured report for an input file dropped by ``on_bad_file="skip"``.

    Returned by ``shuffle_map`` in place of a ``MapShard``; the reduce
    gather skips it, and the report is recorded in
    ``stats.fault_stats()`` so the drop is observable, not silent.
    """

    filename: str
    epoch: int
    file_index: int
    error: str
    timestamp: float = dataclasses.field(default_factory=time.time)

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class ChaosRule:
    """One parsed spec rule (see module docstring for the grammar)."""

    site: str
    epoch: Optional[int] = None   # None = any epoch
    task: Optional[int] = None    # None = any task
    after: int = 0                # skip the key's first N matching calls
    count: int = 1                # then fail N consecutive calls per key
    rate: Optional[float] = None  # probabilistic gate per key (None = 1.0)
    delay_ms: Optional[int] = None  # slow the call instead of failing it
    text: str = ""                # original rule text, for error messages

    def matches(self, site: str, epoch: Optional[int],
                task: Optional[int]) -> bool:
        if site != self.site:
            return False
        if self.epoch is not None and epoch != self.epoch:
            return False
        if self.task is not None and task != self.task:
            return False
        return True


def _parse_rule(text: str) -> ChaosRule:
    tokens = [t.strip() for t in text.split(":") if t.strip()]
    if not tokens:
        raise ValueError(f"empty chaos rule in spec: {text!r}")
    site_token = tokens[0]
    rate = None
    if "@" in site_token:
        site_token, _, rate_token = site_token.partition("@")
        rate = float(rate_token)
        if not 0.0 <= rate <= 1.0:
            raise ValueError(f"chaos rate must be in [0, 1]: {text!r}")
    if site_token not in SITES:
        raise ValueError(
            f"unknown chaos site {site_token!r} in rule {text!r} "
            f"(known: {sorted(SITES)})")
    rule = ChaosRule(site=site_token, rate=rate, text=text)
    for token in tokens[1:]:
        for prefix, field in (("epoch", "epoch"), ("file", "task"),
                              ("task", "task"), ("rank", "task"),
                              ("after", "after"),
                              ("delay", "delay_ms"), ("x", "count")):
            if token.startswith(prefix) and token[len(prefix):].isdigit():
                setattr(rule, field, int(token[len(prefix):]))
                break
        else:
            raise ValueError(
                f"bad chaos qualifier {token!r} in rule {text!r} "
                "(expected epochN, taskN/fileN, afterN, xN, or delayN)")
    if rule.count < 1:
        raise ValueError(f"xN count must be >= 1: {text!r}")
    return rule


def parse_spec(spec: str) -> List[ChaosRule]:
    """Parse a full chaos spec string; raises ValueError on any bad rule."""
    return [_parse_rule(part) for part in spec.split(",") if part.strip()]


def spec_for_node(site: str, node, delay_ms: Optional[int] = None,
                  count: int = 1) -> str:
    """Chaos-rule text targeting one epoch-plan node (plan/ir.py).

    The harness used to hand-write ``site:epochE:taskT`` rules from
    privately re-derived key arithmetic; deriving the rule FROM the plan
    node keeps the chaos key and the task's lineage key equal by
    construction (they join in telemetry by ``(kind, epoch, task)``).
    ``delay_ms`` builds a ``delayN`` straggler rule (the speculation
    bench leg's injector) instead of a failure rule.
    """
    if site not in SITES:
        raise ValueError(f"unknown chaos site {site!r} "
                         f"(known: {sorted(SITES)})")
    rule = f"{site}:epoch{node.key.epoch}:task{node.key.task}"
    if delay_ms is not None:
        rule += f":delay{int(delay_ms)}"
    if count != 1:
        rule += f":x{int(count)}"
    _parse_rule(rule)  # validate the composed text loudly
    return rule


def _stable_draw(seed: int, site: str, epoch, task) -> float:
    """Deterministic uniform [0, 1) draw keyed by (seed, site, epoch,
    task) — the same seed reproduces the same failure set on any host."""
    digest = hashlib.sha256(
        f"{seed}:{site}:{epoch}:{task}".encode()).digest()
    return int.from_bytes(digest[:8], "little") / 2.0**64


class FaultInjector:
    """Active chaos configuration: parsed rules + per-key call counters."""

    def __init__(self, rules: List[ChaosRule], seed: int = 0):
        self.rules = rules
        self.seed = seed
        self._lock = threading.Lock()
        # (rule_index, site, epoch, task) -> matching calls seen so far.
        self._calls: Dict[Tuple, int] = {}
        self._fired: List[dict] = []

    def check(self, site: str, epoch: Optional[int],
              task: Optional[int]) -> Optional[InjectedFault]:
        for index, rule in enumerate(self.rules):
            if not rule.matches(site, epoch, task):
                continue
            key = (index, site, epoch, task)
            with self._lock:
                seen = self._calls.get(key, 0)
                self._calls[key] = seen + 1
            if not rule.after <= seen < rule.after + rule.count:
                continue
            if rule.rate is not None and _stable_draw(
                    self.seed, site, epoch, task) >= rule.rate:
                continue
            with self._lock:
                self._fired.append({
                    "site": site, "epoch": epoch, "task": task,
                    "rule": rule.text, "call": seen,
                })
            if rule.delay_ms is not None:
                # A latency fault: slow the matched call instead of
                # failing it (bottleneck-attribution regressions inject
                # a slow stage this way). Later rules may still fail
                # this same call.
                from ray_shuffling_data_loader_tpu.runtime import telemetry
                telemetry.record(site, epoch=epoch, task=task,
                                 fault="delay", delay_ms=rule.delay_ms)
                time.sleep(rule.delay_ms / 1e3)
                continue
            return InjectedFault(site, epoch, task, rule.text)
        return None

    def fired(self) -> List[dict]:
        with self._lock:
            return list(self._fired)


# Fast path: `inject()` sits on per-item hot paths (queue get/put), so
# the inactive case must be one attribute load, not an env lookup.
_ACTIVE = False
_injector: Optional[FaultInjector] = None
_install_lock = threading.Lock()


def install(spec: str, seed: int = 0) -> FaultInjector:
    """Programmatically activate a chaos spec (tests, bench --chaos)."""
    global _ACTIVE, _injector
    injector = FaultInjector(parse_spec(spec), seed=seed)
    with _install_lock:
        _injector = injector
        _ACTIVE = bool(injector.rules)
    if injector.rules:
        logger.warning("fault injection ACTIVE: %d rule(s), seed=%d: %s",
                       len(injector.rules), seed, spec)
    return injector


def clear() -> None:
    """Deactivate fault injection (does NOT re-read the environment)."""
    global _ACTIVE, _injector
    with _install_lock:
        _injector = None
        _ACTIVE = False


def configure_from_env() -> Optional[FaultInjector]:
    """(Re-)read ``RSDL_CHAOS_SPEC``/``RSDL_CHAOS_SEED`` (aliases:
    ``RSDL_FAULTS_*``); clears the injector when no spec is set."""
    spec = next((os.environ[name] for name in _SPEC_ENVS
                 if os.environ.get(name, "").strip()), None)
    if spec is None:
        clear()
        return None
    seed = int(next((os.environ[name] for name in _SEED_ENVS
                     if os.environ.get(name, "").strip()), "0"))
    return install(spec, seed=seed)


def active() -> bool:
    return _ACTIVE


def get_injector() -> Optional[FaultInjector]:
    return _injector


def inject(site: str, epoch: Optional[int] = None,
           task: Optional[int] = None) -> None:
    """Fault-site hook: raises :class:`InjectedFault` when the active
    chaos spec matches this call; free (one global load) when inactive."""
    if not _ACTIVE:
        return
    injector = _injector
    if injector is None:
        return
    fault = injector.check(site, epoch, task)
    if fault is not None:
        from ray_shuffling_data_loader_tpu import stats as stats_mod
        from ray_shuffling_data_loader_tpu.runtime import telemetry
        stats_mod.fault_stats().record_injected(site, epoch, task)
        # kind = the fault-site name: the chaos event and the stage's
        # own telemetry events join on (kind, epoch, task).
        telemetry.record(site, epoch=epoch, task=task, fault="injected",
                         rule=fault.rule)
        logger.warning("%s", fault)
        raise fault


# Honor a spec present in the environment at import time, so a driver
# exporting RSDL_CHAOS_SPEC reproduces its failures with zero code.
configure_from_env()
