"""The ONE retry policy for the pipeline (bounded, jittered, observable).

The repo had accumulated four independent retry idioms — the executor's
zero-sleep whole-task loop, the transport's fixed-cap doubling redial,
the queue registry's doubling lookup, and the remote queue's connect
loop — each with its own bounds, none with jitter, and none feeding the
stats subsystem. Production failure handling needs one answer:
:class:`RetryPolicy` owns attempt bounds, exponential backoff with
decorrelated jitter (AWS-style: ``sleep = min(cap, uniform(base,
prev * 3))`` — concurrent retriers de-synchronize instead of hammering
a recovering resource in lockstep), an optional wall-clock deadline,
and a retryable-exception predicate. Every retry and every
recovered-after-failure call is recorded in ``stats.fault_stats()``.

Policy knobs resolve through :mod:`runtime.policy`
(``RSDL_RETRY_MAX_ATTEMPTS``, ``RSDL_RETRY_INITIAL_BACKOFF_S``,
``RSDL_RETRY_MAX_BACKOFF_S``, ``RSDL_RETRY_DEADLINE_S``, with
``RSDL_<COMPONENT>_RETRY_*`` per-component overrides); construct via
:meth:`RetryPolicy.for_component`.

Stdlib-only on purpose (same contract as runtime.policy): importable
from the executor and the native layer without cycles.
"""

from __future__ import annotations

import dataclasses
import random
import time
from typing import Any, Callable, Optional, Tuple

from ray_shuffling_data_loader_tpu.utils.logger import setup_custom_logger

logger = setup_custom_logger(__name__)

#: Exception classes never retried regardless of the predicate: retrying
#: a cancellation/interpreter-teardown signal turns a prompt stop into a
#: backoff-long hang, and a failed assertion is a bug, not weather.
NON_RETRYABLE = (KeyboardInterrupt, SystemExit, GeneratorExit,
                 AssertionError)


def default_retryable(error: BaseException) -> bool:
    """Retry ordinary ``Exception``s; never the teardown signals above."""
    return isinstance(error, Exception) and not isinstance(
        error, NON_RETRYABLE)


def transient_retryable(error: BaseException) -> bool:
    """Predicate for IO-shaped call sites (transport, device transfer,
    remote queue): retry connection/OS-level failures and injected
    faults, not logic errors."""
    from ray_shuffling_data_loader_tpu.runtime import faults
    return isinstance(error, (OSError, ConnectionError, TimeoutError,
                              faults.InjectedFault))


@dataclasses.dataclass
class RetryPolicy:
    """Bounded retry with exponential backoff and decorrelated jitter.

    ``max_attempts`` is the TOTAL number of calls (1 = no retries).
    ``deadline_s`` bounds the whole call-plus-retries wall clock: once
    exceeded, the last error is raised even if attempts remain (``None``
    = no deadline). ``retryable`` decides per-exception; ``seed`` makes
    the jitter sequence reproducible (tests, chaos replays). ``sleep``
    is injectable so unit tests run in microseconds.
    """

    max_attempts: int = 3
    initial_backoff_s: float = 0.05
    max_backoff_s: float = 2.0
    deadline_s: Optional[float] = None
    retryable: Callable[[BaseException], bool] = default_retryable
    seed: Optional[int] = None
    sleep: Callable[[float], None] = time.sleep
    #: Component tag used in logs and fault-stats attribution.
    component: str = "retry"

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError(
                f"max_attempts must be >= 1, got {self.max_attempts}")
        if self.initial_backoff_s < 0 or self.max_backoff_s < 0:
            raise ValueError("backoff bounds must be >= 0")

    @classmethod
    def for_component(cls, component: str, **overrides: Any) -> "RetryPolicy":
        """Build a policy from the runtime policy registry: explicit
        ``overrides`` > ``RSDL_<COMPONENT>_RETRY_*`` env >
        ``RSDL_RETRY_*`` env > library defaults. ``deadline_s`` <= 0
        resolves to "no deadline"."""
        from ray_shuffling_data_loader_tpu.runtime import policy as rt_policy

        def res(key, default=None):
            return rt_policy.resolve(component, key,
                                     override=overrides.pop(key, None),
                                     default=default)

        deadline = res("retry_deadline_s")
        return cls(max_attempts=int(res("retry_max_attempts")),
                   initial_backoff_s=res("retry_initial_backoff_s"),
                   max_backoff_s=res("retry_max_backoff_s"),
                   deadline_s=None if deadline <= 0 else deadline,
                   component=component, **overrides)

    def backoffs(self):
        """Generator of sleep durations between attempts (decorrelated
        jitter, capped). Deterministic when ``seed`` is set."""
        rng = random.Random(self.seed)
        prev = self.initial_backoff_s
        while True:
            if self.initial_backoff_s <= 0:
                yield 0.0
                continue
            prev = min(self.max_backoff_s,
                       rng.uniform(self.initial_backoff_s, prev * 3))
            yield prev

    def call(self, fn: Callable[..., Any], *args: Any,
             describe: Optional[str] = None,
             on_retry: Optional[Callable[[BaseException], None]] = None,
             on_recovery: Optional[Callable[[int, float], None]] = None,
             **kwargs: Any) -> Any:
        """Run ``fn(*args, **kwargs)`` under this policy.

        ``on_retry(error)`` runs before each backoff sleep (e.g. to
        reconnect a socket); ``on_recovery(failed_attempts, elapsed_s)``
        runs when an attempt succeeds after at least one failure. The
        final failed attempt is logged at ERROR with the attempt budget;
        intermediate failures at WARNING.
        """
        from ray_shuffling_data_loader_tpu import stats as stats_mod
        what = describe or getattr(fn, "__name__", repr(fn))
        start = time.monotonic()
        deadline = (None if self.deadline_s is None
                    else start + self.deadline_s)
        backoffs = self.backoffs()
        for attempt in range(1, self.max_attempts + 1):
            try:
                result = fn(*args, **kwargs)
            except BaseException as e:  # noqa: BLE001 - filtered below
                out_of_time = (deadline is not None
                               and time.monotonic() >= deadline)
                if (attempt == self.max_attempts or out_of_time
                        or isinstance(e, NON_RETRYABLE)
                        or not self.retryable(e)):
                    if attempt > 1 or out_of_time:
                        logger.error(
                            "%s: %s failed permanently (attempt %d/%d%s): "
                            "%s", self.component, what, attempt,
                            self.max_attempts,
                            ", deadline exceeded" if out_of_time else "", e)
                    raise
                stats_mod.fault_stats().record_retry(self.component)
                pause = next(backoffs)
                if deadline is not None:
                    pause = min(pause, max(0.0,
                                           deadline - time.monotonic()))
                logger.warning(
                    "%s: %s failed (attempt %d/%d): %s; retrying in %.3fs",
                    self.component, what, attempt, self.max_attempts, e,
                    pause)
                if on_retry is not None:
                    on_retry(e)
                if pause > 0:
                    self.sleep(pause)
                continue
            if attempt > 1:
                elapsed = time.monotonic() - start
                if on_recovery is not None:
                    on_recovery(attempt - 1, elapsed)
                logger.info("%s: %s recovered on attempt %d/%d (%.3fs)",
                            self.component, what, attempt,
                            self.max_attempts, elapsed)
            return result


def policy_snapshot(policy: RetryPolicy) -> "Tuple[int, float, float]":
    """(max_attempts, initial_backoff_s, max_backoff_s) — diagnostics."""
    return (policy.max_attempts, policy.initial_backoff_s,
            policy.max_backoff_s)
