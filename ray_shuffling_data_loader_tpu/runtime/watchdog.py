"""Generic progress/deadline watchdog for pipeline stages.

The flagship bulk device-rebatch path had no liveness guarantee: a
wedged ``jax.device_put`` (dying TPU tunnel, stuck PJRT client) blocked
the producer thread forever while the consumer sat in ``queue.get`` —
an indefinite, silent stall at exactly the scale the library exists for
(VERDICT r5 Weak #1). Threads can't be interrupted mid-C-call, so the
cure is supervision: a stage registers a *watch* around its blocking
step; a single daemon monitor thread detects a missed deadline WHILE
the step is still stuck, files a structured :class:`StallReport` into
``stats.watchdog_stats()``, logs the reason, and runs the stage's
``on_stall`` escalation hook (which for the bulk path flips the
converter to the per-batch fallback — see jax_dataset.py). When the
stuck call finally returns, the stage sees ``handle.stalled`` and
finishes degraded instead of trusting the path that just wedged.

One process-wide instance (:func:`get_watchdog`) supervises every
stage; the monitor thread parks on a condition when no watches are
active, so an idle watchdog costs nothing.
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
import time
from typing import Callable, Iterator, Optional

from ray_shuffling_data_loader_tpu.utils.logger import setup_custom_logger

logger = setup_custom_logger(__name__)


@dataclasses.dataclass
class StallReport:
    """One deadline miss, as recorded into ``stats.watchdog_stats()``."""

    name: str            # watch name, e.g. "jax_dataset.bulk_transfer"
    waited_s: float      # time since the watch's last heartbeat
    deadline_s: float    # the deadline that was missed
    escalation: int      # 1 on the first miss, 2 at 2x the deadline, ...
    detail: str          # stage-supplied context (queue depth, bytes, ...)
    timestamp: float     # time.time() at detection


class WatchHandle:
    """Live handle for one supervised step.

    The supervised thread calls :meth:`beat` to reset the deadline (for
    multi-part steps); the monitor sets :attr:`stalled` /
    :attr:`report` when a deadline is missed, which the supervised
    thread inspects after its blocking call returns.
    """

    __slots__ = ("name", "deadline_s", "on_stall", "detail_fn",
                 "_last_beat", "stalled", "escalations", "report")

    def __init__(self, name: str, deadline_s: float,
                 on_stall: Optional[Callable[[StallReport], None]],
                 detail_fn: Optional[Callable[[], str]]):
        self.name = name
        self.deadline_s = deadline_s
        self.on_stall = on_stall
        self.detail_fn = detail_fn
        self._last_beat = time.monotonic()
        self.stalled = False
        self.escalations = 0
        self.report: Optional[StallReport] = None

    def beat(self) -> None:
        """Report progress: the deadline clock restarts from now."""
        self._last_beat = time.monotonic()

    def _detail(self) -> str:
        if self.detail_fn is None:
            return ""
        try:
            return str(self.detail_fn())
        except Exception as e:  # noqa: BLE001 - detail must never kill it
            return f"<detail failed: {e}>"


class PeriodicHandle:
    """One registered periodic callback run by the monitor thread."""

    __slots__ = ("name", "interval_s", "fn", "next_due")

    def __init__(self, name: str, interval_s: float, fn: Callable[[], None]):
        self.name = name
        # Floor guards a zero/negative interval from busy-looping the
        # one monitor thread every subsystem shares.
        self.interval_s = max(0.01, float(interval_s))
        self.fn = fn
        self.next_due = time.monotonic() + self.interval_s


class Watchdog:
    """Deadline monitor: one daemon thread supervising all active watches.

    The same thread services registered *periodic* callbacks
    (:meth:`every`) — the history ring tick and the health detectors ride
    the existing supervision thread instead of each spawning their own.
    """

    def __init__(self, poll_interval_s: float = 0.05):
        self.poll_interval_s = poll_interval_s
        self._cond = threading.Condition()
        self._watches: "set[WatchHandle]" = set()
        self._periodics: "set[PeriodicHandle]" = set()
        self._thread: Optional[threading.Thread] = None

    def _ensure_thread_locked(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._monitor, daemon=True, name="rsdl-watchdog")
            self._thread.start()
        self._cond.notify_all()

    @contextlib.contextmanager
    def watch(self, name: str, deadline_s: float,
              on_stall: Optional[Callable[[StallReport], None]] = None,
              detail_fn: Optional[Callable[[], str]] = None
              ) -> Iterator[WatchHandle]:
        """Supervise the enclosed block: if it runs longer than
        ``deadline_s`` without a :meth:`WatchHandle.beat`, a stall is
        reported (and re-escalated at every further deadline multiple).
        ``on_stall`` runs on the MONITOR thread — the supervised thread
        is, by definition, stuck."""
        handle = WatchHandle(name, deadline_s, on_stall, detail_fn)
        with self._cond:
            self._watches.add(handle)
            self._ensure_thread_locked()
        try:
            yield handle
        finally:
            with self._cond:
                self._watches.discard(handle)

    def every(self, interval_s: float, fn: Callable[[], None],
              name: str = "periodic") -> PeriodicHandle:
        """Run ``fn`` on the monitor thread every ``interval_s`` seconds
        until :meth:`cancel` — even while no watches are active (the
        monitor parks only when it has neither watches nor periodics).
        ``fn`` must be brief and must never raise for long-term health;
        raising is survived and logged."""
        handle = PeriodicHandle(name, interval_s, fn)
        with self._cond:
            self._periodics.add(handle)
            self._ensure_thread_locked()
        return handle

    def cancel(self, handle: PeriodicHandle) -> None:
        with self._cond:
            self._periodics.discard(handle)

    def _monitor(self) -> None:
        from ray_shuffling_data_loader_tpu import stats as stats_mod
        while True:
            with self._cond:
                if not self._watches and not self._periodics:
                    # Idle park; a new watch()/every() notifies. Bounded
                    # wait only so a torn-down interpreter lets the
                    # daemon cycle out.
                    self._cond.wait(timeout=5.0)
                    continue
                now = time.monotonic()
                due = []
                for w in self._watches:
                    waited = now - w._last_beat
                    if waited >= w.deadline_s * (w.escalations + 1):
                        w.escalations += 1
                        w.stalled = True
                        due.append((w, waited, w.escalations))
                due_periodics = []
                for p in self._periodics:
                    if now >= p.next_due:
                        p.next_due = now + p.interval_s
                        due_periodics.append(p)
                if not due and not due_periodics:
                    # Nothing to fire this pass: sleep to the earlier of
                    # the watch poll tick and the next periodic due time.
                    if self._watches:
                        timeout = self.poll_interval_s
                    else:
                        timeout = min(5.0, max(
                            0.005,
                            min(p.next_due for p in self._periodics) - now))
                    self._cond.wait(timeout=timeout)
            # Reports, logs, escalation hooks and periodic callbacks run
            # OUTSIDE the lock: a callback that takes its subsystem's
            # locks (the degrade path does) must not be able to deadlock
            # new watch()ers.
            for p in due_periodics:
                try:
                    p.fn()
                except Exception:  # noqa: BLE001 - supervision survives
                    logger.exception("watchdog periodic %s failed", p.name)
            for w, waited, escalation in due:
                report = StallReport(
                    name=w.name, waited_s=waited, deadline_s=w.deadline_s,
                    escalation=escalation, detail=w._detail(),
                    timestamp=time.time())
                w.report = report
                stats_mod.watchdog_stats().record_stall(report)
                if escalation == 2:
                    # The stall persisted past a second deadline: dump
                    # the flight recorder + thread stacks ONCE per watch
                    # while the stuck call is still stuck — the forensic
                    # record a post-mortem cannot reconstruct.
                    from ray_shuffling_data_loader_tpu.runtime import (
                        telemetry)
                    try:
                        telemetry.dump(
                            reason=f"watchdog escalation: {w.name}")
                    except Exception:  # noqa: BLE001 - supervision survives
                        logger.exception(
                            "watchdog telemetry dump failed for %s", w.name)
                log = logger.warning if escalation == 1 else logger.error
                log("watchdog: %s has run %.2fs (deadline %.2fs, "
                    "escalation %d)%s", report.name, report.waited_s,
                    report.deadline_s, report.escalation,
                    f": {report.detail}" if report.detail else "")
                if w.on_stall is not None:
                    try:
                        w.on_stall(report)
                    except Exception:  # noqa: BLE001 - supervision survives
                        logger.exception(
                            "watchdog on_stall hook for %s failed", w.name)


_global_lock = threading.Lock()
_global: Optional[Watchdog] = None


def get_watchdog() -> Watchdog:
    """THE process-wide watchdog (poll interval from the policy registry
    at first use)."""
    global _global
    with _global_lock:
        if _global is None:
            from ray_shuffling_data_loader_tpu.runtime import policy
            _global = Watchdog(poll_interval_s=policy.resolve(
                "watchdog", "watchdog_poll_interval_s"))
        return _global
