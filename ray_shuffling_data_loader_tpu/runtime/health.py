"""Declarative SLO/health detectors + auto-captured incident capsules.

Every diagnostic this pipeline had was post-hoc: bench.py prints a
verdict after the run, rsdl_trace explains an epoch after the dump.
This module is the *during*: a set of declarative detectors evaluated on
the history ring (runtime/history.py) at every tick — on the watchdog
monitor thread, so an armed health plane costs one brief callback per
``history_interval_s`` — with hysteresis so a noisy tick cannot flap a
verdict, thresholds resolved through runtime/policy.py (``RSDL_SLO_*``),
and verdicts exported as metrics (``rsdl_health_state`` /
``rsdl_health_breaches_total``) and flight-recorder events
(``health_breach``, joining fault/telemetry events by the usual
``(kind, epoch, task)`` discipline — detector breaches are process-wide,
so epoch/task stay unset and the join key is the kind + time window).

Detectors (thresholds under their policy keys; ``RSDL_SLO_<KEY>`` env):

========================  =================================================
``throughput_droop``      smoothed event rate fell below
                          ``(100 - slo_droop_pct)%`` of the retained peak
                          (peak must exceed ``slo_droop_floor_eps`` — an
                          idle pipeline is not a drooping one)
``stall_breach``          consumer batch-wait share of wall clock over the
                          smoothing window exceeded ``slo_stall_pct``
``ledger_creep``          native-ledger / RSS growth slope exceeded
                          ``slo_creep_mb_per_min`` over the retained window
``queue_saturation``      any queue's depth gauge exceeded
                          ``slo_queue_depth`` items
``lease_churn``           consumer-lease expiries exceeded
                          ``slo_lease_churn_per_min``
``straggler_drift``       the critical-path straggler's seconds exceeded
                          ``slo_straggler_drift_x`` × the rolling median
``delivery_latency_breach``  any queue's windowed p99 of the end-to-end
                          ``birth_to_delivered`` hop (the
                          ``rsdl_delivery_latency_seconds`` sketch,
                          runtime/latency.py) exceeded
                          ``slo_delivery_p99_s``
``freshness_stall``       any queue's EFFECTIVE freshness — the
                          ``rsdl_delivery_freshness_seconds`` gauge plus
                          how long it has sat unchanged (a pipeline that
                          stops delivering freezes its gauge; the age
                          keeps growing) — exceeded ``slo_freshness_s``
``cache_thrash``          the tiered storage cache (storage/cache.py) is
                          evicting faster than
                          ``slo_cache_evictions_per_min`` while its hit
                          share over the same window sits below
                          ``slo_cache_hit_pct`` — churning entries it
                          never serves (working set exceeds the budget)
========================  =================================================

On fire (or on ``SIGUSR2`` — :func:`install_incident_signal`, the
on-demand parallel of telemetry's SIGUSR1 recorder dump) the monitor
captures an **incident capsule**: a self-contained directory with the
detector verdict, trace dumps from every reachable pid (this process
dumps directly; procpool workers and supervised queue servers are
SIGUSR1'd and their dumps collected from ``RSDL_TRACE_DIR``), a
profiler burst, the history slice, the merged exposition, and the
resolved policy/env — rendered by ``tools/rsdl_incident.py``.

Stdlib-only (the runtime/ contract).
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
import signal as signal_mod
import socket
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ray_shuffling_data_loader_tpu.runtime import history as rt_history
from ray_shuffling_data_loader_tpu.runtime import metrics as rt_metrics
from ray_shuffling_data_loader_tpu.runtime import telemetry as rt_telemetry
from ray_shuffling_data_loader_tpu.utils.logger import setup_custom_logger

logger = setup_custom_logger(__name__)

_MIB = float(1 << 20)

#: Counter families whose combined rate is the pipeline's activity pulse
#: (the droop detector's series). rsdl_stage_seconds_count covers the
#: process-backend driver, whose per-task evidence arrives via
#: observe_stage histograms rather than ring events.
_ACTIVITY_SERIES: Tuple[str, ...] = ("rsdl_events_total",
                                     "rsdl_stage_seconds_count")


def _combined_series(ring: rt_history.HistoryRing,
                     names: Sequence[str]) -> List[Tuple[float, float]]:
    out = []
    for snap in ring.snapshots():
        total = None
        for name in names:
            value = rt_history.HistoryRing._sample_value(snap, name, None)
            if value is not None:
                total = (total or 0.0) + value
        if total is not None:
            out.append((snap["t"], total))
    return out


def _windowed_rates(pts: List[Tuple[float, float]],
                    window_ticks: int) -> List[Tuple[float, float]]:
    window_ticks = max(1, int(window_ticks))
    out = []
    for i in range(window_ticks, len(pts)):
        t0, v0 = pts[i - window_ticks]
        t1, v1 = pts[i]
        if t1 - t0 <= 0:
            continue
        out.append((t1, max(0.0, v1 - v0) / (t1 - t0)))
    return out


@dataclasses.dataclass
class Breach:
    """One detector's breach evidence at one tick."""

    detector: str
    value: float
    threshold: float
    detail: str

    def as_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


class Detector:
    """One health invariant. Subclasses resolve their thresholds from
    the policy registry at construction (``component`` controls the
    ``RSDL_<COMPONENT>_SLO_*`` env rung; the generic ``RSDL_SLO_*`` form
    applies everywhere) and implement :meth:`evaluate` returning a
    :class:`Breach` while the invariant is violated, else None."""

    name = "detector"

    def __init__(self, component: str = "health", **overrides: Any):
        from ray_shuffling_data_loader_tpu.runtime import policy
        self._resolve = lambda key, default=None: policy.resolve(
            component, key, override=overrides.get(key), default=default)

    def evaluate(self, ring: rt_history.HistoryRing) -> Optional[Breach]:
        raise NotImplementedError

    def _breach(self, value: float, threshold: float,
                detail: str) -> Breach:
        return Breach(self.name, round(float(value), 6),
                      round(float(threshold), 6), detail)


class ThroughputDroopDetector(Detector):
    """Smoothed activity rate fell far below the retained peak."""

    name = "throughput_droop"

    def __init__(self, component: str = "health", **overrides: Any):
        super().__init__(component, **overrides)
        self.droop_pct = self._resolve("slo_droop_pct")
        self.floor_eps = self._resolve("slo_droop_floor_eps")
        self.window_ticks = self._resolve("slo_droop_window_ticks")

    def evaluate(self, ring: rt_history.HistoryRing) -> Optional[Breach]:
        rates = _windowed_rates(
            _combined_series(ring, _ACTIVITY_SERIES), self.window_ticks)
        if len(rates) < 3:
            return None
        current = rates[-1][1]
        peak = max(rate for _, rate in rates[:-1])
        if peak < self.floor_eps:
            return None  # never saw real traffic: idle, not drooping
        allowed = peak * (1.0 - self.droop_pct / 100.0)
        if current < allowed:
            return self._breach(
                current, allowed,
                f"activity rate {current:.1f}/s fell below "
                f"{100 - self.droop_pct:.0f}% of peak {peak:.1f}/s")
        return None


class StallBreachDetector(Detector):
    """Consumer batch-wait share of wall clock over the window."""

    name = "stall_breach"

    def __init__(self, component: str = "health", **overrides: Any):
        super().__init__(component, **overrides)
        self.stall_pct = self._resolve("slo_stall_pct")
        self.window_ticks = self._resolve("slo_droop_window_ticks")

    def evaluate(self, ring: rt_history.HistoryRing) -> Optional[Breach]:
        waits = ring.series("rsdl_batch_wait_seconds_sum")
        counts = ring.series("rsdl_batch_wait_seconds_count")
        window = max(1, int(self.window_ticks))
        if len(waits) <= window or len(counts) <= window:
            return None
        (t0, w0), (t1, w1) = waits[-1 - window], waits[-1]
        batches = counts[-1][1] - counts[-1 - window][1]
        if t1 - t0 <= 0 or batches < 1:
            return None
        stall_pct = 100.0 * max(0.0, w1 - w0) / (t1 - t0)
        if stall_pct > self.stall_pct:
            return self._breach(
                stall_pct, self.stall_pct,
                f"consumer stalled {stall_pct:.1f}% of the last "
                f"{t1 - t0:.1f}s ({int(batches)} batch waits)")
        return None


class LedgerCreepDetector(Detector):
    """Monotone growth slope of the buffer ledger (or process RSS)."""

    name = "ledger_creep"
    _series = ("rsdl_ledger_bytes_in_use", "rsdl_process_rss_bytes")

    def __init__(self, component: str = "health", **overrides: Any):
        super().__init__(component, **overrides)
        self.mb_per_min = self._resolve("slo_creep_mb_per_min")

    def evaluate(self, ring: rt_history.HistoryRing) -> Optional[Breach]:
        worst = None
        for name in self._series:
            pts = ring.series(name)
            if len(pts) < 5:
                continue
            (t0, v0), (t1, v1) = pts[0], pts[-1]
            if t1 - t0 < 2 * ring.interval_s:
                continue
            slope_mb_min = (v1 - v0) / (t1 - t0) * 60.0 / _MIB
            if worst is None or slope_mb_min > worst[0]:
                worst = (slope_mb_min, name, t1 - t0)
        if worst is not None and worst[0] > self.mb_per_min:
            slope, name, span = worst
            return self._breach(
                slope, self.mb_per_min,
                f"{name} grew {slope:.1f} MiB/min over {span:.0f}s")
        return None


class QueueSaturationDetector(Detector):
    """Any queue's depth gauge pinned above the saturation bound."""

    name = "queue_saturation"

    def __init__(self, component: str = "health", **overrides: Any):
        super().__init__(component, **overrides)
        self.max_depth = self._resolve("slo_queue_depth")

    def evaluate(self, ring: rt_history.HistoryRing) -> Optional[Breach]:
        snaps = ring.snapshots()
        if not snaps:
            return None
        series = snaps[-1]["samples"].get("rsdl_queue_depth")
        if not series:
            return None
        labels, depth = max(series.items(), key=lambda kv: kv[1])
        if depth > self.max_depth:
            return self._breach(
                depth, self.max_depth,
                f"queue {dict(labels).get('queue', '?')} holds "
                f"{int(depth)} items")
        return None


class LeaseChurnDetector(Detector):
    """Consumer leases expiring faster than the churn budget."""

    name = "lease_churn"

    def __init__(self, component: str = "health", **overrides: Any):
        super().__init__(component, **overrides)
        self.per_min = self._resolve("slo_lease_churn_per_min")
        self.window_ticks = self._resolve("slo_droop_window_ticks")

    def evaluate(self, ring: rt_history.HistoryRing) -> Optional[Breach]:
        rates = ring.rate("rsdl_queue_lease_expiries_total",
                          window_ticks=self.window_ticks)
        if not rates:
            return None
        churn_per_min = rates[-1][1] * 60.0
        if churn_per_min > self.per_min:
            return self._breach(
                churn_per_min, self.per_min,
                f"leases expiring at {churn_per_min:.1f}/min")
        return None


class StragglerDriftDetector(Detector):
    """The critical-path straggler drifting away from its own median."""

    name = "straggler_drift"

    def __init__(self, component: str = "health", **overrides: Any):
        super().__init__(component, **overrides)
        self.drift_x = self._resolve("slo_straggler_drift_x")
        #: Medians below this are noise, not a trend to drift from.
        self.floor_s = 0.05

    def evaluate(self, ring: rt_history.HistoryRing) -> Optional[Breach]:
        values = []
        for snap in ring.snapshots():
            series = snap["samples"].get("rsdl_trace_straggler_seconds")
            if series:
                values.append(max(series.values()))
        if len(values) < 5:
            return None
        current = values[-1]
        prior = sorted(values[:-1])
        median = prior[len(prior) // 2]
        if median < self.floor_s:
            return None
        if current > self.drift_x * median:
            return self._breach(
                current, self.drift_x * median,
                f"straggler now {current:.2f}s vs rolling median "
                f"{median:.2f}s")
        return None


_DELIVERY_CENTROID_SERIES = "rsdl_delivery_latency_seconds_centroid"
_FRESHNESS_SERIES = "rsdl_delivery_freshness_seconds"


class DeliveryLatencyDetector(Detector):
    """Windowed per-queue p99 of the end-to-end birth->delivered hop.

    The sketch's centroid counts are cumulative per label set, so the
    window's distribution is the element-wise DELTA between the newest
    snapshot and the one ``slo_droop_window_ticks`` back — handed to
    the same quantile math every other sketch reader uses
    (``metrics.sketch_quantiles``). Breaches on the WORST queue: the
    SLO is per-queue, and averaging ranks together would let one
    starving trainer hide behind its siblings."""

    name = "delivery_latency_breach"

    def __init__(self, component: str = "health", **overrides: Any):
        super().__init__(component, **overrides)
        self.p99_s = self._resolve("slo_delivery_p99_s")
        self.window_ticks = self._resolve("slo_droop_window_ticks")

    def evaluate(self, ring: rt_history.HistoryRing) -> Optional[Breach]:
        snaps = ring.snapshots()
        if len(snaps) < 2:
            return None
        window = max(1, int(self.window_ticks))
        now = snaps[-1]["samples"].get(_DELIVERY_CENTROID_SERIES)
        if not now:
            return None
        base = snaps[max(0, len(snaps) - 1 - window)]["samples"].get(
            _DELIVERY_CENTROID_SERIES, {})
        delta = {}
        for labels, value in now.items():
            d = value - base.get(labels, 0.0)
            if d > 0:
                delta[labels] = d
        if not delta:
            return None
        stats = rt_metrics.sketch_quantiles(
            {_DELIVERY_CENTROID_SERIES: delta},
            "rsdl_delivery_latency_seconds", qs=(0.99,),
            hop="birth_to_delivered")
        worst = None
        for labels, entry in stats.items():
            queue = dict(labels).get("queue", "?")
            if worst is None or entry["p99"] > worst[0]:
                worst = (entry["p99"], queue, int(entry["count"]))
        if worst is not None and worst[0] > self.p99_s:
            p99, queue, count = worst
            return self._breach(
                p99, self.p99_s,
                f"queue {queue} delivery p99 {p99:.2f}s over the last "
                f"{count} frame(s)")
        return None


class FreshnessStallDetector(Detector):
    """Effective payload freshness at the consumer's final hop.

    The freshness gauge is set to the newest payload's birth age at
    each delivery — so when deliveries STOP, the gauge freezes while
    the data keeps aging. The detector therefore judges
    ``gauge value + seconds the gauge has sat unchanged`` (scanned back
    through the retained snapshots), catching both stale-data delivery
    and no-data stalls with one threshold."""

    name = "freshness_stall"

    def __init__(self, component: str = "health", **overrides: Any):
        super().__init__(component, **overrides)
        self.freshness_s = self._resolve("slo_freshness_s")

    def evaluate(self, ring: rt_history.HistoryRing) -> Optional[Breach]:
        snaps = ring.snapshots()
        if not snaps:
            return None
        latest = snaps[-1]
        series = latest["samples"].get(_FRESHNESS_SERIES)
        if not series:
            return None
        worst = None
        for labels, value in series.items():
            t_change = latest["t"]
            for snap in reversed(snaps[:-1]):
                prev = snap["samples"].get(_FRESHNESS_SERIES,
                                           {}).get(labels)
                if prev is None or prev != value:
                    break
                t_change = snap["t"]
            effective = value + max(0.0, latest["t"] - t_change)
            if worst is None or effective > worst[0]:
                worst = (effective, value, dict(labels).get("queue", "?"))
        if worst is not None and worst[0] > self.freshness_s:
            effective, raw, queue = worst
            return self._breach(
                effective, self.freshness_s,
                f"queue {queue} freshness {effective:.1f}s "
                f"(last delivered age {raw:.1f}s)")
        return None


class CacheThrashDetector(Detector):
    """Tiered storage cache evicting entries it never gets to serve.

    Thrash is a *joint* condition: a high eviction rate alone is fine
    while the hit share stays healthy (steady-state LRU turnover), and
    a low hit share alone is the expected cold-start shape. Only the
    combination — evictions above ``slo_cache_evictions_per_min`` while
    hits/(hits+misses) over the same window sits below
    ``slo_cache_hit_pct`` — means the working set has outgrown the
    cache budget and every insert is displacing something still live."""

    name = "cache_thrash"

    def __init__(self, component: str = "health", **overrides: Any):
        super().__init__(component, **overrides)
        self.evictions_per_min = self._resolve("slo_cache_evictions_per_min")
        self.hit_pct = self._resolve("slo_cache_hit_pct")
        self.window_ticks = self._resolve("slo_droop_window_ticks")

    def evaluate(self, ring: rt_history.HistoryRing) -> Optional[Breach]:
        window = max(1, int(self.window_ticks))
        evict_rates = ring.rate("rsdl_storage_evictions_total",
                                window_ticks=window)
        if not evict_rates:
            return None
        evict_per_min = evict_rates[-1][1] * 60.0
        if evict_per_min <= self.evictions_per_min:
            return None
        hits = ring.series("rsdl_storage_hits_total")
        misses = ring.series("rsdl_storage_misses_total")
        if len(hits) <= window or len(misses) <= window:
            return None
        dh = max(0.0, hits[-1][1] - hits[-1 - window][1])
        dm = max(0.0, misses[-1][1] - misses[-1 - window][1])
        if dh + dm <= 0:
            return None
        hit_pct = 100.0 * dh / (dh + dm)
        if hit_pct < self.hit_pct:
            return self._breach(
                evict_per_min, self.evictions_per_min,
                f"cache evicting {evict_per_min:.1f}/min at "
                f"{hit_pct:.1f}% hit rate (floor {self.hit_pct:.0f}%)")
        return None


class TenantCacheThrashDetector(Detector):
    """Per-tenant cache thrash: the :class:`CacheThrashDetector` joint
    condition evaluated per tenant label over the
    ``rsdl_tenant_storage_*`` series (storage/cache.py attributes every
    hot-tier hit/miss/eviction to the ambient TenantContext).

    The aggregate detector can stay green while one tenant churns —
    its evictions diluted by a neighbor's hits. This one names the
    thrashing tenant, which is also the actionable unit: the fix is
    that tenant's ``cache_quota_bytes``, not the global budget."""

    name = "tenant_cache_thrash"

    def __init__(self, component: str = "health", **overrides: Any):
        super().__init__(component, **overrides)
        self.evictions_per_min = self._resolve("slo_cache_evictions_per_min")
        self.hit_pct = self._resolve("slo_cache_hit_pct")
        self.window_ticks = self._resolve("slo_droop_window_ticks")

    def _tenants(self, ring: rt_history.HistoryRing) -> List[str]:
        snaps = ring.snapshots()
        if not snaps:
            return []
        series = snaps[-1]["samples"].get(
            "rsdl_tenant_storage_evictions_total", {})
        return sorted({dict(labels).get("tenant", "")
                       for labels in series} - {""})

    def evaluate(self, ring: rt_history.HistoryRing) -> Optional[Breach]:
        window = max(1, int(self.window_ticks))
        worst = None
        for tenant in self._tenants(ring):
            labels = {"tenant": tenant}
            evict_rates = ring.rate("rsdl_tenant_storage_evictions_total",
                                    labels=labels, window_ticks=window)
            if not evict_rates:
                continue
            evict_per_min = evict_rates[-1][1] * 60.0
            if evict_per_min <= self.evictions_per_min:
                continue
            hits = ring.series("rsdl_tenant_storage_hits_total",
                               labels=labels)
            misses = ring.series("rsdl_tenant_storage_misses_total",
                                 labels=labels)
            if len(hits) <= window or len(misses) <= window:
                continue
            dh = max(0.0, hits[-1][1] - hits[-1 - window][1])
            dm = max(0.0, misses[-1][1] - misses[-1 - window][1])
            if dh + dm <= 0:
                continue
            hit_pct = 100.0 * dh / (dh + dm)
            if hit_pct < self.hit_pct and (
                    worst is None or evict_per_min > worst[0]):
                worst = (evict_per_min, hit_pct, tenant)
        if worst is not None:
            evict_per_min, hit_pct, tenant = worst
            return self._breach(
                evict_per_min, self.evictions_per_min,
                f"tenant {tenant} evicting {evict_per_min:.1f}/min at "
                f"{hit_pct:.1f}% hit rate (floor {self.hit_pct:.0f}%)")
        return None


_TENANT_DELIVERY_CENTROID_SERIES = \
    "rsdl_tenant_delivery_latency_seconds_centroid"


class TenantDeliverySLODetector(Detector):
    """Sustained per-tenant delivery-p99 SLO breach — the rebalance
    trigger.

    Same windowed centroid-delta math as
    :class:`DeliveryLatencyDetector`, evaluated over the per-tenant
    sketch the wire client feeds (``rsdl_tenant_delivery_latency_seconds``
    with ``hop=birth_to_delivered``) and breaching on the WORST tenant.
    The threshold is the rebalance plane's own knob
    (``RSDL_REBALANCE_SLO_P99_S``), not the generic delivery SLO: this
    detector's consumer is the :mod:`rebalance` controller, and its
    hysteresis (``HealthMonitor``'s fire/clear tick runs) is what turns
    a noisy latency series into exactly one migration per episode."""

    name = "tenant_delivery_slo"

    def __init__(self, component: str = "health", **overrides: Any):
        super().__init__(component, **overrides)
        self.p99_s = self._resolve("rebalance_slo_p99_s")
        self.window_ticks = self._resolve("slo_droop_window_ticks")

    def evaluate(self, ring: rt_history.HistoryRing) -> Optional[Breach]:
        snaps = ring.snapshots()
        if len(snaps) < 2:
            return None
        window = max(1, int(self.window_ticks))
        now = snaps[-1]["samples"].get(_TENANT_DELIVERY_CENTROID_SERIES)
        if not now:
            return None
        base = snaps[max(0, len(snaps) - 1 - window)]["samples"].get(
            _TENANT_DELIVERY_CENTROID_SERIES, {})
        delta = {}
        for labels, value in now.items():
            d = value - base.get(labels, 0.0)
            if d > 0:
                delta[labels] = d
        if not delta:
            return None
        stats = rt_metrics.sketch_quantiles(
            {_TENANT_DELIVERY_CENTROID_SERIES: delta},
            "rsdl_tenant_delivery_latency_seconds", qs=(0.99,),
            hop="birth_to_delivered")
        worst = None
        for labels, entry in stats.items():
            tenant = dict(labels).get("tenant", "?")
            if worst is None or entry["p99"] > worst[0]:
                worst = (entry["p99"], tenant, int(entry["count"]))
        if worst is not None and worst[0] > self.p99_s:
            p99, tenant, count = worst
            return self._breach(
                p99, self.p99_s,
                f"tenant {tenant} delivery p99 {p99:.2f}s over the last "
                f"{count} frame(s) (rebalance SLO {self.p99_s:.2f}s)")
        return None


class WatermarkLagDetector(Detector):
    """Streaming ingest running away from serving.

    ``rsdl_stream_watermark_lag_seconds`` (streaming/runner.py) is the
    ingest watermark minus the serve watermark, in STREAM seconds: how
    much sealed-but-unserved input exists. A bounded lag is the normal
    pipelining depth (`max_concurrent_epochs` windows in flight); a lag
    above ``slo_watermark_lag_s`` means windows close faster than the
    shuffle+serving plane drains them — online training is falling
    behind the stream and model freshness is decaying."""

    name = "watermark_lag"

    def __init__(self, component: str = "health", **overrides: Any):
        super().__init__(component, **overrides)
        self.lag_s = self._resolve("slo_watermark_lag_s")

    def evaluate(self, ring: rt_history.HistoryRing) -> Optional[Breach]:
        pts = ring.series("rsdl_stream_watermark_lag_seconds")
        if not pts:
            return None
        lag = pts[-1][1]
        if lag > self.lag_s:
            return self._breach(
                lag, self.lag_s,
                f"stream serving lags ingest by {lag:.1f}s of stream "
                f"time (budget {self.lag_s:.0f}s)")
        return None


_DETECTOR_TYPES: Dict[str, type] = {
    cls.name: cls for cls in (
        ThroughputDroopDetector, StallBreachDetector, LedgerCreepDetector,
        QueueSaturationDetector, LeaseChurnDetector, StragglerDriftDetector,
        DeliveryLatencyDetector, FreshnessStallDetector, CacheThrashDetector,
        TenantCacheThrashDetector, TenantDeliverySLODetector,
        WatermarkLagDetector)
}


def default_detectors(component: str = "health",
                      names: Optional[Sequence[str]] = None,
                      **overrides: Any) -> List[Detector]:
    """Instantiate detectors by name (None = all six), with thresholds
    resolved for ``component`` plus explicit ``overrides``."""
    names = tuple(names) if names is not None else tuple(_DETECTOR_TYPES)
    unknown = set(names) - set(_DETECTOR_TYPES)
    if unknown:
        raise ValueError(f"unknown detectors: {sorted(unknown)} "
                         f"(known: {sorted(_DETECTOR_TYPES)})")
    return [_DETECTOR_TYPES[name](component, **overrides) for name in names]


class _DetectorState:
    __slots__ = ("breach_run", "ok_run", "firing", "fires", "last_breach")

    def __init__(self):
        self.breach_run = 0
        self.ok_run = 0
        self.firing = False
        self.fires = 0
        self.last_breach: Optional[Breach] = None


class HealthMonitor:
    """Hysteresis state machine over a detector set, driven by history
    ticks. A breach must persist ``fire_ticks`` consecutive ticks to
    FIRE (once per episode); ``clear_ticks`` consecutive clean ticks
    re-arm the detector — so an oscillating signal inside one episode
    cannot fire twice (the no-flapping contract, pinned by tests)."""

    def __init__(self, ring: rt_history.HistoryRing,
                 detectors: Optional[Sequence[Detector]] = None,
                 component: str = "health",
                 fire_ticks: Optional[int] = None,
                 clear_ticks: Optional[int] = None,
                 on_fire: Optional[Callable[[Dict[str, Any]], None]] = None,
                 capture: bool = True,
                 incident_dir: Optional[str] = None,
                 capture_cooldown_s: Optional[float] = None):
        from ray_shuffling_data_loader_tpu.runtime import policy
        self.ring = ring
        self.detectors = list(detectors if detectors is not None
                              else default_detectors(component))
        self.fire_ticks = int(policy.resolve(component, "health_fire_ticks",
                                             override=fire_ticks))
        self.clear_ticks = int(policy.resolve(
            component, "health_clear_ticks", override=clear_ticks))
        self.on_fire = on_fire
        self.capture = capture
        self.incident_dir = incident_dir
        #: None = the module default (CAPSULE_COOLDOWN_S); tests and the
        #: dryrun pass 0.0 — repeated scenes in one process must each
        #: get their capsule.
        self.capture_cooldown_s = capture_cooldown_s
        self._states = {d.name: _DetectorState() for d in self.detectors}
        self._lock = threading.Lock()
        self._capture_threads: List[threading.Thread] = []
        self.capsules: List[str] = []
        self._attached = False

    # -- lifecycle -----------------------------------------------------------

    def attach(self) -> "HealthMonitor":
        if not self._attached:
            self._attached = True
            self.ring.add_listener(self._on_tick)
        return self

    def detach(self) -> None:
        if self._attached:
            self._attached = False
            self.ring.remove_listener(self._on_tick)

    def _on_tick(self, ring: rt_history.HistoryRing) -> None:
        self.tick()

    # -- evaluation ----------------------------------------------------------

    def tick(self) -> List[Breach]:
        """Evaluate every detector against the ring once; returns the
        breaches that FIRED this tick (post-hysteresis)."""
        fired: List[Breach] = []
        for detector in self.detectors:
            try:
                breach = detector.evaluate(self.ring)
            except Exception:  # noqa: BLE001 - detectors must not kill ticks
                logger.exception("health detector %s failed", detector.name)
                continue
            with self._lock:
                state = self._states[detector.name]
                if breach is not None:
                    state.breach_run += 1
                    state.ok_run = 0
                    state.last_breach = breach
                    should_fire = (not state.firing
                                   and state.breach_run >= self.fire_ticks)
                    if should_fire:
                        state.firing = True
                        state.fires += 1
                else:
                    state.ok_run += 1
                    state.breach_run = 0
                    should_fire = False
                    if state.firing and state.ok_run >= self.clear_ticks:
                        state.firing = False
                        self._export_state(detector.name, 0.0)
                        rt_telemetry.record("health_clear",
                                            detector=detector.name)
            if breach is not None and should_fire:
                fired.append(breach)
                self._fire(breach)
        return fired

    def _export_state(self, name: str, value: float) -> None:
        rt_metrics.gauge("rsdl_health_state",
                         "1 while the detector's breach episode is open",
                         detector=name).set(value)

    def _fire(self, breach: Breach) -> None:
        rt_metrics.counter("rsdl_health_breaches_total",
                           "detector fires (post-hysteresis episodes)",
                           detector=breach.detector).inc()
        self._export_state(breach.detector, 1.0)
        rt_telemetry.record("health_breach", detector=breach.detector,
                            value=breach.value, threshold=breach.threshold,
                            detail=breach.detail)
        logger.error("health: %s FIRED (%s; value %.3f, threshold %.3f)",
                     breach.detector, breach.detail, breach.value,
                     breach.threshold)
        verdict = self.verdict(breach)
        if self.on_fire is not None:
            try:
                self.on_fire(verdict)
            except Exception:  # noqa: BLE001 - capture must not kill ticks
                logger.exception("health on_fire hook failed")
        elif self.capture:
            thread = threading.Thread(
                target=self._capture, args=(verdict,), daemon=True,
                name="rsdl-incident-capture")
            with self._lock:
                self._capture_threads.append(thread)
            thread.start()

    def _capture(self, verdict: Dict[str, Any]) -> None:
        try:
            path = capture_incident(
                reason=f"detector {verdict['detector']}", verdict=verdict,
                ring=self.ring, base_dir=self.incident_dir,
                cooldown_s=self.capture_cooldown_s)
            if path:
                with self._lock:
                    self.capsules.append(path)
        except Exception:  # noqa: BLE001 - capture is best-effort evidence
            logger.exception("incident capture failed")

    def wait_captures(self, timeout_s: float = 30.0) -> List[str]:
        """Block until in-flight capsule captures finish; returns the
        capsule paths captured so far."""
        deadline = time.monotonic() + timeout_s
        with self._lock:
            threads = list(self._capture_threads)
        for thread in threads:
            thread.join(timeout=max(0.0, deadline - time.monotonic()))
        with self._lock:
            return list(self.capsules)

    # -- reporting -----------------------------------------------------------

    def verdict(self, breach: Breach) -> Dict[str, Any]:
        with self._lock:
            state = self._states[breach.detector]
            return {
                "detector": breach.detector,
                "value": breach.value,
                "threshold": breach.threshold,
                "detail": breach.detail,
                "fires": state.fires,
                "fire_ticks": self.fire_ticks,
                "clear_ticks": self.clear_ticks,
                "pid": os.getpid(),
                "t_unix": time.time(),
            }

    @property
    def total_fires(self) -> int:
        with self._lock:
            return sum(s.fires for s in self._states.values())

    def summary(self) -> Dict[str, Any]:
        """Bench-record shape: per-detector episode counts + the last
        breach evidence of every detector that ever fired."""
        with self._lock:
            detectors = {}
            for name, state in self._states.items():
                entry: Dict[str, Any] = {"fires": state.fires,
                                         "firing": state.firing}
                if state.fires and state.last_breach is not None:
                    entry["last"] = state.last_breach.as_dict()
                detectors[name] = entry
            return {
                "fire_ticks": self.fire_ticks,
                "clear_ticks": self.clear_ticks,
                "interval_s": self.ring.interval_s,
                "fires": sum(s.fires for s in self._states.values()),
                "detectors": detectors,
                "capsules": list(self.capsules),
            }


# ---------------------------------------------------------------------------
# Arm/disarm: the one-call ops-plane switch (bench, dryrun, drivers)
# ---------------------------------------------------------------------------

_armed_lock = threading.Lock()
_armed: Optional[HealthMonitor] = None


def arm(interval_s: Optional[float] = None,
        capacity: Optional[int] = None,
        detectors: Optional[Sequence[str]] = None,
        component: str = "health",
        on_fire: Optional[Callable[[Dict[str, Any]], None]] = None,
        capture: bool = True,
        incident_dir: Optional[str] = None,
        fire_ticks: Optional[int] = None,
        clear_ticks: Optional[int] = None,
        capture_cooldown_s: Optional[float] = None,
        **threshold_overrides: Any) -> Optional[HealthMonitor]:
    """Start history ticking and attach a monitor over it (None when the
    ``health`` policy key disarms the plane). Re-arming replaces the
    previous monitor — per-phase arming (bench.py) gets a fresh ring and
    fresh hysteresis state each time."""
    from ray_shuffling_data_loader_tpu.runtime import policy
    if not policy.resolve(component, "health"):
        return None
    global _armed
    with _armed_lock:
        if _armed is not None:
            _armed.detach()
        ring = rt_history.start(interval_s=interval_s, capacity=capacity)
        monitor = HealthMonitor(
            ring,
            detectors=default_detectors(component, detectors,
                                        **threshold_overrides),
            component=component, fire_ticks=fire_ticks,
            clear_ticks=clear_ticks, on_fire=on_fire, capture=capture,
            incident_dir=incident_dir,
            capture_cooldown_s=capture_cooldown_s).attach()
        _armed = monitor
    return monitor


def disarm() -> Optional[HealthMonitor]:
    """Stop history ticking and detach; returns the monitor (for its
    :meth:`HealthMonitor.summary`)."""
    global _armed
    with _armed_lock:
        monitor, _armed = _armed, None
    if monitor is not None:
        monitor.detach()
    rt_history.stop()
    return monitor


def armed_monitor() -> Optional[HealthMonitor]:
    with _armed_lock:
        return _armed


# ---------------------------------------------------------------------------
# Incident capsules
# ---------------------------------------------------------------------------

_capsule_lock = threading.Lock()
_capsule_seq = 0
_last_capture_mono: Optional[float] = None

#: Minimum seconds between capsules (a breach storm — several detectors
#: firing in one window — yields ONE capsule; the first already embeds
#: every detector's state via the history slice).
CAPSULE_COOLDOWN_S = 30.0


def _capsule_base_dir(override: Optional[str] = None) -> str:
    from ray_shuffling_data_loader_tpu.runtime import policy
    import tempfile
    return (override
            or policy.resolve("health", "incident_dir")
            or policy.resolve("telemetry", "trace_dir")
            or policy.resolve("telemetry", "telemetry_dump_dir")
            or tempfile.gettempdir())


def _signal_candidate_pids() -> List[int]:
    """Sibling pids worth asking for a trace dump: the last worker
    pool's processes plus every pid with a metrics shard."""
    pids = set()
    try:
        from ray_shuffling_data_loader_tpu import executor as rsdl_ex
        pids.update(rsdl_ex.last_worker_pool().get("pids") or [])
    except ImportError:
        # Capture runs even on a stripped host where the package layer
        # (numpy et al.) is absent; shard pids below still cover it.
        logger.warning("incident capture: executor pool registry "
                       "unavailable; using shard pids only")
    directory = rt_metrics.telemetry_dir()
    if directory:
        pids.update(rt_metrics.read_shards(directory))
    pids.discard(os.getpid())
    return sorted(pids)


def capture_incident(reason: str = "on-demand",
                     verdict: Optional[Dict[str, Any]] = None,
                     ring: Optional[rt_history.HistoryRing] = None,
                     base_dir: Optional[str] = None,
                     profile_s: Optional[float] = None,
                     wait_s: Optional[float] = None,
                     cooldown_s: Optional[float] = None,
                     stem: Optional[str] = None) -> Optional[str]:
    """Write one incident capsule directory; returns its path (None when
    suppressed by the capture cooldown). ``stem`` overrides the
    ``rsdl-incident-<pid>-<seq>`` directory name — bench.py names its
    per-round flight capsules after the record they accompany.

    Layout (rendered by ``tools/rsdl_incident.py``)::

        rsdl-incident-<pid>-<seq>[-<detector>]/
          capsule.json    # manifest: reason, verdict, pids, file list
          history.json    # history-ring slice (rsdl-history-v1)
          metrics.prom    # merged multi-process exposition
          policy.json     # resolved policy snapshot + RSDL_* env
          profile.folded  # sampling-profiler burst (flamegraph input)
          traces/rsdl-telemetry-<pid>-*.jsonl   # per-pid recorder dumps
    """
    from ray_shuffling_data_loader_tpu.runtime import policy
    global _capsule_seq, _last_capture_mono
    cooldown = (CAPSULE_COOLDOWN_S if cooldown_s is None
                else float(cooldown_s))
    start_mono = time.monotonic()
    with _capsule_lock:
        if (_last_capture_mono is not None
                and start_mono - _last_capture_mono < cooldown):
            logger.warning(
                "incident capture suppressed (%s): previous capsule is "
                "%.1fs old (cooldown %.0fs)", reason,
                start_mono - _last_capture_mono, cooldown)
            return None
        _last_capture_mono = start_mono
        _capsule_seq += 1
        seq = _capsule_seq
    detector = (verdict or {}).get("detector")
    if stem is None:
        stem = f"rsdl-incident-{os.getpid()}-{seq}" + (
            f"-{detector}" if detector else "")
    capsule = os.path.join(_capsule_base_dir(base_dir), stem)
    traces_dir = os.path.join(capsule, "traces")
    os.makedirs(traces_dir, exist_ok=True)

    # 1. Flush this process's shard so the merged exposition is current,
    #    then freeze the cluster-wide view.
    rt_metrics.write_shard()
    federated_text = rt_metrics.render_federated()
    with open(os.path.join(capsule, "metrics.prom"), "w",
              encoding="utf-8") as f:
        f.write(federated_text)
    # Delivery-latency slice of the frozen exposition: the capsule's
    # manifest answers "how late was delivery when this fired" without
    # re-deriving quantiles from the .prom file.
    latency_summary: Dict[str, Any] = {}
    try:
        samples = rt_metrics.parse_exposition(federated_text)
        for labels, stats in sorted(rt_metrics.sketch_quantiles(
                samples, "rsdl_delivery_latency_seconds").items()):
            key = ",".join(f"{k}={v}" for k, v in labels)
            latency_summary[key] = {
                name: round(value, 6) for name, value in stats.items()}
    except (ValueError, KeyError):
        logger.exception("incident latency summary failed")

    # 2. History slice (armed ring, explicit ring, or none).
    ring = ring or rt_history.get_history()
    if ring is not None:
        with open(os.path.join(capsule, "history.json"), "w",
                  encoding="utf-8") as f:
            json.dump(rt_history.downsample_slice(ring.slice()), f)

    # 3. Resolved policy + environment (the "what was configured" half
    #    every incident review starts with).
    with open(os.path.join(capsule, "policy.json"), "w",
              encoding="utf-8") as f:
        json.dump({
            "policy": {k: repr(v) if not isinstance(
                v, (int, float, str, bool, type(None))) else v
                for k, v in policy.describe().items()},
            "env": {k: v for k, v in sorted(os.environ.items())
                    if k.startswith("RSDL_")},
        }, f, indent=2)

    # 4. Profiler burst: a short always-available flamegraph window of
    #    the moment the detector fired.
    profile_s = policy.resolve("health", "incident_profile_s",
                               override=profile_s)
    profile_summary = None
    if profile_s and profile_s > 0:
        try:
            from ray_shuffling_data_loader_tpu.runtime import profiler
            prof = profiler.SamplingProfiler().start()
            time.sleep(profile_s)
            prof.stop()
            prof.write_folded(os.path.join(capsule, "profile.folded"))
            profile_summary = prof.summary()
        except Exception:  # noqa: BLE001 - a capsule without a profile
            logger.exception("incident profiler burst failed")

    # 5. Trace dumps: this process dumps directly into the capsule;
    #    sibling pids are SIGUSR1'd (procpool workers and supervised
    #    queue servers install the handler) and their dumps — landing in
    #    the shared RSDL_TRACE_DIR — are collected after a bounded wait.
    own_dump = os.path.join(traces_dir,
                            f"rsdl-telemetry-{os.getpid()}-0.jsonl")
    try:
        rt_telemetry.dump(path=own_dump, reason=f"incident: {reason}")
    except OSError:
        logger.exception("incident self-dump failed")
    signaled: List[int] = []
    for pid in _signal_candidate_pids():
        try:
            os.kill(pid, signal_mod.SIGUSR1)
            signaled.append(pid)
        except (ProcessLookupError, PermissionError, OSError):
            continue
    trace_dir = policy.resolve("telemetry", "trace_dir") or None
    wait_s = policy.resolve("health", "incident_wait_s", override=wait_s)
    if signaled and trace_dir:
        deadline = start_mono + wait_s
        # Bounded collection wait, not a retry: each pass polls for the
        # signaled pids' fresh dumps until the deadline.
        # rsdl-lint: disable=unbounded-retry
        while time.monotonic() < deadline:
            fresh = {pid for pid in signaled
                     if _fresh_dumps(trace_dir, pid, start_mono)}
            if fresh == set(signaled):
                break
            time.sleep(0.05)
        for pid in signaled:
            for path in _fresh_dumps(trace_dir, pid, start_mono):
                try:
                    shutil.copy(path, traces_dir)
                except OSError:
                    continue

    # 6. Manifest, written LAST: a capsule with a manifest is complete.
    trace_files = sorted(os.listdir(traces_dir))
    pids = []
    for name in trace_files:
        try:
            with open(os.path.join(traces_dir, name),
                      encoding="utf-8") as f:
                meta = json.loads(f.readline())
            if isinstance(meta.get("pid"), int):
                pids.append(meta["pid"])
        except (OSError, ValueError):
            continue
    manifest = {
        "schema": "rsdl-incident-v1",
        "reason": reason,
        "verdict": verdict,
        "created_unix": time.time(),
        "pid": os.getpid(),
        "host": socket.gethostname(),
        "pids": sorted(set(pids)),
        "pids_signaled": signaled,
        "traces": trace_files,
        "profile": profile_summary,
        "latency": latency_summary,
        "files": sorted(os.listdir(capsule)),
    }
    with open(os.path.join(capsule, "capsule.json"), "w",
              encoding="utf-8") as f:
        json.dump(manifest, f, indent=2)
    rt_metrics.counter("rsdl_incident_capsules_total",
                       "incident capsules captured").inc()
    rt_telemetry.record("incident_capsule", reason=reason,
                        detector=detector, path=capsule)
    logger.error("incident capsule (%s): %s [pids %s]", reason, capsule,
                 manifest["pids"])
    return capsule


def _fresh_dumps(trace_dir: str, pid: int, since_mono: float) -> List[str]:
    """Dump files for ``pid`` in ``trace_dir`` written after the capture
    started (mtime compared on a monotonic-anchored wall offset — the
    capture and the dumps happen on the same host)."""
    # Anchoring a monotonic capture start onto the wall clock is the only
    # way to compare against file mtimes (same host, sub-second window,
    # 1s slack below). rsdl-lint: disable=wallclock-interval
    since_wall = time.time() - (time.monotonic() - since_mono)
    out = []
    prefix = f"rsdl-telemetry-{pid}-"
    try:
        names = os.listdir(trace_dir)
    except OSError:
        return out
    for name in names:
        if not name.startswith(prefix) or not name.endswith(".jsonl"):
            continue
        path = os.path.join(trace_dir, name)
        try:
            if os.stat(path).st_mtime >= since_wall - 1.0:
                out.append(path)
        except OSError:
            continue
    return out


def install_incident_signal(signum: int = signal_mod.SIGUSR2) -> bool:
    """SIGUSR2 -> incident capsule on demand, the parallel of
    telemetry's SIGUSR1 recorder dump (``kill -USR2 <pid>`` on any armed
    driver). The handler only spawns the capture thread — capture does
    real I/O and must not run in signal context. Returns False (no-op)
    off the main thread or without the signal — callers never guard."""
    if threading.current_thread() is not threading.main_thread():
        return False

    def _handler(_signum, _frame):
        threading.Thread(
            target=capture_incident,
            kwargs={"reason": f"signal {_signum}", "cooldown_s": 0.0},
            daemon=True, name="rsdl-incident-capture").start()

    try:
        signal_mod.signal(signum, _handler)
    except (ValueError, OSError, AttributeError):
        return False
    return True
