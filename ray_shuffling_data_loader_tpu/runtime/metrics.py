"""Typed metrics registry with Prometheus text-format exposition.

The pipeline's quantitative state used to live in ad-hoc snapshot dicts
(``stats.watchdog_stats()``, ``stats.fault_stats()``, per-phase bench
dicts) with no shared naming, no types, and no way to observe a live
run without instrumenting the caller. This module is the ONE registry:
typed counters / gauges / fixed-bucket histograms behind a
``metrics.get(name)`` API, exposable as Prometheus text format to a
file (``write_file``) and an optional localhost HTTP endpoint
(``start_http_server``), with a hand-rolled :func:`parse_exposition`
so tooling (``tools/rsdl_top.py``, tests) can round-trip the output
without a Prometheus dependency.

Design constraints, in order:

- **Stdlib-only** (the runtime/ contract): importable before jax or
  pyarrow, and from the native layer without cycles.
- **Hot-path cheap**: a counter ``inc`` is one lock round-trip; metric
  lookup by name happens once at wiring time, not per event (call
  sites hold the metric object).
- **Mergeable histograms**: fixed bucket bounds shared per metric, so
  per-epoch histograms (telemetry's bottleneck attribution) merge into
  run totals by adding bucket counts.

Label support is deliberately minimal: a metric family keyed by name
holds one child per label set (``counter("rsdl_faults_injected_total",
site="map_read")``); exposition renders the standard
``name{label="value"} v`` lines.
"""

from __future__ import annotations

import bisect
import os
import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple

__all__ = [
    "Counter", "Gauge", "Histogram", "Registry", "REGISTRY",
    "counter", "gauge", "histogram", "get", "render", "parse_exposition",
    "write_file", "start_http_server", "start_exporter",
    "DEFAULT_LATENCY_BUCKETS",
]

#: Exponential-ish latency bucket upper bounds in SECONDS (``+Inf`` is
#: implicit). Spans 100us..60s — queue waits through cold map decodes.
DEFAULT_LATENCY_BUCKETS: Tuple[float, ...] = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


class Counter:
    """Monotonic float counter."""

    __slots__ = ("_lock", "_value")
    kind = "counter"

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counters only go up, got {amount}")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Set/inc/dec current-value metric."""

    __slots__ = ("_lock", "_value")
    kind = "gauge"

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value -= amount

    def max(self, value: float) -> None:
        """Keep the running maximum (recovery-latency style gauges)."""
        with self._lock:
            if value > self._value:
                self._value = float(value)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Fixed-bucket histogram with cumulative Prometheus semantics.

    ``bounds`` are upper bucket bounds (``+Inf`` implicit). Internally
    counts are per-bucket (NON-cumulative) so :meth:`merge` is a plain
    elementwise add; exposition renders the cumulative ``_bucket`` lines
    the text format requires. :meth:`percentile` interpolates linearly
    within the winning bucket — the conventional estimate for
    fixed-bucket histograms (upper-bounded by the bucket edge).
    """

    __slots__ = ("bounds", "_counts", "_sum", "_count", "_lock")
    kind = "histogram"

    def __init__(self, bounds: Iterable[float] = DEFAULT_LATENCY_BUCKETS):
        self.bounds: Tuple[float, ...] = tuple(sorted(bounds))
        if not self.bounds:
            raise ValueError("histogram needs at least one bucket bound")
        self._counts = [0] * (len(self.bounds) + 1)  # last = +Inf
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        index = bisect.bisect_left(self.bounds, value)
        with self._lock:
            self._counts[index] += 1
            self._sum += value
            self._count += 1

    def merge(self, other: "Histogram") -> None:
        """Add ``other``'s counts into this histogram (same bounds)."""
        if other.bounds != self.bounds:
            raise ValueError("cannot merge histograms with different "
                             f"bounds: {self.bounds} vs {other.bounds}")
        with other._lock:
            counts = list(other._counts)
            osum, ocount = other._sum, other._count
        with self._lock:
            for i, c in enumerate(counts):
                self._counts[i] += c
            self._sum += osum
            self._count += ocount

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def bucket_counts(self) -> List[int]:
        """Per-bucket (non-cumulative) counts; last entry is +Inf."""
        with self._lock:
            return list(self._counts)

    def percentile(self, q: float) -> float:
        """Estimated q-quantile (q in [0, 1]) by linear interpolation
        inside the winning bucket; 0.0 when empty. Values landing in the
        +Inf bucket report the largest finite bound (a floor, explicit
        rather than invented)."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"q must be in [0, 1], got {q}")
        with self._lock:
            total = self._count
            counts = list(self._counts)
        if total == 0:
            return 0.0
        rank = q * total
        seen = 0.0
        for i, c in enumerate(counts):
            if c == 0:
                continue
            if seen + c >= rank:
                lo = self.bounds[i - 1] if i > 0 else 0.0
                hi = (self.bounds[i] if i < len(self.bounds)
                      else self.bounds[-1])
                frac = (rank - seen) / c if c else 0.0
                return lo + (hi - lo) * min(1.0, max(0.0, frac))
            seen += c
        return self.bounds[-1]


Labels = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, str]) -> Labels:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _Family:
    """All children of one metric name (one per label set)."""

    __slots__ = ("name", "kind", "help", "buckets", "_children", "_lock")

    def __init__(self, name: str, kind: str, help_text: str,
                 buckets: Optional[Tuple[float, ...]] = None):
        self.name = name
        self.kind = kind
        self.help = help_text
        self.buckets = buckets
        self._children: Dict[Labels, object] = {}
        self._lock = threading.Lock()

    def child(self, labels: Dict[str, str]):
        key = _label_key(labels)
        with self._lock:
            metric = self._children.get(key)
            if metric is None:
                if self.kind == "counter":
                    metric = Counter()
                elif self.kind == "gauge":
                    metric = Gauge()
                else:
                    metric = Histogram(self.buckets
                                       or DEFAULT_LATENCY_BUCKETS)
                self._children[key] = metric
            return metric

    def children(self) -> Dict[Labels, object]:
        with self._lock:
            return dict(self._children)


class Registry:
    """Name -> family index with get-or-create typed accessors."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}

    def _family(self, name: str, kind: str, help_text: str,
                buckets=None) -> _Family:
        with self._lock:
            family = self._families.get(name)
            if family is None:
                family = _Family(name, kind, help_text,
                                 tuple(buckets) if buckets else None)
                self._families[name] = family
            elif family.kind != kind:
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{family.kind}, requested {kind}")
            return family

    # name/help_text are positional-only so label keys may legally be
    # "name" or "help_text" (e.g. rsdl_watchdog_stalls_total{name=...}).
    def counter(self, name: str, help_text: str = "", /,
                **labels: str) -> Counter:
        return self._family(name, "counter", help_text).child(labels)

    def gauge(self, name: str, help_text: str = "", /,
              **labels: str) -> Gauge:
        return self._family(name, "gauge", help_text).child(labels)

    def histogram(self, name: str, help_text: str = "", /, buckets=None,
                  **labels: str) -> Histogram:
        return self._family(name, "histogram", help_text,
                            buckets=buckets).child(labels)

    def get(self, name: str, labels: Optional[Dict[str, str]] = None):
        """Look up a registered metric: the family when ``labels`` is
        None and the family is labeled, else the child. Returns None
        for unknown names (observability lookups must never raise)."""
        with self._lock:
            family = self._families.get(name)
        if family is None:
            return None
        children = family.children()
        if labels is not None:
            return children.get(_label_key(labels))
        if list(children.keys()) == [()]:
            return children[()]
        return family

    def families(self) -> Dict[str, _Family]:
        with self._lock:
            return dict(self._families)

    # -- exposition ---------------------------------------------------------

    def render(self) -> str:
        """Prometheus text format (v0.0.4) of every registered metric."""
        out: List[str] = []
        for name in sorted(self._families):
            family = self._families[name]
            if family.help:
                out.append(f"# HELP {name} {family.help}")
            out.append(f"# TYPE {name} {family.kind}")
            for labels, metric in sorted(family.children().items()):
                label_txt = _format_labels(labels)
                if family.kind in ("counter", "gauge"):
                    out.append(f"{name}{label_txt} {_fmt(metric.value)}")
                    continue
                cumulative = 0
                counts = metric.bucket_counts()
                for bound, count in zip(metric.bounds, counts):
                    cumulative += count
                    le = _label_key(dict(labels) | {"le": _fmt(bound)})
                    out.append(f"{name}_bucket{_format_labels(le)} "
                               f"{cumulative}")
                cumulative += counts[-1]
                le = _label_key(dict(labels) | {"le": "+Inf"})
                out.append(
                    f"{name}_bucket{_format_labels(le)} {cumulative}")
                out.append(f"{name}_sum{label_txt} {_fmt(metric.sum)}")
                out.append(f"{name}_count{label_txt} {metric.count}")
        return "\n".join(out) + "\n"


def _fmt(value: float) -> str:
    if value == int(value) and abs(value) < 1e15:
        return str(int(value))
    return repr(float(value))


def _format_labels(labels: Labels) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape(v)}"' for k, v in labels)
    return "{" + inner + "}"


def _escape(value: str) -> str:
    return (str(value).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


#: THE process-wide registry; the module-level helpers below proxy it.
REGISTRY = Registry()


def counter(name: str, help_text: str = "", /, **labels: str) -> Counter:
    return REGISTRY.counter(name, help_text, **labels)


def gauge(name: str, help_text: str = "", /, **labels: str) -> Gauge:
    return REGISTRY.gauge(name, help_text, **labels)


def histogram(name: str, help_text: str = "", /, buckets=None,
              **labels: str) -> Histogram:
    return REGISTRY.histogram(name, help_text, buckets=buckets, **labels)


def get(name: str, labels: Optional[Dict[str, str]] = None):
    return REGISTRY.get(name, labels)


def render() -> str:
    return REGISTRY.render()


# ---------------------------------------------------------------------------
# Hand-rolled exposition parser (round-trip contract for tools + tests)
# ---------------------------------------------------------------------------


def parse_exposition(text: str) -> Dict[str, Dict[Labels, float]]:
    """Parse Prometheus text format into ``{name: {labels: value}}``.

    Covers exactly what :meth:`Registry.render` emits (names, quoted
    label values with escapes, int/float/``+Inf`` values); histogram
    series appear under their ``_bucket``/``_sum``/``_count`` names.
    Unparseable lines raise ``ValueError`` — a dump that does not
    round-trip is a bug, not noise.
    """
    out: Dict[str, Dict[Labels, float]] = {}
    for raw in text.splitlines():
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        name, labels, value_txt = _parse_sample(line)
        value = float("inf") if value_txt == "+Inf" else float(value_txt)
        out.setdefault(name, {})[labels] = value
    return out


def _parse_sample(line: str) -> Tuple[str, Labels, str]:
    if "{" in line:
        name, rest = line.split("{", 1)
        label_txt, rest = rest.split("}", 1)
        labels = _parse_labels(label_txt)
        value = rest.strip()
    else:
        parts = line.split()
        if len(parts) != 2:
            raise ValueError(f"unparseable exposition line: {line!r}")
        name, value = parts
        labels = ()
    if not name or not value:
        raise ValueError(f"unparseable exposition line: {line!r}")
    return name.strip(), labels, value


def _parse_labels(text: str) -> Labels:
    labels: List[Tuple[str, str]] = []
    i = 0
    while i < len(text):
        eq = text.index("=", i)
        key = text[i:eq].strip().lstrip(",").strip()
        assert text[eq + 1] == '"', f"unquoted label value in {text!r}"
        j = eq + 2
        value: List[str] = []
        while text[j] != '"':
            if text[j] == "\\":
                nxt = text[j + 1]
                value.append({"n": "\n", '"': '"', "\\": "\\"}.get(nxt, nxt))
                j += 2
                continue
            value.append(text[j])
            j += 1
        labels.append((key, "".join(value)))
        i = j + 1
    return tuple(sorted(labels))


# ---------------------------------------------------------------------------
# Exposition transports: file + localhost HTTP
# ---------------------------------------------------------------------------


def write_file(path: str) -> str:
    """Atomically write the current exposition to ``path``; returns it."""
    directory = os.path.dirname(os.path.abspath(path))
    os.makedirs(directory, exist_ok=True)
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(render())
    os.replace(tmp, path)
    return path


def start_http_server(port: int = 0, host: str = "127.0.0.1"):
    """Serve ``/metrics`` on localhost; returns ``(server, port)``.

    Loopback-only by default — the endpoint is an operator tool, not a
    service surface. The server runs on a named daemon thread; call
    ``server.shutdown()`` to stop it.
    """
    import http.server

    class _Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 - stdlib API
            if self.path.rstrip("/") not in ("", "/metrics", "/healthz"):
                self.send_response(404)
                self.end_headers()
                return
            body = render().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):  # silence per-request stderr spam
            pass

    server = http.server.ThreadingHTTPServer((host, port), _Handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True,
                              name="rsdl-metrics-http")
    thread.start()
    return server, server.server_address[1]


_exporter_lock = threading.Lock()
_exporter_stop: Optional[threading.Event] = None


def start_exporter(path: Optional[str] = None, port: Optional[int] = None,
                   interval_s: float = 5.0):
    """Periodic file exposition and/or HTTP endpoint, policy-resolvable.

    With no arguments, resolves ``metrics_file`` / ``metrics_port`` /
    ``metrics_interval_s`` from the runtime policy registry
    (``RSDL_METRICS_FILE=/run/rsdl.prom python bench.py`` is the
    zero-code way to watch any run with ``tools/rsdl_top.py``). Returns
    ``(stop_event, http_port_or_None)``; idempotent — a second call
    stops the previous file-writer loop first.
    """
    from ray_shuffling_data_loader_tpu.runtime import policy as rt_policy
    if path is None:
        path = rt_policy.resolve("metrics", "metrics_file") or None
    if port is None:
        port = rt_policy.resolve("metrics", "metrics_port") or None
    interval_s = rt_policy.resolve("metrics", "metrics_interval_s",
                                   default=interval_s)
    global _exporter_stop
    with _exporter_lock:
        if _exporter_stop is not None:
            _exporter_stop.set()
        stop = _exporter_stop = threading.Event()
    http_port = None
    if port is not None:
        _, http_port = start_http_server(int(port))
    if path:
        def _loop():
            while not stop.wait(interval_s):
                try:
                    write_file(path)
                except OSError:
                    pass  # scratch volume hiccup; next tick retries
            try:
                write_file(path)  # final flush on stop
            except OSError:
                pass

        write_file(path)
        threading.Thread(target=_loop, daemon=True,
                         name="rsdl-metrics-export").start()
    return stop, http_port
