"""Typed metrics registry with Prometheus text-format exposition.

The pipeline's quantitative state used to live in ad-hoc snapshot dicts
(``stats.watchdog_stats()``, ``stats.fault_stats()``, per-phase bench
dicts) with no shared naming, no types, and no way to observe a live
run without instrumenting the caller. This module is the ONE registry:
typed counters / gauges / fixed-bucket histograms behind a
``metrics.get(name)`` API, exposable as Prometheus text format to a
file (``write_file``) and an optional localhost HTTP endpoint
(``start_http_server``), with a hand-rolled :func:`parse_exposition`
so tooling (``tools/rsdl_top.py``, tests) can round-trip the output
without a Prometheus dependency.

Design constraints, in order:

- **Stdlib-only** (the runtime/ contract): importable before jax or
  pyarrow, and from the native layer without cycles.
- **Hot-path cheap**: a counter ``inc`` is one lock round-trip; metric
  lookup by name happens once at wiring time, not per event (call
  sites hold the metric object).
- **Mergeable histograms**: fixed bucket bounds shared per metric, so
  per-epoch histograms (telemetry's bottleneck attribution) merge into
  run totals by adding bucket counts.

Label support is deliberately minimal: a metric family keyed by name
holds one child per label set (``counter("rsdl_faults_injected_total",
site="map_read")``); exposition renders the standard
``name{label="value"} v`` lines.
"""

from __future__ import annotations

import bisect
import os
import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple

__all__ = [
    "Counter", "Gauge", "Histogram", "Sketch", "Registry", "REGISTRY",
    "counter", "gauge", "histogram", "sketch", "get", "render",
    "parse_exposition", "parse_exposition_typed", "write_file",
    "start_http_server", "start_exporter", "DEFAULT_LATENCY_BUCKETS",
    "DEFAULT_LATENCY_CENTROIDS", "sketch_quantiles",
    "telemetry_dir", "write_shard", "read_shards", "merge_series",
    "federated_series", "render_federated", "maybe_start_shard_writer",
]

#: Exponential-ish latency bucket upper bounds in SECONDS (``+Inf`` is
#: implicit). Spans 100us..60s — queue waits through cold map decodes.
DEFAULT_LATENCY_BUCKETS: Tuple[float, ...] = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)

#: Fixed latency-sketch centroids in SECONDS: 12 per decade,
#: geometrically spaced over 100us..100s (73 values, ratio 10^(1/12)
#: ~= 1.21 — quantile estimates land within ~±10% of truth, which is
#: the error a p99 SLO can live with). FIXED on purpose: every process
#: assigns an observation to the same centroid, so per-pid counts sum
#: EXACTLY under the shard federation (`merge_series`) — the property
#: mergeable-quantile structures (t-digest et al.) only approximate.
DEFAULT_LATENCY_CENTROIDS: Tuple[float, ...] = tuple(
    round(10.0 ** (exp / 12.0), 9) for exp in range(-48, 25))


class Counter:
    """Monotonic float counter."""

    __slots__ = ("_lock", "_value")
    kind = "counter"

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counters only go up, got {amount}")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Set/inc/dec current-value metric."""

    __slots__ = ("_lock", "_value")
    kind = "gauge"

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value -= amount

    def max(self, value: float) -> None:
        """Keep the running maximum (recovery-latency style gauges)."""
        with self._lock:
            if value > self._value:
                self._value = float(value)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Fixed-bucket histogram with cumulative Prometheus semantics.

    ``bounds`` are upper bucket bounds (``+Inf`` implicit). Internally
    counts are per-bucket (NON-cumulative) so :meth:`merge` is a plain
    elementwise add; exposition renders the cumulative ``_bucket`` lines
    the text format requires. :meth:`percentile` interpolates linearly
    within the winning bucket — the conventional estimate for
    fixed-bucket histograms (upper-bounded by the bucket edge).
    """

    __slots__ = ("bounds", "_counts", "_sum", "_count", "_lock")
    kind = "histogram"

    def __init__(self, bounds: Iterable[float] = DEFAULT_LATENCY_BUCKETS):
        self.bounds: Tuple[float, ...] = tuple(sorted(bounds))
        if not self.bounds:
            raise ValueError("histogram needs at least one bucket bound")
        self._counts = [0] * (len(self.bounds) + 1)  # last = +Inf
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        index = bisect.bisect_left(self.bounds, value)
        with self._lock:
            self._counts[index] += 1
            self._sum += value
            self._count += 1

    def merge(self, other: "Histogram") -> None:
        """Add ``other``'s counts into this histogram (same bounds)."""
        if other.bounds != self.bounds:
            raise ValueError("cannot merge histograms with different "
                             f"bounds: {self.bounds} vs {other.bounds}")
        with other._lock:
            counts = list(other._counts)
            osum, ocount = other._sum, other._count
        with self._lock:
            for i, c in enumerate(counts):
                self._counts[i] += c
            self._sum += osum
            self._count += ocount

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def bucket_counts(self) -> List[int]:
        """Per-bucket (non-cumulative) counts; last entry is +Inf."""
        with self._lock:
            return list(self._counts)

    def percentile(self, q: float) -> float:
        """Estimated q-quantile (q in [0, 1]) by linear interpolation
        inside the winning bucket; 0.0 when empty. Values landing in the
        +Inf bucket report the largest finite bound (a floor, explicit
        rather than invented)."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"q must be in [0, 1], got {q}")
        with self._lock:
            total = self._count
            counts = list(self._counts)
        if total == 0:
            return 0.0
        rank = q * total
        seen = 0.0
        for i, c in enumerate(counts):
            if c == 0:
                continue
            if seen + c >= rank:
                lo = self.bounds[i - 1] if i > 0 else 0.0
                hi = (self.bounds[i] if i < len(self.bounds)
                      else self.bounds[-1])
                frac = (rank - seen) / c if c else 0.0
                return lo + (hi - lo) * min(1.0, max(0.0, frac))
            seen += c
        return self.bounds[-1]


class Sketch:
    """Mergeable fixed-centroid latency sketch (the delivery-latency
    plane's quantile primitive, runtime/latency.py).

    Observations snap to the nearest of a FIXED geometric centroid set
    (boundaries at geometric midpoints), so the sketch is a sparse
    ``{centroid: count}`` map. Quantiles read the cumulative walk over
    centroids; merging is plain per-centroid addition — **exact** under
    `merge_series`-style summation across process shards, unlike
    adaptive-centroid sketches whose merge is lossy. Exposition renders
    one ``name_centroid{c="<seconds>"} count`` line per NON-ZERO
    centroid plus ``_sum``/``_count``, so the text format stays sparse
    and round-trips through :func:`parse_exposition`.
    """

    __slots__ = ("centroids", "_bounds", "_counts", "_sum", "_count",
                 "_lock")
    kind = "sketch"

    def __init__(self,
                 centroids: Iterable[float] = DEFAULT_LATENCY_CENTROIDS):
        self.centroids: Tuple[float, ...] = tuple(sorted(centroids))
        if not self.centroids:
            raise ValueError("sketch needs at least one centroid")
        # Assignment boundaries: geometric midpoints between adjacent
        # centroids (natural for a log-spaced set).
        self._bounds = [
            (self.centroids[i] * self.centroids[i + 1]) ** 0.5
            for i in range(len(self.centroids) - 1)]
        self._counts = [0] * len(self.centroids)
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        value = max(0.0, float(value))
        index = bisect.bisect_right(self._bounds, value)
        with self._lock:
            self._counts[index] += 1
            self._sum += value
            self._count += 1

    def merge(self, other: "Sketch") -> None:
        """Add ``other``'s centroid counts into this sketch (exact)."""
        if other.centroids != self.centroids:
            raise ValueError("cannot merge sketches with different "
                             "centroid sets")
        with other._lock:
            counts = list(other._counts)
            osum, ocount = other._sum, other._count
        with self._lock:
            for i, c in enumerate(counts):
                self._counts[i] += c
            self._sum += osum
            self._count += ocount

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def centroid_counts(self) -> Dict[float, int]:
        """Sparse ``{centroid_seconds: count}`` of non-zero centroids."""
        with self._lock:
            return {c: n for c, n in zip(self.centroids, self._counts)
                    if n}

    def percentile(self, q: float) -> float:
        """q-quantile (q in [0, 1]) over the centroid mass; 0.0 when
        empty. By construction within one centroid-spacing ratio of the
        true quantile."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"q must be in [0, 1], got {q}")
        with self._lock:
            counts = list(self._counts)
            total = self._count
        return _centroid_quantile(
            {c: n for c, n in zip(self.centroids, counts) if n}, total, q)


def _centroid_quantile(counts: Dict[float, int], total: int,
                       q: float) -> float:
    """Quantile over a sparse {centroid: count} mass (shared by
    :meth:`Sketch.percentile` and :func:`sketch_quantiles`)."""
    if total <= 0:
        return 0.0
    rank = q * total
    seen = 0.0
    last = 0.0
    for centroid in sorted(counts):
        last = centroid
        seen += counts[centroid]
        if seen >= rank:
            return centroid
    return last


Labels = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, str]) -> Labels:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _Family:
    """All children of one metric name (one per label set)."""

    __slots__ = ("name", "kind", "help", "buckets", "_children", "_lock")

    def __init__(self, name: str, kind: str, help_text: str,
                 buckets: Optional[Tuple[float, ...]] = None):
        self.name = name
        self.kind = kind
        self.help = help_text
        self.buckets = buckets
        self._children: Dict[Labels, object] = {}
        self._lock = threading.Lock()

    def child(self, labels: Dict[str, str]):
        key = _label_key(labels)
        with self._lock:
            metric = self._children.get(key)
            if metric is None:
                if self.kind == "counter":
                    metric = Counter()
                elif self.kind == "gauge":
                    metric = Gauge()
                elif self.kind == "sketch":
                    # The centroid set is deliberately NOT configurable:
                    # fixed centroids are what make cross-pid merges
                    # exact (every process bins identically).
                    metric = Sketch()
                else:
                    metric = Histogram(self.buckets
                                       or DEFAULT_LATENCY_BUCKETS)
                self._children[key] = metric
            return metric

    def children(self) -> Dict[Labels, object]:
        with self._lock:
            return dict(self._children)


class Registry:
    """Name -> family index with get-or-create typed accessors."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}

    def _family(self, name: str, kind: str, help_text: str,
                buckets=None) -> _Family:
        with self._lock:
            family = self._families.get(name)
            if family is None:
                family = _Family(name, kind, help_text,
                                 tuple(buckets) if buckets else None)
                self._families[name] = family
            elif family.kind != kind:
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{family.kind}, requested {kind}")
            return family

    # name/help_text are positional-only so label keys may legally be
    # "name" or "help_text" (e.g. rsdl_watchdog_stalls_total{name=...}).
    def counter(self, name: str, help_text: str = "", /,
                **labels: str) -> Counter:
        return self._family(name, "counter", help_text).child(labels)

    def gauge(self, name: str, help_text: str = "", /,
              **labels: str) -> Gauge:
        return self._family(name, "gauge", help_text).child(labels)

    def histogram(self, name: str, help_text: str = "", /, buckets=None,
                  **labels: str) -> Histogram:
        return self._family(name, "histogram", help_text,
                            buckets=buckets).child(labels)

    def sketch(self, name: str, help_text: str = "", /,
               **labels: str) -> Sketch:
        return self._family(name, "sketch", help_text).child(labels)

    def get(self, name: str, labels: Optional[Dict[str, str]] = None):
        """Look up a registered metric: the family when ``labels`` is
        None and the family is labeled, else the child. Returns None
        for unknown names (observability lookups must never raise)."""
        with self._lock:
            family = self._families.get(name)
        if family is None:
            return None
        children = family.children()
        if labels is not None:
            return children.get(_label_key(labels))
        if list(children.keys()) == [()]:
            return children[()]
        return family

    def families(self) -> Dict[str, _Family]:
        with self._lock:
            return dict(self._families)

    # -- exposition ---------------------------------------------------------

    def render(self) -> str:
        """Prometheus text format (v0.0.4) of every registered metric."""
        out: List[str] = []
        for name in sorted(self._families):
            family = self._families[name]
            if family.help:
                out.append(f"# HELP {name} {family.help}")
            out.append(f"# TYPE {name} {family.kind}")
            for labels, metric in sorted(family.children().items()):
                label_txt = _format_labels(labels)
                if family.kind in ("counter", "gauge"):
                    out.append(f"{name}{label_txt} {_fmt(metric.value)}")
                    continue
                if family.kind == "sketch":
                    # Sparse: one line per non-zero centroid. Counts are
                    # NON-cumulative so federation summing is exact.
                    for centroid, count in sorted(
                            metric.centroid_counts().items()):
                        ct = _label_key(dict(labels)
                                        | {"c": _fmt(centroid)})
                        out.append(f"{name}_centroid{_format_labels(ct)} "
                                   f"{count}")
                    out.append(f"{name}_sum{label_txt} {_fmt(metric.sum)}")
                    out.append(f"{name}_count{label_txt} {metric.count}")
                    continue
                cumulative = 0
                counts = metric.bucket_counts()
                for bound, count in zip(metric.bounds, counts):
                    cumulative += count
                    le = _label_key(dict(labels) | {"le": _fmt(bound)})
                    out.append(f"{name}_bucket{_format_labels(le)} "
                               f"{cumulative}")
                cumulative += counts[-1]
                le = _label_key(dict(labels) | {"le": "+Inf"})
                out.append(
                    f"{name}_bucket{_format_labels(le)} {cumulative}")
                out.append(f"{name}_sum{label_txt} {_fmt(metric.sum)}")
                out.append(f"{name}_count{label_txt} {metric.count}")
        return "\n".join(out) + "\n"


def _fmt(value: float) -> str:
    if value == int(value) and abs(value) < 1e15:
        return str(int(value))
    return repr(float(value))


def _format_labels(labels: Labels) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape(v)}"' for k, v in labels)
    return "{" + inner + "}"


def _escape(value: str) -> str:
    return (str(value).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


#: THE process-wide registry; the module-level helpers below proxy it.
REGISTRY = Registry()


def counter(name: str, help_text: str = "", /, **labels: str) -> Counter:
    return REGISTRY.counter(name, help_text, **labels)


def gauge(name: str, help_text: str = "", /, **labels: str) -> Gauge:
    return REGISTRY.gauge(name, help_text, **labels)


def histogram(name: str, help_text: str = "", /, buckets=None,
              **labels: str) -> Histogram:
    return REGISTRY.histogram(name, help_text, buckets=buckets, **labels)


def sketch(name: str, help_text: str = "", /, **labels: str) -> Sketch:
    return REGISTRY.sketch(name, help_text, **labels)


def get(name: str, labels: Optional[Dict[str, str]] = None):
    return REGISTRY.get(name, labels)


def render() -> str:
    return REGISTRY.render()


# ---------------------------------------------------------------------------
# Hand-rolled exposition parser (round-trip contract for tools + tests)
# ---------------------------------------------------------------------------


def parse_exposition_typed(
        text: str) -> "tuple[Dict[str, Dict[Labels, float]], Dict[str, str]]":
    """:func:`parse_exposition` plus the ``# TYPE`` metadata: returns
    ``(samples, types)`` where ``types`` maps family name -> kind. The
    federation merge needs the kinds to re-render a merged exposition
    that itself round-trips."""
    types: Dict[str, str] = {}
    for raw in text.splitlines():
        line = raw.strip()
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) == 4:
                types[parts[2]] = parts[3]
    return parse_exposition(text), types


def parse_exposition(text: str) -> Dict[str, Dict[Labels, float]]:
    """Parse Prometheus text format into ``{name: {labels: value}}``.

    Covers exactly what :meth:`Registry.render` emits (names, quoted
    label values with escapes, int/float/``+Inf`` values); histogram
    series appear under their ``_bucket``/``_sum``/``_count`` names.
    Unparseable lines raise ``ValueError`` — a dump that does not
    round-trip is a bug, not noise.
    """
    out: Dict[str, Dict[Labels, float]] = {}
    for raw in text.splitlines():
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        name, labels, value_txt = _parse_sample(line)
        value = float("inf") if value_txt == "+Inf" else float(value_txt)
        out.setdefault(name, {})[labels] = value
    return out


def _parse_sample(line: str) -> Tuple[str, Labels, str]:
    if "{" in line:
        name, rest = line.split("{", 1)
        label_txt, rest = rest.split("}", 1)
        labels = _parse_labels(label_txt)
        value = rest.strip()
    else:
        parts = line.split()
        if len(parts) != 2:
            raise ValueError(f"unparseable exposition line: {line!r}")
        name, value = parts
        labels = ()
    if not name or not value:
        raise ValueError(f"unparseable exposition line: {line!r}")
    return name.strip(), labels, value


def _parse_labels(text: str) -> Labels:
    labels: List[Tuple[str, str]] = []
    i = 0
    while i < len(text):
        eq = text.index("=", i)
        key = text[i:eq].strip().lstrip(",").strip()
        assert text[eq + 1] == '"', f"unquoted label value in {text!r}"
        j = eq + 2
        value: List[str] = []
        while text[j] != '"':
            if text[j] == "\\":
                nxt = text[j + 1]
                value.append({"n": "\n", '"': '"', "\\": "\\"}.get(nxt, nxt))
                j += 2
                continue
            value.append(text[j])
            j += 1
        labels.append((key, "".join(value)))
        i = j + 1
    return tuple(sorted(labels))


def sketch_quantiles(samples: Dict[str, "Dict[Labels, float]"],
                     name: str,
                     qs: Tuple[float, ...] = (0.5, 0.95, 0.99),
                     **label_filter: str
                     ) -> "Dict[Labels, Dict[str, float]]":
    """Quantiles of a sketch family from PARSED exposition samples
    (one process's, or the federation-merged view — the centroid counts
    sum exactly either way).

    Groups ``<name>_centroid`` samples by their labels minus the
    structural ``c`` label, optionally restricted by ``label_filter``
    equality; returns ``{group_labels: {"p50": s, ..., "count": n}}``
    (quantile keys are ``p<100q>`` in seconds). Tools (rsdl_top, the
    run report), the health detectors and the bench latency leg all
    read the plane through this one function.
    """
    grouped: Dict[Labels, Dict[float, int]] = {}
    for labels, value in samples.get(f"{name}_centroid", {}).items():
        d = dict(labels)
        centroid_txt = d.pop("c", None)
        if centroid_txt is None:
            continue
        if any(d.get(k) != str(v) for k, v in label_filter.items()):
            continue
        key = tuple(sorted(d.items()))
        counts = grouped.setdefault(key, {})
        centroid = float(centroid_txt)
        counts[centroid] = counts.get(centroid, 0.0) + value
    out: Dict[Labels, Dict[str, float]] = {}
    for key, counts in grouped.items():
        total = int(sum(counts.values()))
        stats = {"count": float(total)}
        for q in qs:
            stats[f"p{int(round(q * 100))}"] = _centroid_quantile(
                counts, total, q)
        out[key] = stats
    return out


def distribution_masses(samples: Dict[str, "Dict[Labels, float]"],
                        family: str, kind: str
                        ) -> "Dict[Labels, Dict[float, float]]":
    """Per-group bucket/centroid mass of one distribution family from
    PARSED exposition samples: ``{group_labels: {edge: mass}}``.

    For histograms the cumulative ``_bucket`` series is differenced into
    per-bucket mass (edge = ``le`` upper bound, ``+Inf`` included); for
    sketches the ``_centroid`` counts are already masses (edge = the
    centroid value). Group labels drop the structural ``le``/``c``
    label. This is the one shape the differential engine
    (``runtime/regress.py``) compares distributions in, so histogram
    and sketch families diff through identical bucket-overlap math.
    """
    struct_label = "le" if kind == "histogram" else "c"
    series = samples.get(
        f"{family}_bucket" if kind == "histogram" else f"{family}_centroid",
        {})
    grouped: Dict[Labels, Dict[float, float]] = {}
    for labels, value in series.items():
        d = dict(labels)
        edge_txt = d.pop(struct_label, None)
        if edge_txt is None:
            continue
        edge = float("inf") if edge_txt == "+Inf" else float(edge_txt)
        key = tuple(sorted(d.items()))
        grouped.setdefault(key, {})[edge] = \
            grouped.get(key, {}).get(edge, 0.0) + value
    if kind != "histogram":
        return grouped
    out: Dict[Labels, Dict[float, float]] = {}
    for key, cumulative in grouped.items():
        masses: Dict[float, float] = {}
        prev = 0.0
        for edge in sorted(cumulative):
            masses[edge] = max(0.0, cumulative[edge] - prev)
            prev = cumulative[edge]
        out[key] = masses
    return out


# ---------------------------------------------------------------------------
# Multi-process federation: per-pid exposition shards + merge reader
# ---------------------------------------------------------------------------
#
# Since the data plane moved into spawn-mode pool workers (procpool.py),
# most map/reduce samples live in OTHER processes' registries — a
# driver-only exposition under-counts exactly the processes doing the
# work. The federation contract mirrors RSDL_TRACE_DIR: every process
# whose environment carries RSDL_TELEMETRY_DIR writes its registry as a
# per-pid shard file there (periodically + at exit), and readers merge
# the shards into cluster-wide totals. Counters and histogram series sum
# exactly; gauges also SUM in the merged view (pool widths, queue depths
# and ledger bytes are additive across processes) — the per-pid view
# (rsdl_top --dir, read_shards) keeps the unaggregated truth.

_SHARD_PREFIX = "rsdl-metrics-"


def telemetry_dir() -> Optional[str]:
    """The federation shard directory (RSDL_TELEMETRY_DIR), or None."""
    from ray_shuffling_data_loader_tpu.runtime import policy as rt_policy
    return rt_policy.resolve("metrics", "telemetry_dir") or None


def shard_path(directory: str, pid: Optional[int] = None) -> str:
    return os.path.join(directory, f"{_SHARD_PREFIX}{pid or os.getpid()}.prom")


def write_shard(directory: Optional[str] = None) -> Optional[str]:
    """Atomically write THIS process's exposition as its per-pid shard;
    returns the path (None when no directory is configured)."""
    directory = directory or telemetry_dir()
    if not directory:
        return None
    os.makedirs(directory, exist_ok=True)
    path = shard_path(directory)
    tmp = f"{path}.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(render())
    os.replace(tmp, path)
    return path


def read_shards(directory: str, skip_pid: Optional[int] = None
                ) -> "Dict[int, tuple]":
    """Parse every shard in ``directory``: ``{pid: (samples, types,
    age_s)}``. Unparseable/torn shards are skipped (the writer is atomic,
    but a reader must survive a shard mid-replace on exotic filesystems);
    ``age_s`` is seconds since the shard was last rewritten."""
    out: Dict[int, tuple] = {}
    try:
        names = os.listdir(directory)
    except OSError:
        return out
    now = time.time()
    for name in names:
        if not name.startswith(_SHARD_PREFIX) or not name.endswith(".prom"):
            continue
        try:
            pid = int(name[len(_SHARD_PREFIX):-len(".prom")])
        except ValueError:
            continue
        if skip_pid is not None and pid == skip_pid:
            continue
        path = os.path.join(directory, name)
        try:
            with open(path, encoding="utf-8") as f:
                text = f.read()
            samples, types = parse_exposition_typed(text)
        except (OSError, ValueError, AssertionError):
            continue
        try:
            # Shard age vs a file mtime: both are wall clock by nature
            # (freshness display only, never a deadline).
            # rsdl-lint: disable=wallclock-interval
            age_s = max(0.0, now - os.stat(path).st_mtime)
        except OSError:
            age_s = 0.0
        out[pid] = (samples, types, age_s)
    return out


def merge_series(shards: Iterable["tuple"]) -> "tuple":
    """Sum ``(samples, types)`` pairs element-wise into one
    ``(samples, types)``. Counter/histogram series merge exactly by
    construction (cumulative counts add); gauges sum — the cluster-wide
    aggregate — and the per-pid shards remain the per-process view."""
    merged: Dict[str, Dict[Labels, float]] = {}
    types: Dict[str, str] = {}
    for entry in shards:
        samples, kinds = entry[0], entry[1]
        for name, series in samples.items():
            into = merged.setdefault(name, {})
            for labels, value in series.items():
                into[labels] = into.get(labels, 0.0) + value
        types.update(kinds)
    return merged, types


def federated_series() -> "tuple":
    """``(samples, types, pids)`` of the cluster-wide view: this
    process's LIVE registry merged with every other pid's shard under
    the telemetry dir (no dir configured: just the live registry)."""
    own = parse_exposition_typed(render())
    directory = telemetry_dir()
    pids = [os.getpid()]
    shards = [own]
    if directory:
        for pid, entry in sorted(read_shards(directory,
                                             skip_pid=os.getpid()).items()):
            pids.append(pid)
            shards.append(entry)
    samples, types = merge_series(shards)
    samples["rsdl_federated_processes"] = {(): float(len(pids))}
    types["rsdl_federated_processes"] = "gauge"
    return samples, types, pids


def render_merged(samples: Dict[str, Dict[Labels, float]],
                  types: Dict[str, str]) -> str:
    """Render merged series back to exposition text (round-trips through
    :func:`parse_exposition_typed`). TYPE lines are emitted per family
    (histogram series look up their ``_bucket``/``_sum``/``_count``
    base name)."""
    out: List[str] = []
    typed_done = set()
    for name in sorted(samples):
        base = name
        for suffix in ("_bucket", "_centroid", "_sum", "_count"):
            if name.endswith(suffix) and name[:-len(suffix)] in types:
                base = name[:-len(suffix)]
                break
        if base in types and base not in typed_done:
            typed_done.add(base)
            out.append(f"# TYPE {base} {types[base]}")
        for labels, value in sorted(samples[name].items()):
            out.append(f"{name}{_format_labels(labels)} {_fmt(value)}")
    return "\n".join(out) + "\n"


def render_federated() -> str:
    samples, types, _ = federated_series()
    return render_merged(samples, types)


_shard_writer_lock = threading.Lock()
_shard_writer_started = False


def maybe_start_shard_writer(interval_s: Optional[float] = None) -> bool:
    """Start this process's periodic shard writer iff RSDL_TELEMETRY_DIR
    is configured (idempotent; registers an atexit final flush so even a
    short-lived worker's last counts land). Every participating process
    — driver, procpool worker, supervised queue server — calls this at
    startup; the env inherits through spawn/fork like RSDL_TRACE_DIR."""
    global _shard_writer_started
    if telemetry_dir() is None:
        return False
    from ray_shuffling_data_loader_tpu.runtime import policy as rt_policy
    interval_s = rt_policy.resolve("metrics", "metrics_shard_interval_s",
                                   override=interval_s)
    with _shard_writer_lock:
        if _shard_writer_started:
            return True
        _shard_writer_started = True
    import atexit

    def _flush() -> None:
        try:
            write_shard()
        except OSError:
            pass  # scratch volume went away at teardown; nothing to save

    def _loop() -> None:
        stop = threading.Event()
        while not stop.wait(interval_s):
            _flush()

    atexit.register(_flush)
    _flush()
    threading.Thread(target=_loop, daemon=True,
                     name="rsdl-metrics-shard").start()
    return True


# ---------------------------------------------------------------------------
# Exposition transports: file + localhost HTTP
# ---------------------------------------------------------------------------


def _exposition_text() -> str:
    """What the transports serve: the federated view when a telemetry
    dir is configured (cluster-wide truth), else this registry alone."""
    if telemetry_dir() is not None:
        try:
            return render_federated()
        except (OSError, ValueError):
            pass  # torn shard dir mid-teardown; fall back to own registry
    return render()


def write_file(path: str) -> str:
    """Atomically write the current exposition to ``path``; returns it.
    With RSDL_TELEMETRY_DIR set this is the MERGED multi-process view."""
    directory = os.path.dirname(os.path.abspath(path))
    os.makedirs(directory, exist_ok=True)
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(_exposition_text())
    os.replace(tmp, path)
    return path


def start_http_server(port: int = 0, host: str = "127.0.0.1"):
    """Serve ``/metrics`` on localhost; returns ``(server, port)``.

    Loopback-only by default — the endpoint is an operator tool, not a
    service surface. The server runs on a named daemon thread; call
    ``server.shutdown()`` to stop it.
    """
    import http.server

    class _Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 - stdlib API
            if self.path.rstrip("/") not in ("", "/metrics", "/healthz"):
                self.send_response(404)
                self.end_headers()
                return
            body = _exposition_text().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):  # silence per-request stderr spam
            pass

    server = http.server.ThreadingHTTPServer((host, port), _Handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True,
                              name="rsdl-metrics-http")
    thread.start()
    return server, server.server_address[1]


_exporter_lock = threading.Lock()
_exporter_stop: Optional[threading.Event] = None


def start_exporter(path: Optional[str] = None, port: Optional[int] = None,
                   interval_s: float = 5.0):
    """Periodic file exposition and/or HTTP endpoint, policy-resolvable.

    With no arguments, resolves ``metrics_file`` / ``metrics_port`` /
    ``metrics_interval_s`` from the runtime policy registry
    (``RSDL_METRICS_FILE=/run/rsdl.prom python bench.py`` is the
    zero-code way to watch any run with ``tools/rsdl_top.py``). Returns
    ``(stop_event, http_port_or_None)``; idempotent — a second call
    stops the previous file-writer loop first.
    """
    from ray_shuffling_data_loader_tpu.runtime import policy as rt_policy
    if path is None:
        path = rt_policy.resolve("metrics", "metrics_file") or None
    if port is None:
        port = rt_policy.resolve("metrics", "metrics_port") or None
    interval_s = rt_policy.resolve("metrics", "metrics_interval_s",
                                   default=interval_s)
    global _exporter_stop
    with _exporter_lock:
        if _exporter_stop is not None:
            _exporter_stop.set()
        stop = _exporter_stop = threading.Event()
    # Join the federation as a writer too (no-op without a dir): the
    # driver's shard is what per-pid views (rsdl_top --dir) show for it.
    maybe_start_shard_writer()
    http_port = None
    if port is not None:
        _, http_port = start_http_server(int(port))
    if path:
        def _loop():
            while not stop.wait(interval_s):
                try:
                    write_file(path)
                except OSError:
                    pass  # scratch volume hiccup; next tick retries
            try:
                write_file(path)  # final flush on stop
            except OSError:
                pass

        write_file(path)
        threading.Thread(target=_loop, daemon=True,
                         name="rsdl-metrics-export").start()
    return stop, http_port
