"""Degradation-policy registry: one resolution surface for runtime knobs.

The pipeline accumulated operational mitigations that lived only in the
bench harness (``RSDL_BENCH_DEVICE_REBATCH=0`` to force the per-batch
transfer path, ad-hoc timeouts in module constants). Production traffic
needs those to be LIBRARY behavior: every runtime knob resolves through
this module, with one precedence order everywhere::

    explicit kwarg > RSDL_<COMPONENT>_<KEY> env > RSDL_<KEY> env
                   > registered component default > library default

Components are short names for the subsystem consulting the policy
(``jax_dataset``, ``shuffle``, ``spill``, ``bench``). Example: a host
whose device tunnel is known-flaky exports ``RSDL_DEVICE_REBATCH=0`` and
every loader in every process degrades to per-batch transfers, while
``RSDL_JAX_DATASET_BULK_TRANSFER_DEADLINE_S=5`` tightens only the
loader's bulk-transfer watchdog.

Stdlib-only on purpose: policy must be importable before (and without)
jax/pyarrow, and from the native layer without cycles.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict, Optional

_FALSE_WORDS = frozenset({"0", "false", "no", "off"})


def _parse_bool(raw: str) -> bool:
    return raw.strip().lower() not in _FALSE_WORDS


def _parse_tristate(raw: str):
    """``"auto"`` stays the string sentinel; anything else parses as bool."""
    word = raw.strip().lower()
    if word == "auto":
        return "auto"
    return _parse_bool(word)


#: key -> (library default, parser for env-var strings). The parser also
#: normalizes programmatic overrides where cheap (bools stay bools).
_KEYS: Dict[str, "tuple[Any, Callable[[str], Any]]"] = {
    # Bulk device-rebatch mode: "auto" / True / False. A False here turns
    # the bench-only RSDL_BENCH_DEVICE_REBATCH=0 mitigation into the
    # library default for every loader in the process.
    "device_rebatch": ("auto", _parse_tristate),
    # Progress watchdog over the bulk transfer/carve path.
    "watchdog": (True, _parse_bool),
    # Seconds a single bulk chunk device_put/carve may run before the
    # watchdog declares a stall. Generous by default: a miss is meant to
    # catch wedged transports, not slow ones.
    "bulk_transfer_deadline_s": (30.0, float),
    # What a stall does: "degrade" (drop to the per-batch path and keep
    # going), "warn" (log + stats only), "raise" (fail the producer).
    "stall_action": ("degrade", str),
    # How long an epoch launch waits for consumers to release tables when
    # over max_inflight_bytes before proceeding with a warning.
    "budget_wait_timeout_s": (30.0, float),
    # Upper bound between predicate re-checks in release-event waits — a
    # safety heartbeat, not a polling cadence (releases wake waiters
    # immediately).
    "release_heartbeat_s": (0.25, float),
    # Free-list trim cooldown under sustained budget pressure (spill.py).
    "trim_cooldown_s": (1.0, float),
    # Watchdog monitor thread poll interval.
    "watchdog_poll_interval_s": (0.05, float),
    # Shared RetryPolicy defaults (runtime/retry.py): total attempts,
    # decorrelated-jitter backoff bounds, and a wall-clock deadline for
    # the whole call-plus-retries (<= 0 means no deadline). Resolved per
    # component, so e.g. RSDL_TRANSPORT_RETRY_MAX_ATTEMPTS=60 deepens
    # only the transport's connect redial budget.
    "retry_max_attempts": (3, int),
    "retry_initial_backoff_s": (0.05, float),
    "retry_max_backoff_s": (2.0, float),
    "retry_deadline_s": (0.0, float),
    # Telemetry spine (runtime/telemetry.py): flight-recorder on/off,
    # ring capacity (events), and where escalation/SIGUSR1 dumps land
    # ("" = the system temp dir).
    "telemetry": (True, _parse_bool),
    "telemetry_capacity": (4096, int),
    "telemetry_dump_dir": ("", str),
    # Causal tracing (runtime/trace.py): when set, EVERY process dumps
    # its flight recorder into this directory at exit (and dump()
    # defaults there), so `tools/rsdl_trace.py <dir>` can merge the
    # multi-process story. Child processes (supervised queue servers)
    # inherit it through the environment.
    "trace_dir": ("", str),
    # Continuous sampling profiler (runtime/profiler.py): stdlib stack
    # sampling over named threads + per-thread CPU attribution. Off by
    # default; the interval bounds its overhead (~1 stack walk per
    # thread per tick).
    "profiler": (False, _parse_bool),
    "profiler_interval_s": (0.01, float),
    # Batch-wait share of wall clock above which the per-epoch verdict
    # names a producer stage instead of train_step (the <=10% stall
    # contract's mirror image).
    "bottleneck_stall_threshold_pct": (10.0, float),
    # Metrics exposition (runtime/metrics.py): Prometheus text file path
    # ("" = off), localhost HTTP port (0 = off), file rewrite cadence.
    "metrics_file": ("", str),
    "metrics_port": (0, int),
    "metrics_interval_s": (5.0, float),
    # Multi-process metrics federation (runtime/metrics.py): when set,
    # EVERY process (driver, procpool workers, supervised queue servers)
    # periodically writes a per-pid exposition shard into this directory
    # (same inherit-via-env pattern as RSDL_TRACE_DIR), and the driver's
    # exposition file / HTTP endpoint / rsdl_top merge the shards into
    # cluster-wide totals with a per-pid view.
    "telemetry_dir": ("", str),
    "metrics_shard_interval_s": (2.0, float),
    # Time-series history ring (runtime/history.py): periodic registry
    # snapshots in fixed memory, ticked from the watchdog monitor thread.
    "history_interval_s": (1.0, float),
    "history_capacity": (600, int),
    # Health/SLO detector engine (runtime/health.py): detectors evaluate
    # on every history tick with hysteresis (breach must persist
    # `health_fire_ticks` ticks to fire; `health_clear_ticks` clean ticks
    # re-arm it) so a noisy tick cannot flap a verdict.
    "health": (True, _parse_bool),
    "health_fire_ticks": (3, int),
    "health_clear_ticks": (5, int),
    # SLO thresholds (RSDL_SLO_* via the generic env rung; component
    # form RSDL_HEALTH_SLO_* wins over it). Detector semantics live in
    # runtime/health.py next to each detector.
    "slo_droop_pct": (60.0, float),        # rate below (100-x)% of peak
    "slo_droop_floor_eps": (2.0, float),   # min peak (events/s) to judge
    "slo_droop_window_ticks": (8, int),    # smoothing window for rates
    "slo_stall_pct": (95.0, float),        # consumer batch-wait share
    "slo_creep_mb_per_min": (512.0, float),  # ledger/RSS growth slope
    "slo_queue_depth": (100000.0, float),  # per-queue item saturation
    "slo_lease_churn_per_min": (3.0, float),
    "slo_straggler_drift_x": (4.0, float),  # straggler vs rolling median
    # Delivery-latency plane (runtime/latency.py): windowed p99 of the
    # end-to-end birth->delivered hop above which delivery_latency_breach
    # fires, and the effective freshness age (newest payload's birth age
    # at the consumer's final hop, PLUS how long that gauge has been
    # frozen) above which freshness_stall fires.
    "slo_delivery_p99_s": (30.0, float),
    "slo_freshness_s": (120.0, float),
    # Incident capsules (runtime/health.py): where capsule directories
    # land ("" = trace_dir, else telemetry_dump_dir, else temp dir), how
    # long the profiler burst samples, and how long capture waits for
    # sibling processes to land their signal-driven trace dumps.
    "incident_dir": ("", str),
    "incident_profile_s": (0.25, float),
    "incident_wait_s": (2.0, float),
    # Per-round bench flight capsules (bench.py + runtime/regress.py):
    # after the phases finish (outside every timed window) bench.py
    # captures an incident-layout capsule beside the record —
    # RSDL_BENCH_CAPSULE=0 restores pre-capsule bench behavior exactly.
    # Capture dir "" = the record's directory (cwd).
    "bench_capsule": (True, _parse_bool),
    "bench_capsule_dir": ("", str),
    # Cross-process queue service (multiqueue_service.py) socket hygiene:
    # recv timeout applied to BOTH serve_queue connections and
    # RemoteQueue dials (0 = no timeout — a deliberate infinite wait;
    # with protocol v2 a timed-out response is reconnected-and-replayed,
    # never lost), and TCP_NODELAY on both ends.
    "queue_timeout_s": (300.0, float),
    "queue_nodelay": (True, _parse_bool),
    # Per-queue replay-buffer byte budget: unacked frames held for
    # reconnect replay. At the budget the server stops popping new items
    # (backpressure) rather than dropping unacked data.
    "queue_replay_bytes": (256 << 20, int),
    # Consumer lease: seconds without a heartbeat/request before a
    # consumer is declared dead. Client heartbeats run at a third of it.
    "queue_lease_timeout_s": (30.0, float),
    # Weighted-fair tenancy (tenancy/fairshare.py): the DRR replenish
    # quantum (each round hands a tenant quantum*weight bytes of pop
    # credit) and the activity window after which an idle tenant's
    # share redistributes to the rest (work conservation).
    "tenant_drr_quantum_bytes": (1 << 20, int),
    "tenant_active_window_s": (1.0, float),
    # Pace of the one-frame-per-GET liveness floor while the scheduler
    # is denying a tenant: the denied GET is delayed this long before
    # its floor frame pops. Without it a fast-RTT consumer's floor
    # alone out-runs the DRR grants and the weights shape nothing.
    # 0 disables pacing (floor at raw round-trip rate).
    "tenant_floor_pace_s": (0.002, float),
    # Serving-plane table delivery (multiqueue_service v3): "auto"
    # (consumers on a loopback address offer shm-handle delivery and the
    # server sends segment handles instead of streaming table bytes;
    # cross-host consumers stream), "handle" (offer handles regardless
    # of address — containers sharing a shm mount), "stream" (always
    # stream bytes; the v2 wire exactly).
    "queue_delivery": ("auto", str),
    # Frame compression for STREAMED table payloads (handle frames are
    # ~100 bytes and never compressed): "off" | "zlib" | "zstd" | "lz4".
    # zstd/lz4 degrade to zlib with a warning when the codec module is
    # not installed. CRC is computed pre-compression, so corruption
    # detection and NACK/replay semantics are unchanged.
    "queue_compression": ("off", str),
    # Streamed payloads below this size skip compression (header + CPU
    # overhead dwarfs the saving on small frames).
    "queue_compression_min_bytes": (4096, int),
    # Serving-plane shard count consulted by the serve helpers when the
    # caller does not pass one explicitly (1 = the pre-PR-10 topology).
    "queue_shards": (1, int),
    # What the server does when a consumer's lease expires
    # (RSDL_QUEUE_ON_DEAD_CONSUMER): "fail_fast" (down the server so the
    # pipeline fails loudly), "drain" (free the dead rank's queues so
    # producers are unblocked and memory is released), "redistribute"
    # (reroute its undelivered tables to a surviving consumer).
    "on_dead_consumer": ("fail_fast", str),
    # Executor data-plane backend (executor.py / procpool.py): "thread"
    # (GIL-releasing thread pool, the historical default), "process"
    # (supervised worker subprocesses with shared-memory Arrow handoff),
    # or "auto" (process when the host has >1 core, a writable shared-
    # memory dir, and the workload's transforms are picklable; thread
    # otherwise). shuffle() consults this only when it owns the pool.
    "executor_backend": ("auto", str),
    # Worker count for the pool (0 = one per host CPU).
    "executor_workers": (0, int),
    # Where process-backend shm segments live ("" = /dev/shm when
    # writable, else the system temp dir — which silently degrades
    # zero-copy to page-cache-backed mmap, still correct).
    "executor_shm_dir": ("", str),
    # Byte budget for decoded-table segments cached across epochs in the
    # process backend's shm arena (0 = half the free bytes of the shm
    # filesystem at pool creation).
    "executor_shm_bytes": (0, int),
    # Map-stage partition plan: "fused" (one native kernel emits
    # partition indices straight from a counter-based splitmix64 stream;
    # bit-identical NumPy fallback) or "philox" (legacy two-stage
    # numpy Philox draw + counting sort). Both are deterministic in
    # (seed, epoch, file); the streams differ, so flipping this knob
    # mid-checkpoint changes the shuffle order.
    "partition_plan": ("fused", str),
    # Streaming map pipeline (RSDL_SHUFFLE_FUSED_PIPELINE): fuse
    # decode->partition->gather at the map stage — Parquet record batches
    # scatter straight into per-reducer output buffers, no intermediate
    # decoded-table materialization. "auto"/True enable it wherever it
    # preserves the caching and bit-identity contracts (cache-less reads,
    # primitive null-free columns, elementwise transforms); False forces
    # the legacy read-then-plan path everywhere. The partition stream is
    # the SAME (seed, epoch, file) splitmix64 stream either way, so
    # flipping this knob never changes the shuffle order.
    "shuffle_fused_pipeline": ("auto", _parse_tristate),
    # CRC backend for every checksummed path (wire frames, spill files,
    # shm segments, watermark journals): "auto" (native kernel when the
    # library is loaded), "native", "zlib". Output is zlib.crc32-
    # compatible in all cases — recorded checksums survive backend flips.
    "crc_backend": ("auto", str),
    # Scatter-gather wire sends (RSDL_QUEUE_SENDMSG): coalesce a GET
    # response's batch header + per-frame headers + payloads into one
    # sendmsg() syscall instead of one sendall() per piece. Wire bytes
    # are identical; only the syscall count changes.
    "queue_sendmsg": (True, _parse_bool),
    # Codec pool for RSDL_QUEUE_COMPRESSION: compression runs on this
    # many background threads so the serving thread never stalls on
    # codec work (0 = compress inline on the serving thread).
    "queue_codec_threads": (1, int),
    # Double-buffered device staging (jax_dataset.py): convert batch N+1
    # on a staging thread while batch N's host->device transfer is in
    # flight. Delivery order is unchanged (single staging lane, FIFO).
    "device_double_buffer": (True, _parse_bool),
    # Epoch-plan scheduler (plan/scheduler.py). Speculative re-execution
    # of stragglers: off by default (duplicate attempts are bit-identical
    # by the lineage contract, but they absorb injected chaos faults and
    # burn idle capacity, so racing them is an explicit operator choice —
    # RSDL_PLAN_SPECULATION=1). A backup launches when a running task
    # exceeds max(plan_speculation_min_s, multiplier x rolling per-stage
    # median) and an idle lane exists; first completion wins.
    "plan_speculation": (False, _parse_bool),
    "plan_speculation_multiplier": (4.0, float),
    "plan_speculation_min_s": (1.0, float),
    # Straggler-check cadence of the plan driver thread (only paid while
    # speculation is on; off, the driver blocks on completion events).
    "plan_speculation_check_s": (0.05, float),
    # Work-stealing placement: an idle lane pulls ready nodes from the
    # longest sibling queue instead of waiting on its static round-robin
    # assignment. On by default (outputs are placement-independent).
    "plan_stealing": (True, _parse_bool),
    # What shuffle_map does with a corrupt/unreadable input file after
    # read retries are exhausted: "raise" (fail the map task; lineage
    # recovery then retries it, and only exhausted recovery poisons the
    # run) or "skip" (quarantine the file into a structured
    # QuarantinedFile report and shuffle the remaining files).
    "on_bad_file": ("raise", str),
    # Storage plane (storage/): which StorageSource dataset reads resolve
    # to when nothing is installed programmatically — "local" (direct
    # filesystem/fsspec reads, the historical behavior), "sim" (the
    # hermetic SimulatedObjectStore over local files, for tests and the
    # 1-CPU bench's remote leg).
    "storage_backend": ("local", str),
    # Plan-driven cache warming: when the active file cache exposes a
    # prefetcher, the plan scheduler issues prefetch tasks on idle lanes
    # (below steal/speculation priority, canceled when real work lands).
    "storage_prefetch": (True, _parse_bool),
    # SimulatedObjectStore shape (RSDL_STORAGE_SIM_*): first-byte latency
    # (ms), sustained bandwidth (MB/s), multiplicative jitter (+/- pct,
    # seeded), transient error rate (fraction of fetches raising OSError
    # — absorbed by the storage RetryPolicy), and the draw seed. All
    # draws are a pure function of (seed, path, attempt-count), so a
    # fixed seed reproduces byte-identical timing/error sequences.
    "storage_sim_first_byte_ms": (2.0, float),
    "storage_sim_mb_per_s": (512.0, float),
    "storage_sim_jitter_pct": (10.0, float),
    "storage_sim_error_rate": (0.0, float),
    "storage_sim_seed": (0, int),
    # Cache-thrash detector (runtime/health.py): fires when the tiered
    # cache's eviction rate exceeds this many evictions/min while its
    # hit rate sits below slo_cache_hit_pct — the signature of a disk
    # tier smaller than the working set re-fetching every epoch.
    "slo_cache_evictions_per_min": (120.0, float),
    "slo_cache_hit_pct": (10.0, float),
    # Streaming windows (streaming/window.py, RSDL_STREAM_WINDOW_*): a
    # window seals at the FIRST bound hit — admitted file count, admitted
    # payload bytes, or stream-time age since the window's first event
    # (the watermark bound). 0 disables a bound (file count falls back
    # to 1 if every bound is disabled: a window must be closable). Late
    # arrivals — events whose stream timestamp precedes the journaled
    # ingest watermark — follow window_late_policy: "admit" rolls them
    # into the NEXT window (bounded disorder, nothing lost), "quarantine"
    # excludes them into a structured report (the on_bad_file idiom).
    "window_max_files": (4, int),
    "window_max_bytes": (0, int),
    "window_max_wait_s": (0.0, float),
    "window_late_policy": ("admit", str),
    # Elastic membership (membership/): the failure detector's probe
    # cadence (RSDL_MEMBER_HEARTBEAT_S — heartbeats ride every data
    # frame too, the prober only covers idle links), the silence after
    # which a quiet rank is declared down (RSDL_MEMBER_SUSPECT_S), and
    # the phi-style suspicion threshold (elapsed silence measured in
    # smoothed inter-arrival units; crossing it marks the rank SUSPECT
    # before the hard suspect_s deadline downs it). Hysteresis: a rank
    # that flaps (suspect -> alive -> suspect inside one suspect_s
    # window) re-arms silently — one flapping link emits one
    # member_suspect, not a storm.
    "member_heartbeat_s": (0.5, float),
    "member_suspect_s": (3.0, float),
    "member_phi": (8.0, float),
    # watermark_lag detector (runtime/health.py): how far the serve
    # watermark (stream time fully drained to trainers) may trail the
    # ingest watermark (stream time sealed into closed windows) before
    # the stream is declared stale — the streaming analog of
    # slo_freshness_s, measured in seconds of stream time.
    "slo_watermark_lag_s": (300.0, float),
    # Self-healing rebalancer (rebalance/, RSDL_REBALANCE_*): the
    # per-tenant delivery-p99 SLO above which the tenant_delivery_slo
    # detector declares a sustained breach (the trigger for a journaled
    # placement decision), the cooldown after a committed move before
    # the controller will consider another (lets the post-move p99
    # window drain so one hot tenant does not ping-pong between
    # shards), and the max committed moves per decision window.
    "rebalance_slo_p99_s": (30.0, float),
    "rebalance_cooldown_s": (60.0, float),
    "rebalance_max_moves": (1, int),
}

_lock = threading.Lock()
#: component -> {key -> default} registered by subsystems at import time.
_component_defaults: Dict[str, Dict[str, Any]] = {}


def register_defaults(component: str, **defaults: Any) -> None:
    """Override library defaults for one component (kwargs surface for
    embedding applications; env vars still win over these)."""
    for key in defaults:
        if key not in _KEYS:
            raise ValueError(f"unknown policy key {key!r} "
                             f"(known: {sorted(_KEYS)})")
    with _lock:
        _component_defaults.setdefault(component, {}).update(defaults)


def _env_raw(component: str, key: str) -> Optional[str]:
    for name in (f"RSDL_{component.upper()}_{key.upper()}",
                 f"RSDL_{key.upper()}"):
        raw = os.environ.get(name)
        if raw is not None and raw.strip() != "":
            return raw
    return None


def resolve(component: str, key: str, override: Any = None,
            default: Any = None) -> Any:
    """Resolve one policy key for a component (see module docstring for
    the precedence order). ``override`` is the explicit-kwarg rung;
    ``None`` means "not given". ``default`` replaces the LIBRARY default
    (the lowest rung) — for call sites whose baseline lives in a module
    constant that must stay patchable at runtime."""
    if key not in _KEYS:
        raise ValueError(f"unknown policy key {key!r} "
                         f"(known: {sorted(_KEYS)})")
    library_default, parser = _KEYS[key]
    if override is not None:
        return parser(override) if isinstance(override, str) else override
    raw = _env_raw(component, key)
    if raw is not None:
        return parser(raw)
    with _lock:
        component_default = _component_defaults.get(component, {})
        if key in component_default:
            return component_default[key]
    return library_default if default is None else default


def resolve_all(component: str, **overrides: Any) -> Dict[str, Any]:
    """Resolve every key for a component; ``overrides`` are explicit
    kwargs (unknown keys raise, so typos fail loudly)."""
    unknown = set(overrides) - set(_KEYS)
    if unknown:
        raise ValueError(f"unknown policy keys: {sorted(unknown)} "
                         f"(known: {sorted(_KEYS)})")
    return {key: resolve(component, key, overrides.get(key))
            for key in _KEYS}


def describe(component: str = "library") -> Dict[str, Any]:
    """Resolved snapshot for diagnostics (bench JSON, bug reports)."""
    return resolve_all(component)
