"""Event-driven release channel over the native buffer ledger.

Plasma frees an object when its last ref-count drops and can wake a
blocked producer at that instant (reference: shuffle.py:131-132 leans on
exactly that). Our ledger decrefs fire from ``weakref.finalize`` when a
table's Python wrapper is collected — but the epoch-launch budget wait
used to OBSERVE those decrefs only by polling, with a periodic
process-wide ``gc.collect()`` to flush wrappers stuck in reference
cycles. That cadence cost up to ~1 s of launch latency per release and
a full-heap cycle collection per second under sustained pressure.

This module replaces the cadence with an explicit channel: the ledger
wrappers (``native/__init__.py``) call :func:`notify_release` whenever
an entry's bytes are returned (last decref, free-list trim), and budget
waiters block in :func:`wait_while` — woken immediately by the release,
re-checking their predicate, with only a coarse heartbeat as a safety
net against release paths that bypass the ledger. The cycles that made
``gc.collect()`` necessary are broken at their sources instead (the
shuffle driver drops drained refs before waiting; the JAX binding
unlinks its wrapper<->generator loop) — see the PR that introduced
``runtime/``.

Stdlib-only; importable from the native layer without cycles.
"""

from __future__ import annotations

import threading
import time
from typing import Callable

# RLock, deliberately: notify_release is reached from weakref.finalize
# callbacks, which the cycle collector may run at ANY allocation — even
# one made inside notify_release by the thread already holding this
# lock. A plain Lock would self-deadlock there; re-entry just bumps the
# counter again.
_cond = threading.Condition(threading.RLock())
#: Monotonic count of release events since import. Waiters snapshot it,
#: then block until it advances — no release is ever missed, even one
#: that fires between the predicate check and the wait.
_seq = 0


def notify_release(count: int = 1) -> None:
    """Record that ledger bytes were released and wake all waiters.

    Called by the buffer-ledger wrappers on every last-ref decref and
    free-list trim. Cheap (one lock round-trip per freed TABLE, not per
    byte) and safe from any thread, including weakref finalizers.
    """
    global _seq
    with _cond:
        _seq += count
        _cond.notify_all()


def release_seq() -> int:
    """Current value of the release counter (snapshot for waiters)."""
    with _cond:
        return _seq


def wait_for_release(last_seen: int, timeout: float) -> int:
    """Block until the release counter advances past ``last_seen`` or
    ``timeout`` elapses; returns the counter's current value."""
    deadline = time.monotonic() + timeout
    with _cond:
        while _seq == last_seen:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            _cond.wait(timeout=remaining)
        return _seq


def wait_while(predicate: Callable[[], bool], timeout_s: float,
               heartbeat_s: float = 0.25) -> bool:
    """Block while ``predicate()`` is True, re-evaluating on every
    release event (and at least every ``heartbeat_s`` as a safety net).

    Returns True if the predicate turned False within ``timeout_s``,
    False on timeout. This is the epoch-launch budget wait's engine: a
    consumer dropping its last reference to a table wakes the blocked
    launch within the notify round-trip (~sub-millisecond), not at the
    next poll tick.
    """
    deadline = time.monotonic() + timeout_s
    seen = release_seq()
    while predicate():
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            return not predicate()
        seen = wait_for_release(seen, timeout=min(heartbeat_s, remaining))
    return True
