"""End-to-end delivery-latency plane: birth stamps + cross-pid clock math.

The stats/telemetry stack can time every STAGE (map, reduce, fetch,
transfer) but nothing follows one frame of data across the pipeline, so
the question ROADMAP's QoS and autoscaler items hinge on — *how old is
a batch by the time it reaches the device, and what is the p99 across
every consumer?* — was unanswerable. This module is the shared
vocabulary of that answer:

**Birth stamps.** A :class:`Stamp` is ``(pid, t_mono, t_unix)`` taken
where a table is produced (the reducer output, stamped as ``rsdl.birth``
schema metadata next to the ``rsdl.trace`` lineage key in shuffle.py).
It carries BOTH clocks on purpose:

- ``t_mono`` (``CLOCK_MONOTONIC``) is system-wide per boot on Linux, so
  any reader *on the same host* — including a different process, and
  including a process started after the producer died — computes an
  exact, skew-free latency as ``now_mono - t_mono``. This is the
  topology the repo ships (the trace.py "same-host alignment" contract).
- ``t_unix`` is the cross-host fallback. Wall clocks skew, so a raw
  wall delta can be negative or wildly wrong; :class:`ClockAnchors`
  re-anchors it **per producer pid** the way ``trace.merge_dumps``
  anchors per-pid dumps: the most-negative wall delta ever observed
  from a pid bounds that pid's clock skew (true delivery latency is
  >= 0 by causality), and later readings subtract that floor — so a
  consumer never reports a negative or skew-polluted latency.

**Hops.** The plane measures four spans, each a fixed ``hop`` label on
the ``rsdl_delivery_latency_seconds`` sketch (runtime/metrics.py
:class:`~ray_shuffling_data_loader_tpu.runtime.metrics.Sketch` —
fixed-centroid, exact under cross-pid federation summing):

========================  ==================================================
``birth_to_queued``       reducer output born -> queue-server frame built
                          (observed server-side, per serving shard process)
``queued_to_delivered``   frame built -> consumer decoded it off the wire
``birth_to_delivered``    end-to-end producer -> consumer (the headline
                          ``delivery_p99_ms``)
``birth_to_device``       producer -> device-transfer complete (the
                          freshness span, ``freshness_p99_ms``)
``delivered_to_device``   consumer received the table -> device-transfer
                          complete (convert + transfer backlog)
========================  ==================================================

The ``queue`` label is the **trainer rank** (bounded — never the raw
``epoch * num_trainers + rank`` queue id, never a seq: the
``metric-label-cardinality`` lint rule pins this), so per-queue p99s
stay a fixed-cardinality family across arbitrarily long runs.

Stdlib-only (the runtime/ contract): importable before pyarrow/jax and
loadable standalone by the tools.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, NamedTuple, Optional, Tuple

from ray_shuffling_data_loader_tpu.runtime import metrics as rt_metrics

__all__ = [
    "Stamp", "now_stamp", "encode_stamp", "parse_stamp", "ClockAnchors",
    "HOP_BIRTH_TO_QUEUED", "HOP_QUEUED_TO_DELIVERED",
    "HOP_BIRTH_TO_DELIVERED", "HOP_BIRTH_TO_DEVICE",
    "HOP_DELIVERED_TO_DEVICE", "HOPS", "DELIVERY_METRIC",
    "FRESHNESS_METRIC", "observe_hop", "set_freshness", "LatencyProbe",
]

HOP_BIRTH_TO_QUEUED = "birth_to_queued"
HOP_QUEUED_TO_DELIVERED = "queued_to_delivered"
HOP_BIRTH_TO_DELIVERED = "birth_to_delivered"
HOP_BIRTH_TO_DEVICE = "birth_to_device"
HOP_DELIVERED_TO_DEVICE = "delivered_to_device"
HOPS: Tuple[str, ...] = (
    HOP_BIRTH_TO_QUEUED, HOP_QUEUED_TO_DELIVERED, HOP_BIRTH_TO_DELIVERED,
    HOP_BIRTH_TO_DEVICE, HOP_DELIVERED_TO_DEVICE)

DELIVERY_METRIC = "rsdl_delivery_latency_seconds"
FRESHNESS_METRIC = "rsdl_delivery_freshness_seconds"

#: Mono deltas outside [0, this] are treated as cross-boot/cross-host
#: (different CLOCK_MONOTONIC epochs compare as garbage) and the wall
#: fallback takes over. Generous: no frame legitimately ages 6h.
MONO_PLAUSIBLE_HORIZON_S = 6 * 3600.0
#: A mono delta may read a hair negative when two processes race the
#: same clock tick; treat within this of zero as zero, not cross-host.
_MONO_EPS_S = 0.005

#: ``rsdl.birth`` schema-metadata key (next to ``rsdl.trace``).
BIRTH_META_KEY = b"rsdl.birth"


class Stamp(NamedTuple):
    """One birth/queued timestamp: producing pid + both clocks."""

    pid: int
    t_mono: float
    t_unix: float


def now_stamp() -> Stamp:
    # Wall + mono sampled together form this stamp's clock anchor — the
    # pairing is the point, not an interval: rsdl-lint: disable=wallclock-interval
    return Stamp(os.getpid(), time.monotonic(), time.time())


def encode_stamp(stamp: Stamp) -> bytes:
    """``b"pid:mono:unix"`` for Arrow schema metadata (survives slicing,
    IPC, spill files and the queue wire, like ``rsdl.trace``)."""
    return f"{stamp.pid}:{stamp.t_mono!r}:{stamp.t_unix!r}".encode()


def parse_stamp(raw) -> Optional[Stamp]:
    """Inverse of :func:`encode_stamp`; None for absent/corrupt input
    (observability parsing must never raise into the data path)."""
    if not raw:
        return None
    try:
        if isinstance(raw, (bytes, bytearray, memoryview)):
            raw = bytes(raw).decode()
        pid_txt, mono_txt, unix_txt = str(raw).split(":")
        return Stamp(int(pid_txt), float(mono_txt), float(unix_txt))
    except (ValueError, TypeError):
        return None


class ClockAnchors:
    """Per-producer-pid latency math that can never go negative.

    Same host (the shipped topology): ``CLOCK_MONOTONIC`` is one
    boot-wide clock shared by every process, so ``now_mono - t_mono``
    is exact whatever the wall clock does — a stepped/skewed wall clock
    cannot touch it (the skewed-anchor regression test pins this).

    Cross host / cross boot: the mono delta is garbage (different
    epochs), detected by implausibility (negative beyond jitter, or
    past :data:`MONO_PLAUSIBLE_HORIZON_S`). The wall delta is then the
    only signal, and it carries the constant inter-host skew. The
    re-anchor, per producer pid: delivery latency is non-negative by
    causality, so the minimum wall delta ever observed from that pid is
    an upper bound on its (negative) skew — track it as the pid's
    anchor floor and subtract it, clamping at zero. A pid whose clock
    runs AHEAD of ours therefore reports 0 on its fastest-ever frame
    and honest relative latencies after, instead of negatives.
    """

    def __init__(self):
        self._lock = threading.Lock()
        #: pid -> most-negative wall delta seen (only kept when < 0).
        self._wall_floor: Dict[int, float] = {}

    def latency_s(self, stamp: Optional[Stamp],
                  now_mono: Optional[float] = None,
                  now_unix: Optional[float] = None) -> Optional[float]:
        """Seconds since ``stamp``, re-anchored; None for no stamp."""
        if stamp is None:
            return None
        if now_mono is None:
            now_mono = time.monotonic()
        if now_unix is None:
            # Paired with now_mono above — a two-clock sample, not an
            # interval: rsdl-lint: disable=wallclock-interval
            now_unix = time.time()
        lat_mono = now_mono - stamp.t_mono
        if -_MONO_EPS_S <= lat_mono <= MONO_PLAUSIBLE_HORIZON_S:
            return max(0.0, lat_mono)
        # Cross-host fallback: wall delta is the ONLY available signal
        # once mono epochs differ, and the per-pid floor below is the
        # skew correction this rule exists to demand.
        # rsdl-lint: disable=wallclock-interval
        lat_wall = now_unix - stamp.t_unix
        with self._lock:
            floor = self._wall_floor.get(stamp.pid, 0.0)
            if lat_wall < floor:
                floor = self._wall_floor[stamp.pid] = lat_wall
        return max(0.0, lat_wall - min(0.0, floor))


def observe_hop(hop: str, queue: str, latency_s: Optional[float]) -> None:
    """One sketch observation on the delivery-latency plane; None is a
    no-op so call sites never guard the stamp-parsing result."""
    if latency_s is None:
        return
    rt_metrics.sketch(
        DELIVERY_METRIC,
        "frame delivery latency per hop (queue label = trainer rank)",
        hop=hop, queue=queue).observe(latency_s)


def set_freshness(queue: str, age_s: Optional[float]) -> None:
    """Refresh a queue's freshness gauge: the birth age of the NEWEST
    payload that completed the consumer's final hop. The freshness_stall
    detector adds the gauge's own staleness on top, so a pipeline that
    stops delivering is caught even though the gauge stops moving."""
    if age_s is None:
        return
    rt_metrics.gauge(
        FRESHNESS_METRIC,
        "birth age of the newest payload at the consumer's last hop",
        queue=queue).set(age_s)


class LatencyProbe:
    """Consumer-side probe closing the loop at the device boundary.

    One per consuming dataset (``queue`` = its trainer rank). The table
    path calls :meth:`table_arrived` where a raw reducer table lands
    (parsing its ``rsdl.birth`` metadata once); the transfer path calls
    :meth:`device_done` when a device transfer completes — observing
    ``delivered_to_device`` and ``birth_to_device`` and refreshing the
    freshness gauge. Bulk paths transfer multi-batch spans of one table,
    so the probe's granularity is per-table — exactly the granularity
    the birth stamp has.
    """

    __slots__ = ("queue", "anchors", "_birth", "_arrived_mono",
                 "observe_delivered")

    def __init__(self, queue: str, anchors: Optional[ClockAnchors] = None,
                 observe_delivered: bool = False):
        self.queue = str(queue)
        self.anchors = anchors or ClockAnchors()
        self._birth: Optional[Stamp] = None
        self._arrived_mono: Optional[float] = None
        #: Also observe ``birth_to_delivered`` at arrival — for sources
        #: (in-process queues) where no wire client observed it already.
        self.observe_delivered = observe_delivered

    def table_arrived(self, table) -> None:
        meta = getattr(getattr(table, "schema", None), "metadata", None)
        self._birth = parse_stamp(meta.get(BIRTH_META_KEY)) if meta else None
        self._arrived_mono = time.monotonic()
        if self.observe_delivered and self._birth is not None:
            observe_hop(HOP_BIRTH_TO_DELIVERED, self.queue,
                        self.anchors.latency_s(self._birth))

    def device_done(self) -> None:
        now = time.monotonic()
        if self._arrived_mono is not None:
            observe_hop(HOP_DELIVERED_TO_DEVICE, self.queue,
                        max(0.0, now - self._arrived_mono))
        if self._birth is not None:
            age = self.anchors.latency_s(self._birth, now_mono=now)
            observe_hop(HOP_BIRTH_TO_DEVICE, self.queue, age)
            set_freshness(self.queue, age)
