"""Differential regression forensics between two bench rounds.

``tools/rsdl_bench_diff.py`` says *that* a number moved between two
``BENCH_r*.json`` records; this module answers *why*, from the evidence
each round already recorded about itself. A round's **flight capsule**
(``bench.py`` writes one beside the record, same layout as the
``runtime/health.py`` incident capsules) carries merged trace dumps,
the federated metric exposition, a bounded history slice, and the
resolved policy + ``RSDL_*`` environment. Given two rounds this module:

- aligns the rounds' pipeline stages by ``(kind, epoch-normalized
  rank)`` (``trace.stage_table`` — per-epoch critical-path ms, so a
  3-epoch round diffs against a 5-epoch round without bias);
- diffs per-stage latency **distributions** using the existing
  mergeable histogram buckets / sketch centroids
  (``metrics.distribution_masses``): the report carries the mean shift
  AND a bucket-overlap significance score, so a real shape change is
  distinguishable from a mean nudged by one outlier;
- diffs the two **critical paths** ("convert entered the critical
  path; reduce self-time +340 ms/epoch");
- diffs resolved **policy/env/config** ("RSDL_TENANT_FLOOR_PACE_S
  appeared");
- ranks **suspects** by what-if attribution: a stage's score is the
  share of the epoch-time increase its critical-path delta explains,
  cross-referenced with the current round's 2x-speedup what-if.

Records without capsules degrade LOUDLY to a record-only numeric diff
(the pre-r11 trajectory stays comparable, it just cannot name stages).
Provenance stamped in the records (``git_rev`` / ``tree_dirty`` / host
fingerprint) is cross-checked first: a dirty tree or a cross-host pair
gets a warning before any number is believed — the r09->r10 case this
plane was built on was exactly a host-capability change masquerading
as a code regression.

Stdlib-only AND standalone on purpose: ``tools/rsdl_regress.py`` loads
this file by path on hosts without numpy/pyarrow/jax (the rsdl_top
pattern); sibling runtime modules are loaded the same way when the
package import fails.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Any, Dict, List, Optional, Tuple


def _load_sibling(stem: str):
    import importlib.util
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        f"{stem}.py")
    spec = importlib.util.spec_from_file_location(f"_rsdl_regress_{stem}",
                                                  path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


try:
    from ray_shuffling_data_loader_tpu.runtime import trace as rt_trace
    from ray_shuffling_data_loader_tpu.runtime import metrics as rt_metrics
    from ray_shuffling_data_loader_tpu.runtime import history as rt_history
except ImportError:  # stripped host: load siblings by path
    rt_trace = _load_sibling("trace")
    rt_metrics = _load_sibling("metrics")
    rt_history = _load_sibling("history")

SCHEMA = "rsdl-regress-v1"

#: A distribution diff is *significant* when the bucket-overlap
#: coefficient drops below this AND both rounds observed at least
#: :data:`MIN_SIGNIFICANT_COUNT` samples — overlap near 1.0 means the
#: two rounds drew from the same shape (noise), near 0.0 means the mass
#: moved buckets (a real shift).
SIGNIFICANT_OVERLAP = 0.75
MIN_SIGNIFICANT_COUNT = 8

#: Record keys that are identities/config, not measurements — excluded
#: from the numeric record diff (they change by design between rounds).
_RECORD_DIFF_SKIP = frozenset({
    "host_cpus", "executor_workers", "train_batch_size",
    "train_microbatch", "train_flops_per_row", "n",
})

#: Provenance fields whose mismatch makes two rounds non-comparable as
#: a *code* regression (the machine changed under the benchmark).
_HOST_FINGERPRINT_FIELDS = ("host", "cpu_model", "host_cpus", "cpu_mhz")


# ---------------------------------------------------------------------------
# Loading: records, capsule discovery, capsule contents
# ---------------------------------------------------------------------------


def load_record(path: str) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """``(wrapper, record)`` from a raw bench JSON line or the committed
    ``BENCH_r*`` wrapper; for raw records the wrapper IS the record."""
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if isinstance(data, dict) and isinstance(data.get("parsed"), dict):
        return data, data["parsed"]
    if not isinstance(data, dict) or "value" not in data:
        raise ValueError(f"{path}: not a bench record "
                         "(no 'value' and no 'parsed' wrapper)")
    return data, data


def find_capsule(record_path: str,
                 record: Dict[str, Any]) -> Optional[str]:
    """The round's flight-capsule directory, or None.

    Resolution order: the record's ``capsule`` reference (absolute, or
    relative to the record's directory), then the sibling-directory
    convention ``<record-stem>.capsule/`` — the latter keeps the
    reference alive after a committed wrapper renames the capsule to
    match its round number. A directory only counts with a readable
    ``capsule.json`` manifest (the manifest is written LAST, so its
    presence means the capsule is complete)."""
    base_dir = os.path.dirname(os.path.abspath(record_path))
    candidates = []
    ref = record.get("capsule")
    if isinstance(ref, str) and ref:
        candidates.append(ref if os.path.isabs(ref)
                          else os.path.join(base_dir, ref))
    stem = os.path.basename(record_path)
    if stem.endswith(".json"):
        stem = stem[:-len(".json")]
    candidates.append(os.path.join(base_dir, f"{stem}.capsule"))
    for cand in candidates:
        if os.path.isfile(os.path.join(cand, "capsule.json")):
            return cand
    return None


def load_capsule(capsule_dir: str,
                 whatif_speedup: float = 2.0) -> Dict[str, Any]:
    """One capsule directory -> the in-memory evidence the differ
    consumes: ``{path, manifest, policy, env, analysis, stage_table,
    masses, means, history_snapshots}``. Every section is best-effort
    (a capsule missing its history is still worth a trace diff)."""
    out: Dict[str, Any] = {
        "path": capsule_dir, "manifest": None, "policy": {}, "env": {},
        "analysis": None, "stage_table": {}, "masses": {}, "means": {},
        "history_snapshots": 0,
    }
    with open(os.path.join(capsule_dir, "capsule.json"),
              encoding="utf-8") as f:
        out["manifest"] = json.load(f)
    policy_path = os.path.join(capsule_dir, "policy.json")
    if os.path.isfile(policy_path):
        with open(policy_path, encoding="utf-8") as f:
            data = json.load(f)
        out["policy"] = data.get("policy", {})
        out["env"] = data.get("env", {})
    dumps = sorted(glob.glob(os.path.join(capsule_dir, "traces",
                                          "*.jsonl")))
    if dumps:
        merged = rt_trace.merge_dumps(dumps)
        if merged["events"]:
            analysis = rt_trace.analyze(merged["events"],
                                        whatif_speedup=whatif_speedup)
            out["analysis"] = analysis
            out["stage_table"] = rt_trace.stage_table(analysis)
    prom_path = os.path.join(capsule_dir, "metrics.prom")
    if os.path.isfile(prom_path):
        with open(prom_path, encoding="utf-8") as f:
            text = f.read()
        samples, types = rt_metrics.parse_exposition_typed(text)
        out["masses"], out["means"] = _distribution_views(samples, types)
    hist_path = os.path.join(capsule_dir, "history.json")
    if os.path.isfile(hist_path):
        with open(hist_path, encoding="utf-8") as f:
            data = json.load(f)
        out["history_snapshots"] = len(data.get("snapshots", []))
    return out


def _distribution_views(samples: Dict[str, Dict[Any, float]],
                        types: Dict[str, str]
                        ) -> Tuple[Dict[Any, Dict[float, float]],
                                   Dict[Any, Tuple[float, int]]]:
    """``(masses, means)`` over every histogram/sketch family in one
    parsed exposition, keyed by ``(family, group_labels)``. Means come
    from the family's ``_sum``/``_count`` series (histograms) or the
    centroid-weighted mass (sketches)."""
    masses: Dict[Any, Dict[float, float]] = {}
    means: Dict[Any, Tuple[float, int]] = {}
    for family, kind in sorted(types.items()):
        if kind not in ("histogram", "sketch"):
            continue
        for group, bucket in rt_metrics.distribution_masses(
                samples, family, kind).items():
            key = (family, group)
            masses[key] = bucket
            if kind == "sketch":
                total = sum(bucket.values())
                mean = (sum(c * n for c, n in bucket.items()) / total
                        if total > 0 else 0.0)
                means[key] = (mean, int(total))
            else:
                sums = samples.get(f"{family}_sum", {})
                counts = samples.get(f"{family}_count", {})
                count = counts.get(group, 0.0)
                means[key] = ((sums.get(group, 0.0) / count
                               if count > 0 else 0.0), int(count))
    return masses, means


# ---------------------------------------------------------------------------
# Differential pieces
# ---------------------------------------------------------------------------


def diff_record_metrics(base: Dict[str, Any], cur: Dict[str, Any],
                        min_delta_pct: float = 2.0
                        ) -> List[Dict[str, Any]]:
    """Relative deltas of every numeric key the rounds share, largest
    movers first — the record-only fallback evidence and the headline
    the capsule evidence must explain."""
    out: List[Dict[str, Any]] = []
    for key in sorted(set(base) & set(cur)):
        if key in _RECORD_DIFF_SKIP:
            continue
        b, c = base.get(key), cur.get(key)
        if isinstance(b, bool) or isinstance(c, bool):
            continue
        if not isinstance(b, (int, float)) or \
                not isinstance(c, (int, float)):
            continue
        if b == 0:
            continue
        delta_pct = 100.0 * (c - b) / abs(b)
        if abs(delta_pct) < min_delta_pct:
            continue
        out.append({"key": key, "base": b, "cur": c,
                    "delta_pct": round(delta_pct, 2)})
    out.sort(key=lambda d: -abs(d["delta_pct"]))
    return out


#: Policy/env keys whose values are per-run scratch paths (bench pins a
#: fresh trace tmpdir for every capsuled round, incident capsules get
#: pid-stamped dirs): they differ on EVERY pair by construction, so
#: diffing them would bury real knob changes under permanent noise.
_VOLATILE_KNOBS = frozenset({
    "trace_dir", "RSDL_TRACE_DIR",
    "incident_dir", "RSDL_INCIDENT_DIR",
    "bench_capsule_dir", "RSDL_BENCH_CAPSULE_DIR",
    "RSDL_TELEMETRY_DUMP_DIR",
})


def diff_policy(base: Dict[str, Any],
                cur: Dict[str, Any]) -> Dict[str, Any]:
    """Appeared / disappeared / changed keys between two flat dicts
    (resolved policy, or the ``RSDL_*`` environment). Per-run scratch
    paths (:data:`_VOLATILE_KNOBS`) are excluded."""
    base = {k: v for k, v in base.items() if k not in _VOLATILE_KNOBS}
    cur = {k: v for k, v in cur.items() if k not in _VOLATILE_KNOBS}
    appeared = {k: cur[k] for k in sorted(set(cur) - set(base))}
    disappeared = {k: base[k] for k in sorted(set(base) - set(cur))}
    changed = {k: [base[k], cur[k]]
               for k in sorted(set(base) & set(cur))
               if base[k] != cur[k]}
    return {"appeared": appeared, "disappeared": disappeared,
            "changed": changed}


def diff_stage_tables(base: Dict[str, Dict[str, float]],
                      cur: Dict[str, Dict[str, float]]
                      ) -> List[Dict[str, Any]]:
    """Critical-path diff, per-epoch-normalized: one row per stage
    either round put on (or near) the path, flagged ``entered`` /
    ``left`` when the stage is on the path in only one round."""
    rows: List[Dict[str, Any]] = []
    for stage in sorted(set(base) | set(cur)):
        b = base.get(stage, {})
        c = cur.get(stage, {})
        b_ms = b.get("cp_ms_per_epoch", 0.0)
        c_ms = c.get("cp_ms_per_epoch", 0.0)
        rows.append({
            "stage": stage,
            "base_cp_ms_per_epoch": round(b_ms, 3),
            "cur_cp_ms_per_epoch": round(c_ms, 3),
            "delta_ms_per_epoch": round(c_ms - b_ms, 3),
            "base_pct": b.get("pct", 0.0),
            "cur_pct": c.get("pct", 0.0),
            "entered": b_ms <= 0.0 < c_ms,
            "left": c_ms <= 0.0 < b_ms,
        })
    rows.sort(key=lambda r: -abs(r["delta_ms_per_epoch"]))
    return rows


def bucket_overlap(base_masses: Dict[float, float],
                   cur_masses: Dict[float, float]) -> Optional[float]:
    """Overlap coefficient of two bucket-mass distributions over their
    shared edges: ``sum(min(p_i, q_i))`` of the count-normalized
    masses, 1.0 = identical shape, 0.0 = disjoint. None when the edge
    vocabularies share fewer than two buckets (nothing comparable —
    bucket layouts drifted between rounds)."""
    edges = sorted(set(base_masses) & set(cur_masses))
    if len(edges) < 2:
        return None
    b_total = sum(base_masses[e] for e in edges)
    c_total = sum(cur_masses[e] for e in edges)
    if b_total <= 0 or c_total <= 0:
        return None
    return sum(min(base_masses[e] / b_total, cur_masses[e] / c_total)
               for e in edges)


def diff_distributions(base_cap: Dict[str, Any],
                       cur_cap: Dict[str, Any]
                       ) -> List[Dict[str, Any]]:
    """Shift + significance per shared distribution family/group:
    ``{family, labels, base_mean, cur_mean, shift_pct, overlap,
    significance, significant, base_count, cur_count}``, most
    significant first."""
    rows: List[Dict[str, Any]] = []
    shared = set(base_cap["masses"]) & set(cur_cap["masses"])
    for key in sorted(shared, key=repr):
        family, group = key
        overlap = bucket_overlap(base_cap["masses"][key],
                                 cur_cap["masses"][key])
        if overlap is None:
            continue
        b_mean, b_count = base_cap["means"].get(key, (0.0, 0))
        c_mean, c_count = cur_cap["means"].get(key, (0.0, 0))
        shift_pct = (100.0 * (c_mean - b_mean) / b_mean
                     if b_mean > 0 else 0.0)
        significance = round(1.0 - overlap, 4)
        rows.append({
            "family": family,
            "labels": dict(group),
            "base_mean": round(b_mean, 6),
            "cur_mean": round(c_mean, 6),
            "shift_pct": round(shift_pct, 2),
            "overlap": round(overlap, 4),
            "significance": significance,
            "significant": (overlap < SIGNIFICANT_OVERLAP
                            and min(b_count, c_count)
                            >= MIN_SIGNIFICANT_COUNT),
            "base_count": b_count,
            "cur_count": c_count,
        })
    rows.sort(key=lambda r: -r["significance"])
    return rows


# ---------------------------------------------------------------------------
# Provenance comparability
# ---------------------------------------------------------------------------


def provenance_warnings(base_rec: Dict[str, Any],
                        cur_rec: Dict[str, Any],
                        include_missing: bool = True) -> List[str]:
    """Why these two rounds may not be comparable, before any delta is
    believed: missing provenance, dirty trees, host-fingerprint
    mismatches. The r09->r10 'regression' was a host change nothing in
    the records could falsify — these warnings are that falsifier.
    ``include_missing=False`` keeps only the hard mismatches (dirty /
    cross-host) for callers that routinely see pre-provenance rounds
    (the bench-diff gate over the committed trajectory)."""
    warnings: List[str] = []
    base_p = base_rec.get("provenance")
    cur_p = cur_rec.get("provenance")
    for name, prov in (("baseline", base_p), ("current", cur_p)):
        if not isinstance(prov, dict):
            if include_missing:
                warnings.append(
                    f"{name} record has no provenance (pre-r11 round): "
                    "host/commit comparability is unverifiable")
        elif prov.get("tree_dirty"):
            warnings.append(
                f"{name} record was measured on a DIRTY tree "
                f"(git_rev {prov.get('git_rev', '?')[:12]} + uncommitted "
                "changes): the measured code is not the committed code")
    if isinstance(base_p, dict) and isinstance(cur_p, dict):
        mismatched = [
            f for f in _HOST_FINGERPRINT_FIELDS
            if base_p.get(f) is not None and cur_p.get(f) is not None
            and base_p.get(f) != cur_p.get(f)
        ]
        if mismatched:
            detail = ", ".join(
                f"{f}: {base_p.get(f)!r} -> {cur_p.get(f)!r}"
                for f in mismatched)
            warnings.append(
                f"CROSS-HOST comparison ({detail}): throughput deltas "
                "reflect the machine as much as the code")
    return warnings


# ---------------------------------------------------------------------------
# Suspect ranking
# ---------------------------------------------------------------------------


def rank_suspects(report: Dict[str, Any],
                  max_suspects: int = 8) -> List[Dict[str, Any]]:
    """Rank what most plausibly explains the delta, best first.

    Stage suspects score by what-if attribution: the share of the
    baseline per-epoch time the stage's critical-path delta added
    (a stage that added 20% of an epoch outranks one that added 2%),
    boosted when a latency distribution it owns shifted significantly.
    Policy/env changes score a flat nudge each — a changed knob is
    always worth a look but never outranks hard trace evidence unless
    the traces are silent. Record-only mode falls back to the largest
    regressing record metrics."""
    suspects: List[Dict[str, Any]] = []
    base_wall = (report.get("base", {}).get("wall_ms_per_epoch")
                 or 0.0)
    sig_by_stage: Dict[str, float] = {}
    for row in report.get("distribution_diff", []):
        stage = row["labels"].get("stage") or row["labels"].get("kind")
        if stage and row["significant"] and row["shift_pct"] > 0:
            sig_by_stage[stage] = max(sig_by_stage.get(stage, 0.0),
                                      row["significance"])
    for row in report.get("critical_path_diff", []):
        delta = row["delta_ms_per_epoch"]
        if delta <= 0:
            continue
        score = (100.0 * delta / base_wall if base_wall > 0
                 else row["cur_pct"])
        boost = sig_by_stage.get(row["stage"], 0.0)
        score *= (1.0 + boost)
        what = "entered the critical path" if row["entered"] else \
            (f"+{delta:.1f} ms/epoch on the critical path "
             f"({row['base_cp_ms_per_epoch']:.1f} -> "
             f"{row['cur_cp_ms_per_epoch']:.1f})")
        evidence = what + (
            f"; latency distribution shifted (significance {boost:.2f})"
            if boost else "")
        suspects.append({"kind": "stage", "name": row["stage"],
                         "score": round(score, 2),
                         "evidence": evidence})
    for section, label in (("policy_diff", "policy"),
                           ("env_diff", "env")):
        diff = report.get(section) or {}
        for key, value in diff.get("appeared", {}).items():
            suspects.append({
                "kind": label, "name": key, "score": 15.0,
                "evidence": f"{key} appeared (= {value!r})"})
        for key, value in diff.get("disappeared", {}).items():
            suspects.append({
                "kind": label, "name": key, "score": 15.0,
                "evidence": f"{key} disappeared (was {value!r})"})
        for key, (old, new) in sorted(
                (k, tuple(v)) for k, v in
                diff.get("changed", {}).items()):
            suspects.append({
                "kind": label, "name": key, "score": 12.0,
                "evidence": f"{key} changed: {old!r} -> {new!r}"})
    for row in report.get("distribution_diff", []):
        if not row["significant"] or row["shift_pct"] <= 0:
            continue
        name = row["family"] + (
            "{" + ",".join(f"{k}={v}" for k, v in
                           sorted(row["labels"].items())) + "}"
            if row["labels"] else "")
        suspects.append({
            "kind": "distribution", "name": name,
            "score": round(10.0 * row["significance"], 2),
            "evidence": (f"mean {row['base_mean']:.6g} -> "
                         f"{row['cur_mean']:.6g} "
                         f"({row['shift_pct']:+.1f}%), bucket overlap "
                         f"{row['overlap']:.2f}")})
    if not suspects:
        for row in report.get("record_diff", [])[:max_suspects]:
            suspects.append({
                "kind": "metric", "name": row["key"],
                "score": round(abs(row["delta_pct"]) / 10.0, 2),
                "evidence": (f"{row['base']:g} -> {row['cur']:g} "
                             f"({row['delta_pct']:+.1f}%)")})
    suspects.sort(key=lambda s: -s["score"])
    for rank, s in enumerate(suspects[:max_suspects], start=1):
        s["rank"] = rank
    return suspects[:max_suspects]


# ---------------------------------------------------------------------------
# Top-level diff
# ---------------------------------------------------------------------------


def _round_summary(path: str, record: Dict[str, Any],
                   capsule: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    analysis = (capsule or {}).get("analysis")
    n_epochs = max(1, len((analysis or {}).get("epochs") or []))
    return {
        "path": path,
        "provenance": record.get("provenance"),
        "capsule": (capsule or {}).get("path"),
        "epochs_traced": (len(analysis["epochs"]) if analysis else 0),
        "wall_ms_per_epoch": (round(analysis["wall_ms"] / n_epochs, 3)
                              if analysis else None),
        "history_snapshots": (capsule or {}).get("history_snapshots", 0),
    }


def diff_rounds(base_path: str, cur_path: str,
                whatif_speedup: float = 2.0,
                max_suspects: int = 8) -> Dict[str, Any]:
    """The full differential report between two bench record paths.

    Capsule-bearing pairs get the stage/distribution/policy diff;
    anything less degrades loudly to record-only mode. Always returns a
    report (missing evidence is a ``warnings`` entry, never an
    exception) — callers gate on ``report["suspects"]``."""
    _, base_rec = load_record(base_path)
    _, cur_rec = load_record(cur_path)
    warnings = provenance_warnings(base_rec, cur_rec)

    base_dir = find_capsule(base_path, base_rec)
    cur_dir = find_capsule(cur_path, cur_rec)
    base_cap = cur_cap = None
    for name, cap_dir, setter in (("baseline", base_dir, "base"),
                                  ("current", cur_dir, "cur")):
        if cap_dir is None:
            warnings.append(
                f"{name} record has NO flight capsule: stage-level "
                "attribution unavailable, degrading to record-only "
                "diff")
            continue
        try:
            cap = load_capsule(cap_dir, whatif_speedup=whatif_speedup)
        except (OSError, ValueError) as e:
            warnings.append(f"{name} capsule unreadable ({e}): "
                            "degrading to record-only diff")
            continue
        if setter == "base":
            base_cap = cap
        else:
            cur_cap = cap

    mode = "capsule" if (base_cap is not None and cur_cap is not None) \
        else "record-only"
    report: Dict[str, Any] = {
        "schema": SCHEMA,
        "mode": mode,
        "base": _round_summary(base_path, base_rec, base_cap),
        "cur": _round_summary(cur_path, cur_rec, cur_cap),
        "warnings": warnings,
        "record_diff": diff_record_metrics(base_rec, cur_rec),
        "policy_diff": None,
        "env_diff": None,
        "critical_path_diff": [],
        "distribution_diff": [],
    }
    if mode == "capsule":
        report["policy_diff"] = diff_policy(base_cap["policy"],
                                            cur_cap["policy"])
        report["env_diff"] = diff_policy(base_cap["env"], cur_cap["env"])
        report["critical_path_diff"] = diff_stage_tables(
            base_cap["stage_table"], cur_cap["stage_table"])
        report["distribution_diff"] = diff_distributions(base_cap,
                                                         cur_cap)
        whatif = ((cur_cap.get("analysis") or {}).get("whatif")) or {}
        report["whatif_cur"] = whatif
    report["suspects"] = rank_suspects(report, max_suspects=max_suspects)
    return report


def render_report(report: Dict[str, Any]) -> List[str]:
    """Human-readable report lines (the CLI and the bench-diff forensic
    footer both print these)."""
    lines: List[str] = []
    lines.append(f"regress: {report['base']['path']} -> "
                 f"{report['cur']['path']} [{report['mode']} mode]")
    for warning in report["warnings"]:
        lines.append(f"  WARNING {warning}")
    for row in report["record_diff"][:10]:
        lines.append(f"  record  {row['key']:<30} {row['base']:g} -> "
                     f"{row['cur']:g} ({row['delta_pct']:+.1f}%)")
    for row in report["critical_path_diff"]:
        if row["delta_ms_per_epoch"] == 0 and not (row["entered"]
                                                   or row["left"]):
            continue
        marker = (" ENTERED" if row["entered"]
                  else " LEFT" if row["left"] else "")
        lines.append(
            f"  path    {row['stage']:<30} "
            f"{row['base_cp_ms_per_epoch']:.1f} -> "
            f"{row['cur_cp_ms_per_epoch']:.1f} ms/epoch "
            f"({row['delta_ms_per_epoch']:+.1f}){marker}")
    for row in report["distribution_diff"]:
        if not row["significant"]:
            continue
        labels = ",".join(f"{k}={v}"
                          for k, v in sorted(row["labels"].items()))
        lines.append(
            f"  dist    {row['family']}{{{labels}}} mean "
            f"{row['base_mean']:.6g} -> {row['cur_mean']:.6g} "
            f"({row['shift_pct']:+.1f}%), overlap {row['overlap']:.2f}")
    for section in ("policy_diff", "env_diff"):
        diff = report.get(section) or {}
        for verb in ("appeared", "disappeared"):
            for key, value in diff.get(verb, {}).items():
                lines.append(f"  {section.split('_')[0]:<7} {key} "
                             f"{verb} ({value!r})")
        for key, pair in diff.get("changed", {}).items():
            lines.append(f"  {section.split('_')[0]:<7} {key} changed: "
                         f"{pair[0]!r} -> {pair[1]!r}")
    if report["suspects"]:
        lines.append("  suspects (most likely first):")
        for s in report["suspects"]:
            lines.append(f"    #{s['rank']} [{s['kind']}] "
                         f"{s['name']} (score {s['score']:g}) — "
                         f"{s['evidence']}")
    else:
        lines.append("  no suspects: rounds are indistinguishable at "
                     "this evidence level")
    return lines


# ---------------------------------------------------------------------------
# Self-test (tools/rsdl_regress.py --check, wired into format.sh)
# ---------------------------------------------------------------------------


def _synthetic_events(reduce_s: float,
                      n_epochs: int = 2) -> List[Dict[str, Any]]:
    """A deterministic two-stage pipeline: per epoch, map_read then
    reduce then train_step back-to-back; ``reduce_s`` is the planted
    dial the self-test turns between 'rounds'."""
    events = []
    t = 1.0
    for epoch in range(n_epochs):
        for kind, dur, task in (("map_read", 0.10, 0),
                                ("reduce", reduce_s, 0),
                                ("train_step", 0.10, None)):
            t += dur
            events.append({"kind": kind, "epoch": epoch, "task": task,
                           "t_mono": t, "dur_s": dur})
        t += 0.01
    return events


def _synthetic_exposition(reduce_scale: float) -> str:
    """A minimal round exposition: one histogram family with the reduce
    group's mass planted ``reduce_scale`` buckets to the right."""
    edges = [0.1, 0.2, 0.4, 0.8]
    counts = {"map_read": [30, 2, 0, 0]}
    if reduce_scale <= 1.0:
        counts["reduce"] = [4, 24, 4, 0]
    else:
        counts["reduce"] = [0, 4, 24, 4]
    lines = ["# TYPE rsdl_stage_latency_seconds histogram"]
    for stage, masses in sorted(counts.items()):
        cumulative = 0
        total_mass = 0.0
        for edge, n in zip(edges, masses):
            cumulative += n
            lines.append(
                f'rsdl_stage_latency_seconds_bucket{{le="{edge}",'
                f'stage="{stage}"}} {cumulative}')
            total_mass += n * edge
        lines.append(
            f'rsdl_stage_latency_seconds_bucket{{le="+Inf",'
            f'stage="{stage}"}} {cumulative}')
        lines.append(
            f'rsdl_stage_latency_seconds_sum{{stage="{stage}"}} '
            f'{total_mass}')
        lines.append(
            f'rsdl_stage_latency_seconds_count{{stage="{stage}"}} '
            f'{cumulative}')
    return "\n".join(lines) + "\n"


def _synthetic_capsule(reduce_s: float, env: Dict[str, str]
                       ) -> Dict[str, Any]:
    analysis = rt_trace.analyze(_synthetic_events(reduce_s))
    samples, types = rt_metrics.parse_exposition_typed(
        _synthetic_exposition(1.0 if reduce_s <= 0.15 else 3.0))
    masses, means = _distribution_views(samples, types)
    return {
        "path": "<synthetic>", "manifest": {"schema": "rsdl-incident-v1"},
        "policy": {"queue_maxsize": 4}, "env": env,
        "analysis": analysis,
        "stage_table": rt_trace.stage_table(analysis),
        "masses": masses, "means": means, "history_snapshots": 0,
    }


def self_check() -> Tuple[bool, List[str]]:
    """Synthesize two rounds with a planted suspect (reduce 3x slower,
    one env knob appeared), run the full differential, and require the
    top suspect to name the plant. Returns ``(ok, report_lines)`` —
    the format.sh informational block prints the lines either way."""
    base_cap = _synthetic_capsule(0.10, {})
    cur_cap = _synthetic_capsule(0.30, {"RSDL_PLANTED_KNOB": "1"})
    report: Dict[str, Any] = {
        "schema": SCHEMA, "mode": "capsule",
        "base": {"path": "<base>", "provenance": None,
                 "capsule": "<synthetic>", "epochs_traced": 2,
                 "wall_ms_per_epoch": round(
                     base_cap["analysis"]["wall_ms"] / 2, 3),
                 "history_snapshots": 0},
        "cur": {"path": "<cur>", "provenance": None,
                "capsule": "<synthetic>", "epochs_traced": 2,
                "wall_ms_per_epoch": round(
                    cur_cap["analysis"]["wall_ms"] / 2, 3),
                "history_snapshots": 0},
        "warnings": [],
        "record_diff": diff_record_metrics(
            {"value": 1000.0}, {"value": 640.0}),
        "policy_diff": diff_policy(base_cap["policy"],
                                   cur_cap["policy"]),
        "env_diff": diff_policy(base_cap["env"], cur_cap["env"]),
        "critical_path_diff": diff_stage_tables(
            base_cap["stage_table"], cur_cap["stage_table"]),
        "distribution_diff": diff_distributions(base_cap, cur_cap),
    }
    report["suspects"] = rank_suspects(report)
    lines = render_report(report)
    ok = bool(report["suspects"]) \
        and report["suspects"][0]["kind"] == "stage" \
        and report["suspects"][0]["name"] == "reduce" \
        and any(s["kind"] == "env" and s["name"] == "RSDL_PLANTED_KNOB"
                for s in report["suspects"]) \
        and any(r["significant"] and r["labels"].get("stage") == "reduce"
                for r in report["distribution_diff"]) \
        and not any(r["significant"]
                    and r["labels"].get("stage") == "map_read"
                    for r in report["distribution_diff"])
    return ok, lines
