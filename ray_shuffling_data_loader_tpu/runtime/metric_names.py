"""THE metric-name catalog: every ``rsdl_*`` registry name, in one place.

Dashboards, the run report (tools/rsdl_report.py), rsdl_top, the history
ring and the health detectors all address metrics BY NAME across process
and repo boundaries — a renamed or ad-hoc metric silently breaks every
one of them without failing a single test. This module pins the
vocabulary: every literal name passed to ``metrics.counter`` / ``gauge``
/ ``histogram`` / ``get`` in library code must appear here (the
``unregistered-metric`` rsdl-lint rule enforces it mechanically), so a
new metric is a reviewed one-line catalog change, not drift.

Keys map name -> (kind, label keys) — documentation the exposition
already carries at runtime, kept here for humans and the lint rule.
Stdlib-only, import-free (loadable by tools without the package).
"""

from __future__ import annotations

from typing import Dict, Tuple

#: name -> (kind, labels). Histogram names implicitly expose their
#: ``_bucket`` / ``_sum`` / ``_count`` series in the text format.
METRIC_NAMES: Dict[str, Tuple[str, Tuple[str, ...]]] = {
    # -- telemetry spine (runtime/telemetry.py) --
    "rsdl_events_total": ("counter", ("kind",)),
    "rsdl_stage_seconds": ("histogram", ("stage",)),
    "rsdl_batch_wait_seconds": ("histogram", ()),
    "rsdl_trace_cp_seconds": ("gauge", ("stage",)),
    "rsdl_trace_straggler_task": ("gauge", ("stage",)),
    "rsdl_trace_straggler_seconds": ("gauge", ("stage",)),
    # -- watchdog / stats (stats.py) --
    "rsdl_watchdog_events_total": ("counter", ()),
    "rsdl_watchdog_escalations_total": ("counter", ()),
    "rsdl_watchdog_fallbacks_total": ("counter", ()),
    "rsdl_watchdog_stalls_total": ("counter", ("name",)),
    # -- fault injection / recovery (stats.py) --
    "rsdl_faults_injected_total": ("counter", ()),
    "rsdl_faults_injected_by_site_total": ("counter", ("site",)),
    "rsdl_fault_retries_total": ("counter", ()),
    "rsdl_fault_recomputes_total": ("counter", ()),
    "rsdl_fault_quarantines_total": ("counter", ()),
    "rsdl_fault_exhausted_total": ("counter", ()),
    "rsdl_fault_recovery_seconds": ("histogram", ()),
    "rsdl_fault_recovery_max_seconds": ("gauge", ()),
    # -- executor data plane (executor.py / procpool.py) --
    "rsdl_executor_workers": ("gauge", ("pool",)),
    "rsdl_executor_tasks_total": ("counter", ("pool",)),
    "rsdl_executor_worker_up": ("gauge", ("pool", "pid")),
    "rsdl_pool_worker_restarts_total": ("counter", ("pool",)),
    "rsdl_worker_tasks_total": ("counter", ("worker",)),
    # -- epoch-plan scheduler (plan/scheduler.py) --
    "rsdl_plan_speculative_launched_total": ("counter", ("stage",)),
    "rsdl_plan_speculative_won_total": ("counter", ("stage",)),
    "rsdl_plan_speculative_wasted_total": ("counter", ("stage",)),
    "rsdl_plan_steals_total": ("counter", ("stage",)),
    # -- queue service (multiqueue.py / multiqueue_service.py) --
    "rsdl_queue_depth": ("gauge", ("queue",)),
    "rsdl_queue_frames_replayed_total": ("counter", ()),
    "rsdl_queue_frames_nacked_total": ("counter", ()),
    "rsdl_queue_frames_corrupt_total": ("counter", ()),
    "rsdl_queue_client_reconnects_total": ("counter", ()),
    "rsdl_queue_lease_expiries_total": ("counter", ()),
    "rsdl_queue_consumers_alive": ("gauge", ()),
    "rsdl_queue_server_restarts_total": ("counter", ()),
    # -- sharded serving plane (multiqueue_service v3, per-shard) --
    "rsdl_queue_payload_bytes_total": ("counter", ("shard",)),
    "rsdl_queue_bytes_on_wire_total": ("counter", ("shard",)),
    "rsdl_queue_handle_hits_total": ("counter", ("shard",)),
    "rsdl_queue_handle_misses_total": ("counter", ("shard",)),
    "rsdl_queue_compression_saved_bytes_total": ("counter", ("shard",)),
    "rsdl_queue_shard_depth": ("gauge", ("shard",)),
    "rsdl_queue_serve_shards": ("gauge", ()),
    # -- delivery-latency plane (runtime/latency.py; queue label is the
    #    TRAINER RANK — bounded — never a raw queue id/seq/pid; the
    #    metric-label-cardinality lint rule enforces the label sets
    #    declared here) --
    "rsdl_delivery_latency_seconds": ("sketch", ("hop", "queue")),
    "rsdl_delivery_freshness_seconds": ("gauge", ("queue",)),
    # -- tenancy plane (tenancy/: per-tenant QoS over the queue,
    #    storage and admission planes; the tenant label is the bounded
    #    configured-tenant vocabulary, validated by
    #    tenancy.validate_tenant_id) --
    "rsdl_tenant_bytes_delivered_total": ("counter", ("tenant",)),
    "rsdl_tenant_replay_bytes": ("gauge", ("tenant",)),
    "rsdl_tenant_budget_bytes": ("gauge", ("tenant",)),
    "rsdl_tenant_delivery_latency_seconds": ("sketch", ("hop", "tenant")),
    "rsdl_tenant_storage_hits_total": ("counter", ("tenant",)),
    "rsdl_tenant_storage_misses_total": ("counter", ("tenant",)),
    "rsdl_tenant_storage_evictions_total": ("counter", ("tenant",)),
    "rsdl_tenant_cache_bytes": ("gauge", ("tenant",)),
    "rsdl_tenant_cache_quota_bytes": ("gauge", ("tenant",)),
    "rsdl_tenant_prefetch_throttled_total": ("counter", ("tenant",)),
    "rsdl_admission_decisions_total": ("counter", ("action",)),
    "rsdl_admission_waiting": ("gauge", ()),
    "rsdl_admission_used_bytes": ("gauge", ()),
    # -- elastic membership (membership/ + parallel/transport.py): view
    #    lifecycle, failure-detector verdicts, and the generation fence --
    "rsdl_member_view_id": ("gauge", ()),
    "rsdl_member_live": ("gauge", ()),
    "rsdl_member_suspect": ("gauge", ()),
    "rsdl_member_incarnation": ("gauge", ("rank",)),
    "rsdl_member_heartbeats_total": ("counter", ()),
    "rsdl_member_suspects_total": ("counter", ()),
    "rsdl_member_flaps_total": ("counter", ()),
    "rsdl_member_downs_total": ("counter", ()),
    "rsdl_member_joins_total": ("counter", ()),
    "rsdl_member_transitions_total": ("counter", ("kind",)),
    "rsdl_member_fenced_frames_total": ("counter", ()),
    "rsdl_member_last_transition_unixtime": ("gauge", ()),
    # -- rebalance plane (rebalance/ + the serving-plane actuator in
    #    multiqueue_service.py): journaled placement decisions, the
    #    placement-generation fence, and move accounting --
    "rsdl_rebalance_generation": ("gauge", ()),
    "rsdl_rebalance_overrides": ("gauge", ()),
    "rsdl_rebalance_decisions_total": ("counter", ("kind",)),
    "rsdl_rebalance_moves_total": ("counter", ()),
    "rsdl_rebalance_last_move_unixtime": ("gauge", ()),
    "rsdl_rebalance_fenced_frames_total": ("counter", ()),
    # -- spill tier (spill.py) --
    "rsdl_spills_total": ("counter", ()),
    "rsdl_spilled_bytes_total": ("counter", ()),
    # -- storage plane (storage/: tiered cache + plan-driven prefetch;
    #    the tier label is the fixed {hot, disk, remote} vocabulary) --
    "rsdl_storage_hits_total": ("counter", ("tier",)),
    "rsdl_storage_misses_total": ("counter", ("tier",)),
    "rsdl_storage_evictions_total": ("counter", ("tier",)),
    "rsdl_storage_corrupt_total": ("counter", ("tier",)),
    "rsdl_storage_tier_bytes": ("gauge", ("tier",)),
    "rsdl_storage_remote_bytes_read_total": ("counter", ()),
    "rsdl_storage_prefetch_issued_total": ("counter", ()),
    "rsdl_storage_prefetch_hits_total": ("counter", ()),
    "rsdl_storage_prefetch_canceled_total": ("counter", ()),
    # -- streaming plane (streaming/: windowed shuffle over unbounded
    #    input; watermarks are STREAM time — the newest admitted event's
    #    timestamp — not wall clock) --
    "rsdl_stream_window": ("gauge", ()),
    "rsdl_stream_windows_closed_total": ("counter", ()),
    "rsdl_stream_events_admitted_total": ("counter", ()),
    "rsdl_stream_rows_ingested_total": ("counter", ()),
    "rsdl_stream_late_events_total": ("counter", ("policy",)),
    "rsdl_stream_ingest_watermark": ("gauge", ()),
    "rsdl_stream_serve_watermark": ("gauge", ()),
    "rsdl_stream_watermark_lag_seconds": ("gauge", ()),
    "rsdl_stream_window_close_seconds": ("histogram", ()),
    # -- ops plane: history / health / incidents (runtime/{history,health}) --
    "rsdl_process_rss_bytes": ("gauge", ()),
    "rsdl_ledger_bytes_in_use": ("gauge", ()),
    "rsdl_health_state": ("gauge", ("detector",)),
    "rsdl_health_breaches_total": ("counter", ("detector",)),
    "rsdl_incident_capsules_total": ("counter", ()),
    # -- federation (runtime/metrics.py merged view) --
    "rsdl_federated_processes": ("gauge", ()),
}

#: The lint rule's membership set.
NAMES = frozenset(METRIC_NAMES)
