"""Runtime health & flow control: watchdogs, release events, policy.

Three pillars, each importable on its own (all stdlib except the
watchdog's stats hookup):

- :mod:`.watchdog` — progress/deadline supervision for pipeline stages
  (heartbeat registration, escalating stall reports into
  ``stats.watchdog_stats()``); the bulk device-rebatch path uses it to
  detect a wedged ``device_put`` and auto-degrade to per-batch
  transfers instead of hanging.
- :mod:`.release` — an explicit release-event channel on the native
  buffer ledger (decref/trim -> condition notify) that replaced the
  ``gc.collect()`` polling cadence in the shuffle's epoch-launch
  budget wait.
- :mod:`.policy` — the degradation-policy registry (env-var + kwargs
  resolution) that turns bench-only mitigations like
  ``RSDL_BENCH_DEVICE_REBATCH=0`` into library defaults
  (``RSDL_DEVICE_REBATCH=0``) with per-component overrides.
- :mod:`.retry` — the ONE bounded/jittered :class:`RetryPolicy` every
  retry loop in the pipeline routes through (executor task retries,
  transport redial, remote-queue fetch, lineage recompute).
- :mod:`.faults` — seeded, deterministic fault injection
  (``RSDL_CHAOS_SPEC``) with named sites threaded through the hot
  paths, plus the :class:`QuarantinedFile` report vocabulary.
- :mod:`.telemetry` — the structured-event flight recorder (ring
  buffer, JSONL/SIGUSR1 dumps with named-thread stacks) and the online
  per-batch bottleneck attribution every stage reports through.
- :mod:`.metrics` — the typed counter/gauge/histogram registry with
  Prometheus text-format exposition (file + localhost HTTP).
- :mod:`.locksan` — the opt-in (``RSDL_LOCKSAN=1``) runtime lock
  sanitizer: wraps package-allocated locks to record the actual
  acquisition-order graph and held-while-blocking events, emitted as
  the JSON artifact that ``rsdl-lint --concurrency --locksan-graph``
  cross-checks against the static lock-order graph.
"""

from ray_shuffling_data_loader_tpu.runtime import (  # noqa: F401
    faults, locksan, metrics, policy, release, retry, telemetry, watchdog)
from ray_shuffling_data_loader_tpu.runtime.faults import (  # noqa: F401
    InjectedFault, QuarantinedFile)
from ray_shuffling_data_loader_tpu.runtime.retry import (  # noqa: F401
    RetryPolicy)
from ray_shuffling_data_loader_tpu.runtime.watchdog import (  # noqa: F401
    StallReport, Watchdog, get_watchdog)

__all__ = ["faults", "locksan", "metrics", "policy", "release", "retry",
           "telemetry", "watchdog", "InjectedFault", "QuarantinedFile",
           "RetryPolicy", "StallReport", "Watchdog", "get_watchdog"]
