"""Process supervision: restart a dead queue-server process.

The ROADMAP north star — production traffic on preemptible TPU slices —
makes process death the common case, not the edge case. PR 3 made the
pipeline survive *task* loss; this module makes the cross-process queue
topology survive the loss of the **queue-server process itself**: a
:class:`ProcessSupervisor` watches a child process, and when it dies
(kill -9, OOM, an injected ``queue_server_crash``) respawns it with
bounded, jittered backoff. The restarted server
(``multiqueue_service.serve_pipeline``) reloads the delivered-watermark
journal (``checkpoint.WatermarkJournal``), asks the epoch plan where to
resume (``plan.ir.resume_from_watermarks`` — the one home of the
journal-resume math) and re-runs the deterministic shuffle lineage for
the in-flight epoch, re-enqueueing only the undelivered remainder —
consumers reconnect (their RetryPolicy redial) and resume exactly where
their acks left off.

Stdlib-only on purpose (the runtime/ contract): importable before
jax/pyarrow; the child is spawned as
``python -m ray_shuffling_data_loader_tpu.multiqueue_service`` so this
module never imports the arrow-heavy service itself.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import tempfile
import threading
import time
from typing import Callable, Optional

from ray_shuffling_data_loader_tpu.runtime import metrics as rt_metrics
from ray_shuffling_data_loader_tpu.runtime import retry as rt_retry
from ray_shuffling_data_loader_tpu.runtime import telemetry as rt_telemetry
from ray_shuffling_data_loader_tpu.utils.logger import setup_custom_logger

logger = setup_custom_logger(__name__)

# Restart budget defaults, resolved via the shared retry keys
# (RSDL_SUPERVISOR_RETRY_*): deeper than a call retry — a preempted
# host may kill the server several times in one run — and with a wider
# backoff cap so a crash-looping child doesn't spin.
from ray_shuffling_data_loader_tpu.runtime import policy as rt_policy
rt_policy.register_defaults("supervisor", retry_max_attempts=6,
                            retry_initial_backoff_s=0.25,
                            retry_max_backoff_s=5.0)


def free_port(host: str = "127.0.0.1") -> int:
    """A currently-free TCP port. The supervised server must come back on
    the SAME address after a restart (consumers redial it), so the port
    is chosen once up front instead of letting the child bind port 0."""
    probe = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    try:
        probe.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        probe.bind((host, 0))
        return probe.getsockname()[1]
    finally:
        probe.close()


class ProcessSupervisor:
    """Keep one child process alive across crashes.

    ``spawn(restart_index)`` builds a fresh ``subprocess.Popen``; the
    monitor thread waits on the child and, unless :meth:`stop` was
    called, records the death (``rsdl_queue_server_restarts_total``, a
    ``queue_server_crash`` telemetry event — the plain twin of the fault
    site, so chaos and recovery join by kind), sleeps a decorrelated-
    jitter backoff, and respawns. The restart budget and backoff resolve
    through the shared retry policy keys (``RSDL_SUPERVISOR_RETRY_*``);
    an exhausted budget marks the supervisor ``failed`` and stops —
    permanent failure must surface, not flap forever.
    """

    def __init__(self, spawn: Callable[[int], subprocess.Popen],
                 name: str = "queue-server",
                 on_restart: Optional[Callable[[int], None]] = None):
        self._spawn = spawn
        self._name = name
        self._on_restart = on_restart
        policy = rt_retry.RetryPolicy.for_component("supervisor")
        self._max_restarts = policy.max_attempts
        self._backoffs = policy.backoffs()
        self._restarts_counter = rt_metrics.counter(
            "rsdl_queue_server_restarts_total",
            "supervised queue-server processes restarted after death")
        self._lock = threading.Lock()
        self._proc: Optional[subprocess.Popen] = None
        self._stopping = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        self.restarts = 0
        self.failed = False

    @property
    def proc(self) -> Optional[subprocess.Popen]:
        with self._lock:
            return self._proc

    @property
    def pid(self) -> Optional[int]:
        proc = self.proc
        return proc.pid if proc is not None else None

    def start(self) -> "ProcessSupervisor":
        with self._lock:
            self._proc = self._spawn(0)
        logger.info("%s: supervised child started (pid %d)", self._name,
                    self._proc.pid)
        self._monitor = threading.Thread(
            target=self._monitor_loop, daemon=True,
            name=f"rsdl-supervisor-{self._name}")
        self._monitor.start()
        return self

    def _monitor_loop(self) -> None:
        while not self._stopping.is_set():
            proc = self.proc
            if proc is None:
                return
            returncode = proc.wait()
            if self._stopping.is_set():
                return
            self.restarts += 1
            self._restarts_counter.inc()
            # Plain telemetry twin of the queue_server_crash fault site:
            # an injected crash (child-side) and the supervisor's
            # observation of it share the event kind by construction.
            rt_telemetry.record("queue_server_crash", rc=returncode,
                                restart=self.restarts)
            if self.restarts >= self._max_restarts:
                self.failed = True
                logger.error(
                    "%s: child died (rc=%s) and the restart budget "
                    "(%d) is exhausted; giving up", self._name,
                    returncode, self._max_restarts)
                return
            pause = next(self._backoffs)
            logger.error(
                "%s: child died (rc=%s); restart %d/%d in %.2fs",
                self._name, returncode, self.restarts,
                self._max_restarts - 1, pause)
            if self._stopping.wait(pause):
                return
            with self._lock:
                if self._stopping.is_set():
                    return
                self._proc = self._spawn(self.restarts)
            logger.info("%s: supervised child restarted (pid %d)",
                        self._name, self._proc.pid)
            if self._on_restart is not None:
                try:
                    self._on_restart(self.restarts)
                except Exception:  # noqa: BLE001 - supervision survives
                    logger.exception("%s: on_restart hook failed",
                                     self._name)

    def stop(self, kill_timeout_s: float = 5.0) -> None:
        """Stop supervising and terminate the child (terminate, then
        kill). Idempotent."""
        self._stopping.set()
        proc = self.proc
        if proc is not None and proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=kill_timeout_s)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=kill_timeout_s)
        if self._monitor is not None:
            self._monitor.join(timeout=kill_timeout_s)

    def __enter__(self) -> "ProcessSupervisor":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()


def launch_supervised_queue_server(config: dict,
                                   name: str = "queue-server"
                                   ) -> "tuple[ProcessSupervisor, tuple]":
    """Spawn a supervised queue-server process serving the pipeline
    described by ``config`` (see ``multiqueue_service.serve_pipeline``:
    filenames / num_epochs / num_trainers / num_reducers / seed /
    journal_path; ``port`` defaults to a fresh free port).

    Returns ``(supervisor, (host, port))`` — consumers dial the address
    with their normal connect retry; it stays valid across restarts.
    """
    config = dict(config)
    host = config.setdefault("host", "127.0.0.1")
    if not config.get("port"):
        config["port"] = free_port(host)
    child_env = config.pop("child_env", None) or {}
    config_dir = tempfile.mkdtemp(prefix="rsdl-qserver-")
    config_path = os.path.join(config_dir, "server.json")
    with open(config_path, "w") as f:
        json.dump(config, f)
    env = dict(os.environ)
    # The queue server shuffles on host CPU; it must never grab (or wait
    # on) an accelerator the trainer owns.
    env["JAX_PLATFORMS"] = "cpu"
    env.update(child_env)

    def spawn(restart_index: int) -> subprocess.Popen:
        # stdout carries the child's READY line; keep stderr attached so
        # server logs land in the driver's stream (the operator's view).
        return subprocess.Popen(
            [sys.executable, "-m",
             "ray_shuffling_data_loader_tpu.multiqueue_service",
             config_path],
            stdout=subprocess.DEVNULL, env=env)

    supervisor = ProcessSupervisor(spawn, name=name).start()
    return supervisor, (host, config["port"])


def launch_supervised_queue_shards(config: dict, num_shards: int,
                                   name: str = "queue-shard"):
    """The sharded serving plane as supervised OS processes: one
    :func:`launch_supervised_queue_server` child per shard, each
    serving the ranks ``plan.ir.shard_ranks`` assigns it, each with its
    OWN watermark journal (``checkpoint.shard_journal_path``) and its
    own restart budget — a ``kill -9`` of one shard recovers exactly
    like the single-server PR 5 matrix, while its siblings keep
    serving untouched.

    Returns ``(supervisors, shard_map)`` — ``shard_map`` is the
    :class:`plan.ir.ShardMap` consumers hand to
    ``multiqueue_service.ShardedRemoteQueue``.
    """
    # Deferred: plan/ir is stdlib-only but lives outside runtime/; the
    # supervisor stays importable without it on minimal tool images.
    from ray_shuffling_data_loader_tpu.plan import ir as plan_ir

    num_shards = max(1, int(num_shards))
    config = dict(config)
    host = config.setdefault("host", "127.0.0.1")
    journal_path = config["journal_path"]
    handle_root = config.pop("handle_dir", None)
    ports = [free_port(host) for _ in range(num_shards)]
    supervisors = []
    for shard in range(num_shards):
        shard_config = dict(
            config, port=ports[shard], shard_index=shard,
            num_shards=num_shards,
            journal_path=_shard_journal_path(journal_path, shard,
                                             num_shards))
        if handle_root:
            shard_config["handle_dir"] = os.path.join(handle_root,
                                                      f"s{shard}")
        supervisor, _ = launch_supervised_queue_server(
            shard_config, name=f"{name}-{shard}")
        supervisors.append(supervisor)
    shard_map = plan_ir.ShardMap(
        num_trainers=max(1, int(config["num_trainers"])),
        addresses=[(host, port) for port in ports])
    return supervisors, shard_map


def _shard_journal_path(path: str, shard_index: int,
                        num_shards: int) -> str:
    """Delegates to ``checkpoint.shard_journal_path`` lazily (checkpoint
    imports nothing heavy, but runtime/ must not import it at module
    scope)."""
    from ray_shuffling_data_loader_tpu import checkpoint as ckpt
    return ckpt.shard_journal_path(path, shard_index, num_shards)


def wait_for_server(address: "tuple[str, int]",
                    timeout_s: float = 30.0) -> bool:
    """Poll until something accepts on ``address`` (or time out)."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        probe = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        probe.settimeout(1.0)
        try:
            probe.connect(address)
            return True
        except OSError:
            # Deadline-bounded liveness probe of a LOCAL listener — no
            # shared recovering resource to herd, and the loop condition
            # is the budget: rsdl-lint: disable=unbounded-retry
            time.sleep(0.1)
        finally:
            probe.close()
    return False
