"""Device mesh construction helpers.

The reference's gradient plane is Horovod/NCCL allreduce and its control
plane is Ray GCS (SURVEY.md §2.4). TPU-native, both collapse into the XLA
device mesh: ``jax.sharding.Mesh`` over the slice's chips, gradients
synced by XLA collectives over ICI (inserted automatically under jit from
sharding annotations), multi-host coordination via
``jax.distributed.initialize``.

Axis convention used across the framework:
- ``"data"``  — batch-dim sharding (DP). One trainer rank per data-axis
  host group replaces the reference's Horovod ranks.
- ``"model"`` — tensor-parallel sharding of params (TP / column-parallel
  embeddings in models/).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh(num_devices: Optional[int] = None,
              model_parallel: int = 1,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a ("data", "model") mesh.

    ``model_parallel`` chips per model group; the rest is the data axis.
    With the default ``model_parallel=1`` this is pure DP — the
    configuration that matches the reference's Horovod example
    (reference: ray_torch_shuffle.py:161-177).
    """
    if devices is None:
        devices = jax.devices()
    if num_devices is not None:
        devices = devices[:num_devices]
    n = len(devices)
    if n % model_parallel != 0:
        raise ValueError(
            f"model_parallel={model_parallel} must divide device count {n}")
    grid = np.asarray(devices).reshape(n // model_parallel, model_parallel)
    return Mesh(grid, (DATA_AXIS, MODEL_AXIS))


def batch_sharding(mesh: Mesh, ndim: int = 2,
                   data_axis: str = DATA_AXIS) -> NamedSharding:
    """Leading-axis (batch) sharding for an ndim-rank array."""
    return NamedSharding(mesh, P(data_axis, *([None] * (ndim - 1))))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def local_data_shard_info():
    """(rank, world) for per-host loader sharding — the multi-host analog
    of the reference's (hvd.rank(), hvd.size()).

    One loader process runs per host (jax.distributed), each feeding all
    of its local chips, so trainer rank = process index and world =
    process count — independent of chips-per-host or mesh layout.
    """
    return jax.process_index(), jax.process_count()
