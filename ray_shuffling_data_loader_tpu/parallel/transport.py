"""Tagged TCP byte transport for cross-host shuffle traffic (the DCN plane).

The reference moves map->reduce chunks between nodes through Ray's plasma
object store and raylet-to-raylet object transfer (C++, external — SURVEY.md
§2.3, reference: shuffle.py:185-186). On a TPU slice that data plane is the
host network / DCN, and nothing external provides it, so this module is the
framework's own transport: one listener per host, persistent peer
connections, length-prefixed frames tagged ``(epoch, reducer, file_index)``,
and a blocking tag-matched receive. Payloads are raw bytes (the shuffle
sends Arrow IPC streams); ``socket.sendall``/``recv`` release the GIL so
large transfers overlap with map/reduce compute threads.

Wire format per message, all little-endian:

    magic   u32 = 0x5244534C ("RSDL")
    src     u32   sending host id
    epoch   u64
    reducer u64
    file    u64
    length  u64   payload byte count
    payload length bytes
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ray_shuffling_data_loader_tpu.runtime import faults as rt_faults
from ray_shuffling_data_loader_tpu.runtime import retry as rt_retry
from ray_shuffling_data_loader_tpu.runtime import telemetry as rt_telemetry
from ray_shuffling_data_loader_tpu.utils.logger import setup_custom_logger

logger = setup_custom_logger(__name__)

_MAGIC = 0x5244534C
_HEADER = struct.Struct("<IIQQQQ")

# Payloads at least this large move through the native C pump (one writev /
# one read loop per frame, a single GIL transition). Below it, Python's own
# C socket methods are just as fast and skip the wrapper overhead —
# measured on loopback: the pump costs ~35us/frame extra at 16KB frames
# and is break-even from ~1MB up.
_NATIVE_PUMP_MIN_BYTES = 1 << 20

Tag = Tuple[int, int, int]  # (epoch, reducer_index, file_index)


class TransportError(RuntimeError):
    pass


class TransportTimeout(TransportError):
    pass


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    """Read exactly n bytes or raise TransportError on EOF."""
    chunks = []
    remaining = n
    while remaining:
        chunk = sock.recv(min(remaining, 1 << 20))
        if not chunk:
            raise TransportError("peer closed connection mid-message")
        chunks.append(chunk)
        remaining -= len(chunk)
    return b"".join(chunks) if len(chunks) != 1 else chunks[0]


def _recv_payload(sock: socket.socket, n: int):
    """Read an n-byte payload into a pool-tracked buffer.

    The buffer comes from the native buffer pool (single copy off the
    socket — no ``b"".join`` concat pass) and its bytes stay charged to the
    pipeline ledger until every reference is gone — including zero-copy
    Arrow tables deserialized over it, which keep the returned array alive
    via ``pa.py_buffer``'s base reference.

    Payloads of at least ``_NATIVE_PUMP_MIN_BYTES`` arrive through the
    native C pump: one GIL-free read loop per frame instead of one
    ``recv_into`` hop (and GIL re-acquisition) per ~MB.
    """
    from ray_shuffling_data_loader_tpu import native
    buf = native.alloc_tracked_buffer(n)
    view = memoryview(buf)
    if native.available() and n >= _NATIVE_PUMP_MIN_BYTES:
        if not native.read_exact_into(sock.fileno(), buf, n):
            raise TransportError("peer closed connection mid-message")
        return view
    received = 0
    while received < n:
        got = sock.recv_into(view[received:], min(n - received, 1 << 20))
        if not got:
            raise TransportError("peer closed connection mid-message")
        received += got
    # memoryview: content-compares equal to bytes, supports the buffer
    # protocol for pa.BufferReader, and keeps `buf` (and its pool bytes)
    # alive exactly as long as anything references the payload.
    return view


class TcpTransport:
    """Point-to-point tagged message transport between shuffle hosts.

    Args:
        host_id: this host's index in ``addresses``.
        addresses: ``(hostname, port)`` per host, identical on every host.

    ``start()`` binds the listener; ``connect()`` dials every peer (call on
    all hosts after all have started — the dial retries with backoff to
    absorb startup skew, the same role as the reference's named-actor
    connect retry, reference: multiqueue.py:310-332).
    """

    def __init__(self, host_id: int, addresses: Sequence[Tuple[str, int]],
                 recv_timeout_s: float = 600.0,
                 reconnect_grace_s: float = 5.0):
        if not 0 <= host_id < len(addresses):
            raise ValueError(
                f"host_id {host_id} out of range for {len(addresses)} hosts")
        self.host_id = host_id
        self.addresses = list(addresses)
        self.world = len(addresses)
        self._recv_timeout_s = recv_timeout_s
        self._reconnect_grace_s = reconnect_grace_s
        # Values are bytes-like: pool-backed memoryviews (remote) or the
        # sender's payload object (self-sends).
        self._inbox: Dict[Tuple[int, Tag], Any] = {}
        self._inbox_cv = threading.Condition()
        # src host id -> (reason, death monotonic time). A src is revived
        # (entry dropped) when a message arrives on a NEW connection — a
        # sender that redials after a transient failure resumes seamlessly;
        # recv() only fails a dead src after reconnect_grace_s.
        self._dead_srcs: Dict[int, Tuple[str, float]] = {}
        self._peers: Dict[int, socket.socket] = {}
        self._peer_locks: Dict[int, threading.Lock] = {}
        self._listener: Optional[socket.socket] = None
        self._accept_threads: List[threading.Thread] = []
        self._closed = threading.Event()

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        """Bind the listener and start accepting peer connections."""
        host, port = self.addresses[self.host_id]
        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.bind((host, port))
        listener.listen(self.world)
        self._listener = listener
        thread = threading.Thread(target=self._accept_loop, daemon=True,
                                  name=f"rsdl-transport-accept-{self.host_id}")
        thread.start()
        self._accept_threads.append(thread)

    def bound_port(self) -> int:
        """The actual listening port (useful when configured with port 0)."""
        assert self._listener is not None, "start() first"
        return self._listener.getsockname()[1]

    def connect(self, retries: int = 30,
                initial_backoff_s: float = 0.1) -> None:
        """Dial every remote peer, retrying to absorb startup skew.

        The redial schedule is the shared ``RetryPolicy`` for the
        ``transport`` component: exponential backoff with decorrelated
        jitter (capped at 5s) — a whole slice's hosts dialing a
        late-arriving peer de-synchronize instead of re-dialing in
        lockstep at a fixed interval. The last underlying ``OSError`` is
        carried in the raised :class:`TransportError` message.
        """
        policy = rt_retry.RetryPolicy.for_component(
            "transport", retry_max_attempts=retries + 1,
            retry_initial_backoff_s=initial_backoff_s,
            retry_max_backoff_s=5.0,
            retryable=lambda e: isinstance(e, OSError))
        for peer in range(self.world):
            if peer == self.host_id:
                continue
            host, port = self.addresses[peer]

            def _dial(host=host, port=port, peer=peer):
                sock = socket.create_connection((host, port), timeout=30)
                # Drop the dial timeout: a timed-out sendall after a
                # partial write would corrupt the framed stream. Blocking
                # sends + the receiver-side recv timeout handle dead peers.
                sock.settimeout(None)
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                # connect() runs before any send/recv traffic exists
                # (single-threaded setup phase), so the per-peer send
                # locks it creates cannot yet have contenders (the
                # redial path's _peers write holds _peer_locks[dest];
                # this one predates every reader):
                # rsdl-lint: disable=lock-mutation,unguarded-shared-mutation
                self._peers[peer] = sock
                self._peer_locks[peer] = threading.Lock()

            try:
                policy.call(_dial, describe=f"dial peer {peer}")
            except OSError as e:
                raise TransportError(
                    f"host {self.host_id} could not reach peer {peer} at "
                    f"{host}:{port} after {retries + 1} attempts: "
                    f"{type(e).__name__}: {e}")
        logger.info("host %d connected to %d peers", self.host_id,
                    self.world - 1)

    def close(self) -> None:
        self._closed.set()
        with self._inbox_cv:
            self._inbox_cv.notify_all()
        for sock in self._peers.values():
            try:
                sock.close()
            except OSError:
                pass
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass

    def __enter__(self) -> "TcpTransport":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- receive path --------------------------------------------------------

    def _accept_loop(self) -> None:
        assert self._listener is not None
        while not self._closed.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return  # listener closed
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # Explicit infinite recv: idle links between epochs are
            # normal, and the recv loop's exit path is transport.close()
            # closing this conn (the recv() API's own timeout is enforced
            # tag-side). A silent default would be a bug; this is the
            # reviewed decision the socket-op-no-timeout rule asks for.
            conn.settimeout(None)
            thread = threading.Thread(target=self._recv_loop, args=(conn,),
                                      daemon=True,
                                      name=f"rsdl-transport-recv-{self.host_id}")
            thread.start()
            self._accept_threads.append(thread)

    def _recv_loop(self, conn: socket.socket) -> None:
        srcs_seen: set = set()
        try:
            while not self._closed.is_set():
                first = conn.recv(_HEADER.size)
                if not first:
                    return  # clean close at a message boundary
                header = (first if len(first) == _HEADER.size else
                          first + _recv_exact(conn,
                                              _HEADER.size - len(first)))
                magic, src, epoch, reducer, file_index, length = (
                    _HEADER.unpack(header))
                if magic != _MAGIC:
                    raise TransportError(
                        f"bad magic {magic:#x} from peer (protocol mismatch)")
                srcs_seen.add(src)
                payload = _recv_payload(conn, length)
                key = (src, (epoch, reducer, file_index))
                with self._inbox_cv:
                    if key in self._inbox:
                        # At-least-once delivery: a sender whose sendall
                        # errored after the frame was in fact delivered
                        # resends it on a fresh connection. Keep the first.
                        logger.warning(
                            "host %d: dropping duplicate message %s "
                            "(sender resend after reconnect)",
                            self.host_id, key)
                    else:
                        self._inbox[key] = payload
                    # A live message revives a src a previous connection
                    # declared dead (sender redialed).
                    self._dead_srcs.pop(src, None)
                    self._inbox_cv.notify_all()
                # Drop the frame's reference: otherwise this loop pins the
                # last payload's pool bytes while blocked on the next header.
                payload = None
        except (TransportError, OSError) as e:
            if not self._closed.is_set():
                # Fail pending/future recvs from these srcs fast (after the
                # reconnect grace) instead of sitting out the recv timeout.
                now = time.monotonic()
                with self._inbox_cv:
                    for src in srcs_seen:
                        self._dead_srcs.setdefault(src, (str(e), now))
                    self._inbox_cv.notify_all()
                logger.warning("host %d: peer connection died: %s",
                               self.host_id, e)
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def recv(self, src: int, tag: Tag, timeout_s: Optional[float] = None):
        """Block until the message with ``tag`` from host ``src`` arrives.

        Returns a bytes-like object: for remote messages a ``memoryview``
        over a pool-tracked recv buffer (content-compares equal to
        ``bytes``, supports the buffer protocol for ``pa.BufferReader`` /
        ``pa.py_buffer``, and keeps the pool bytes charged until every
        reference is gone), for self-sends whatever the sender passed.
        Callers needing an owned immutable copy should ``bytes(payload)``.

        Each message is consumed exactly once. Raises TransportTimeout after
        ``timeout_s`` (default: the transport-wide ``recv_timeout_s``) so a
        dead peer fails the trial loudly instead of hanging it.
        """
        if timeout_s is None:
            timeout_s = self._recv_timeout_s
        # Fault site: fires BEFORE the inbox pop, so the message is not
        # consumed — a caller-level retry of recv() is always safe.
        rt_faults.inject("transport_recv", epoch=tag[0], task=tag[1])
        key = (src, tag)
        start = time.monotonic()
        deadline = start + timeout_s
        with self._inbox_cv:
            while key not in self._inbox:
                if self._closed.is_set():
                    raise TransportError("transport closed while receiving")
                if src in self._dead_srcs:
                    reason, died_at = self._dead_srcs[src]
                    # Give a redialing sender reconnect_grace_s to revive
                    # the src before failing the trial.
                    if (time.monotonic() - died_at
                            >= self._reconnect_grace_s):
                        raise TransportError(
                            f"host {self.host_id}: connection from host "
                            f"{src} died before message {tag} arrived "
                            f"(no reconnect within "
                            f"{self._reconnect_grace_s:.0f}s): {reason}")
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TransportTimeout(
                        f"host {self.host_id}: no message {tag} from host "
                        f"{src} within {timeout_s:.0f}s")
                self._inbox_cv.wait(timeout=min(remaining, 1.0))
            payload = self._inbox.pop(key)
        rt_telemetry.record("transport_recv", epoch=tag[0], task=tag[1],
                            dur_s=time.monotonic() - start, src=src)
        return payload

    # -- send path -----------------------------------------------------------

    def send(self, dest: int, tag: Tag, payload) -> None:
        """Send ``payload`` (any buffer-protocol object, e.g. bytes or a
        ``pyarrow.Buffer``) to host ``dest`` tagged ``tag``. Thread-safe."""
        if dest == self.host_id:
            key = (self.host_id, tag)
            with self._inbox_cv:
                if key in self._inbox:
                    raise TransportError(f"duplicate message for {key}")
                self._inbox[key] = payload
                self._inbox_cv.notify_all()
            return
        sock = self._peers.get(dest)
        if sock is None:
            raise TransportError(
                f"host {self.host_id} has no connection to peer {dest} "
                "(connect() not called or peer unreachable)")
        epoch, reducer, file_index = tag
        header = _HEADER.pack(_MAGIC, self.host_id, epoch, reducer,
                              file_index, memoryview(payload).nbytes)
        from ray_shuffling_data_loader_tpu import native

        def _send_frame(s: socket.socket) -> None:
            # Fault site fires inside the frame sender, so an injected
            # send fault exercises the SAME redial+resend path a real
            # socket error takes (and its per-key budget means the
            # resend on the fresh connection goes through).
            rt_faults.inject("transport_send", epoch=epoch, task=reducer)
            if (native.available()
                    and memoryview(payload).nbytes >= _NATIVE_PUMP_MIN_BYTES):
                # header + payload in one GIL-free writev stream: one GIL
                # transition per frame regardless of payload size.
                native.frame_send(s.fileno(), header, payload)
            else:
                s.sendall(header)
                s.sendall(payload)

        send_start = time.monotonic()
        with self._peer_locks[dest]:
            try:
                _send_frame(sock)
            except (OSError, rt_faults.InjectedFault) as first_err:
                # Elastic path: one redial + resend. The receiver discards
                # nothing on its side — a partial frame on the old
                # connection kills only that connection's recv loop, and
                # the resent frame arrives whole on the new one (the
                # receiver revives the src on first message).
                try:
                    sock.close()
                except OSError:
                    pass
                try:
                    new_sock = socket.create_connection(
                        self.addresses[dest], timeout=30)
                    new_sock.settimeout(None)
                    new_sock.setsockopt(socket.IPPROTO_TCP,
                                        socket.TCP_NODELAY, 1)
                    self._peers[dest] = new_sock
                    _send_frame(new_sock)
                    logger.warning(
                        "host %d: send to peer %d failed (%s); redialed and "
                        "resent %s", self.host_id, dest, first_err, tag)
                except OSError as e:
                    raise TransportError(
                        f"host {self.host_id} failed sending to peer {dest} "
                        f"(redial also failed: {e}): {first_err}")
        # The frame's (epoch, reducer, file) tag IS the cross-host trace
        # context; recording the send gives the merged trace both ends
        # of the hop (the receiver records transport_recv with the same
        # key — runtime/trace.py joins them).
        rt_telemetry.record("transport_send", epoch=epoch, task=reducer,
                            dur_s=time.monotonic() - send_start, dest=dest,
                            nbytes=memoryview(payload).nbytes)


def create_local_transports(world: int,
                            recv_timeout_s: float = 600.0
                            ) -> List[TcpTransport]:
    """A fully-connected ``world`` of transports on localhost ephemeral
    ports — the single-machine stand-in for a TPU slice's host network,
    used by tests and the multi-host simulation example."""
    transports = [
        TcpTransport(h, [("127.0.0.1", 0)] * world,
                     recv_timeout_s=recv_timeout_s) for h in range(world)
    ]
    for t in transports:
        t.start()
    addresses = [("127.0.0.1", t.bound_port()) for t in transports]
    for t in transports:
        t.addresses = addresses
        t.connect()
    return transports
