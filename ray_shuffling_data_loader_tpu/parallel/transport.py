"""Tagged TCP byte transport for cross-host shuffle traffic (the DCN plane).

The reference moves map->reduce chunks between nodes through Ray's plasma
object store and raylet-to-raylet object transfer (C++, external — SURVEY.md
§2.3, reference: shuffle.py:185-186). On a TPU slice that data plane is the
host network / DCN, and nothing external provides it, so this module is the
framework's own transport: one listener per host, persistent peer
connections, length-prefixed frames tagged ``(epoch, reducer, file_index)``,
and a blocking tag-matched receive. Payloads are raw bytes (the shuffle
sends Arrow IPC streams); ``socket.sendall``/``recv`` release the GIL so
large transfers overlap with map/reduce compute threads.

Wire format per message (v2: generation-fenced), all little-endian:

    magic   u32 = 0x5244534C ("RSDL")
    src     u32   sending host id
    incarnation u32  sender's process generation (membership/)
    view    u32   sender's membership view id at send time
    epoch   u64   (2^64-1 = heartbeat control frame, no payload)
    reducer u64
    file    u64
    length  u64   payload byte count
    payload length bytes

**Generation fencing** (PR 18, membership/): every frame carries the
sender's ``(incarnation, view)``. The receiver tracks the highest
incarnation seen per src and drops — loudly: a warning, the
``rsdl_member_fenced_frames_total`` counter, and ``member_fenced_frame``
telemetry — any frame from an OLDER incarnation (a zombie pre-kill
process still flushing its socket) or from a view below an explicit
:meth:`TcpTransport.fence_view` floor (pre-resize stragglers after a
coordinated view cut). A rejoin re-announces itself implicitly: its
first frame's higher incarnation advances the fence. Heartbeat control
frames (epoch sentinel, zero payload) feed the failure detector via the
frame observer and never touch the tag inbox.
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ray_shuffling_data_loader_tpu.runtime import faults as rt_faults
from ray_shuffling_data_loader_tpu.runtime import retry as rt_retry
from ray_shuffling_data_loader_tpu.runtime import telemetry as rt_telemetry
from ray_shuffling_data_loader_tpu.utils.logger import setup_custom_logger

logger = setup_custom_logger(__name__)

_MAGIC = 0x5244534C
_HEADER = struct.Struct("<IIIIQQQQ")

#: Epoch sentinel marking a heartbeat control frame (zero payload,
#: never inboxed — it exists to carry ``(src, incarnation, view)`` to
#: the failure detector across otherwise-idle links).
_HEARTBEAT_EPOCH = (1 << 64) - 1

# Payloads at least this large move through the native C pump (one writev /
# one read loop per frame, a single GIL transition). Below it, Python's own
# C socket methods are just as fast and skip the wrapper overhead —
# measured on loopback: the pump costs ~35us/frame extra at 16KB frames
# and is break-even from ~1MB up.
_NATIVE_PUMP_MIN_BYTES = 1 << 20

Tag = Tuple[int, int, int]  # (epoch, reducer_index, file_index)


class TransportError(RuntimeError):
    pass


class TransportTimeout(TransportError):
    pass


class PeerUnreachable(TransportError):
    """One specific peer could not be dialed.

    ``connect()`` historically collapsed any peer's failure into a
    whole-world ``TransportError`` carrying only the LAST ``OSError`` —
    callers could not tell *which* peer was dead, so partial
    connectivity (the elastic-membership normal case) was
    indistinguishable from total failure. This carries the structured
    facts: ``peer`` (rank), ``address``, ``attempts``, and the
    underlying ``last_error``.
    """

    def __init__(self, host_id: int, peer: int, address: Tuple[str, int],
                 attempts: int, last_error: BaseException):
        super().__init__(
            f"host {host_id} could not reach peer {peer} at "
            f"{address[0]}:{address[1]} after {attempts} attempts: "
            f"{type(last_error).__name__}: {last_error}")
        self.peer = peer
        self.address = address
        self.attempts = attempts
        self.last_error = last_error


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    """Read exactly n bytes or raise TransportError on EOF."""
    chunks = []
    remaining = n
    while remaining:
        chunk = sock.recv(min(remaining, 1 << 20))
        if not chunk:
            raise TransportError("peer closed connection mid-message")
        chunks.append(chunk)
        remaining -= len(chunk)
    return b"".join(chunks) if len(chunks) != 1 else chunks[0]


def _recv_payload(sock: socket.socket, n: int):
    """Read an n-byte payload into a pool-tracked buffer.

    The buffer comes from the native buffer pool (single copy off the
    socket — no ``b"".join`` concat pass) and its bytes stay charged to the
    pipeline ledger until every reference is gone — including zero-copy
    Arrow tables deserialized over it, which keep the returned array alive
    via ``pa.py_buffer``'s base reference.

    Payloads of at least ``_NATIVE_PUMP_MIN_BYTES`` arrive through the
    native C pump: one GIL-free read loop per frame instead of one
    ``recv_into`` hop (and GIL re-acquisition) per ~MB.
    """
    from ray_shuffling_data_loader_tpu import native
    buf = native.alloc_tracked_buffer(n)
    view = memoryview(buf)
    if native.available() and n >= _NATIVE_PUMP_MIN_BYTES:
        if not native.read_exact_into(sock.fileno(), buf, n):
            raise TransportError("peer closed connection mid-message")
        return view
    received = 0
    while received < n:
        got = sock.recv_into(view[received:], min(n - received, 1 << 20))
        if not got:
            raise TransportError("peer closed connection mid-message")
        received += got
    # memoryview: content-compares equal to bytes, supports the buffer
    # protocol for pa.BufferReader, and keeps `buf` (and its pool bytes)
    # alive exactly as long as anything references the payload.
    return view


class TcpTransport:
    """Point-to-point tagged message transport between shuffle hosts.

    Args:
        host_id: this host's index in ``addresses``.
        addresses: ``(hostname, port)`` per host, identical on every host.

    ``start()`` binds the listener; ``connect()`` dials every peer (call on
    all hosts after all have started — the dial retries with backoff to
    absorb startup skew, the same role as the reference's named-actor
    connect retry, reference: multiqueue.py:310-332).
    """

    def __init__(self, host_id: int, addresses: Sequence[Tuple[str, int]],
                 recv_timeout_s: float = 600.0,
                 reconnect_grace_s: float = 5.0,
                 incarnation: int = 0):
        if not 0 <= host_id < len(addresses):
            raise ValueError(
                f"host_id {host_id} out of range for {len(addresses)} hosts")
        self.host_id = host_id
        self.addresses = list(addresses)
        self.world = len(addresses)
        #: This process's generation (membership/): a rank that dies and
        #: rejoins comes back one higher, so receivers fence the dead
        #: generation's zombie frames.
        self.incarnation = int(incarnation)
        #: Membership view id stamped on outgoing frames.
        self.view_id = 0
        self._min_view = 0
        self._peer_incarnations: Dict[int, int] = {}
        self._frame_observer = None  # cb(src, incarnation, view, is_hb)
        self._unreachable: set = set()
        self._recv_timeout_s = recv_timeout_s
        self._reconnect_grace_s = reconnect_grace_s
        # Values are bytes-like: pool-backed memoryviews (remote) or the
        # sender's payload object (self-sends).
        self._inbox: Dict[Tuple[int, Tag], Any] = {}
        self._inbox_cv = threading.Condition()
        # src host id -> (reason, death monotonic time). A src is revived
        # (entry dropped) when a message arrives on a NEW connection — a
        # sender that redials after a transient failure resumes seamlessly;
        # recv() only fails a dead src after reconnect_grace_s.
        self._dead_srcs: Dict[int, Tuple[str, float]] = {}
        self._peers: Dict[int, socket.socket] = {}
        self._peer_locks: Dict[int, threading.Lock] = {}
        self._listener: Optional[socket.socket] = None
        self._accept_threads: List[threading.Thread] = []
        self._closed = threading.Event()

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        """Bind the listener and start accepting peer connections."""
        host, port = self.addresses[self.host_id]
        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.bind((host, port))
        listener.listen(self.world)
        self._listener = listener
        thread = threading.Thread(target=self._accept_loop, daemon=True,
                                  name=f"rsdl-transport-accept-{self.host_id}")
        thread.start()
        self._accept_threads.append(thread)

    def bound_port(self) -> int:
        """The actual listening port (useful when configured with port 0)."""
        assert self._listener is not None, "start() first"
        return self._listener.getsockname()[1]

    def connect(self, retries: int = 30,
                initial_backoff_s: float = 0.1,
                on_unreachable: str = "raise") -> List[int]:
        """Dial every remote peer, retrying to absorb startup skew.

        The redial schedule is the shared ``RetryPolicy`` for the
        ``transport`` component: exponential backoff with decorrelated
        jitter (capped at 5s) — a whole slice's hosts dialing a
        late-arriving peer de-synchronize instead of re-dialing in
        lockstep at a fixed interval.

        Per-peer failure is structured, never all-or-nothing:
        ``on_unreachable="raise"`` (the historical contract, now with
        the peer identified) raises :class:`PeerUnreachable` carrying
        the peer id/address/attempts/cause; ``"skip"`` records the peer
        as unreachable (``member_unreachable`` telemetry) and keeps
        dialing the rest — the elastic-membership mode, where a dead or
        not-yet-joined rank is a view fact, not a fatal error. Returns
        the list of unreachable peer ids (always empty for
        ``"raise"``). A skipped peer can be dialed later with
        :meth:`dial` (the join path) or lazily by :meth:`send`.
        """
        if on_unreachable not in ("raise", "skip"):
            raise ValueError(
                f"on_unreachable must be raise|skip, got "
                f"{on_unreachable!r}")
        policy = rt_retry.RetryPolicy.for_component(
            "transport", retry_max_attempts=retries + 1,
            retry_initial_backoff_s=initial_backoff_s,
            retry_max_backoff_s=5.0,
            retryable=lambda e: isinstance(e, OSError))
        unreachable: List[int] = []
        # The address table is the dial list — the transport's one
        # legitimate frozen-world walk (membership decides liveness on
        # top of it). rsdl-lint: disable=fixed-world-assumption
        for peer in range(self.world):
            if peer == self.host_id:
                continue
            try:
                policy.call(lambda peer=peer: self._dial_peer(peer),
                            describe=f"dial peer {peer}")
            except OSError as e:
                error = PeerUnreachable(self.host_id, peer,
                                        self.addresses[peer],
                                        retries + 1, e)
                if on_unreachable == "raise":
                    raise error
                unreachable.append(peer)
                self._unreachable.add(peer)
                logger.warning("host %d: peer %d unreachable, skipping "
                               "(%s)", self.host_id, peer, error)
                rt_telemetry.record("member_unreachable", task=peer,
                                    src=self.host_id)
        logger.info("host %d connected to %d peers", self.host_id,
                    self.world - 1 - len(unreachable))
        return unreachable

    def _dial_peer(self, peer: int) -> socket.socket:
        sock = socket.create_connection(self.addresses[peer], timeout=30)
        # Drop the dial timeout: a timed-out sendall after a partial
        # write would corrupt the framed stream. Blocking sends + the
        # receiver-side recv timeout handle dead peers.
        sock.settimeout(None)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        # Setup phase (connect()) is single-threaded, and the lazy-dial
        # paths write _peers[peer] before any sender can hold its lock
        # (send() creates the lock first via setdefault):
        # rsdl-lint: disable=lock-mutation,unguarded-shared-mutation
        self._peers[peer] = sock
        self._peer_locks.setdefault(peer, threading.Lock())
        self._unreachable.discard(peer)
        return sock

    def dial(self, peer: int, retries: int = 5,
             initial_backoff_s: float = 0.1) -> None:
        """Dial ONE peer (the member-join path: a grown world dials the
        new rank without re-dialing everyone). Raises
        :class:`PeerUnreachable` on failure."""
        policy = rt_retry.RetryPolicy.for_component(
            "transport", retry_max_attempts=retries + 1,
            retry_initial_backoff_s=initial_backoff_s,
            retry_max_backoff_s=5.0,
            retryable=lambda e: isinstance(e, OSError))
        try:
            policy.call(lambda: self._dial_peer(peer),
                        describe=f"dial peer {peer}")
        except OSError as e:
            raise PeerUnreachable(self.host_id, peer,
                                  self.addresses[peer], retries + 1, e)

    # -- membership hooks ----------------------------------------------------

    def known_peers(self) -> List[int]:
        """Peers with a live dialed connection (the prober's probe set)."""
        return sorted(self._peers.keys())

    def set_frame_observer(self, callback) -> None:
        """Install ``cb(src, incarnation, view, is_heartbeat)``, called
        for every ACCEPTED (non-fenced) frame — the failure detector's
        piggybacked-heartbeat feed."""
        self._frame_observer = callback

    def announce(self, incarnation: int,
                 view_id: Optional[int] = None) -> None:
        """Re-announce this rank's ``(incarnation, view)`` — the rejoin
        path: a restarted rank stamps its new generation on every
        outgoing frame, which is what un-fences it at receivers."""
        self.incarnation = int(incarnation)
        if view_id is not None:
            self.view_id = int(view_id)

    def set_view(self, view_id: int) -> None:
        """Adopt a membership view id for outgoing frames."""
        self.view_id = int(view_id)

    def fence_view(self, min_view: int) -> None:
        """Reject incoming frames stamped with a view below
        ``min_view`` — the post-resize cut: once a new view is adopted
        everywhere, stragglers from the old world are dropped loudly
        instead of corrupting the resized stream."""
        with self._inbox_cv:
            self._min_view = int(min_view)

    def close(self) -> None:
        self._closed.set()
        with self._inbox_cv:
            self._inbox_cv.notify_all()
        for sock in self._peers.values():
            try:
                sock.close()
            except OSError:
                pass
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass

    def __enter__(self) -> "TcpTransport":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- receive path --------------------------------------------------------

    def _accept_loop(self) -> None:
        assert self._listener is not None
        while not self._closed.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return  # listener closed
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # Explicit infinite recv: idle links between epochs are
            # normal, and the recv loop's exit path is transport.close()
            # closing this conn (the recv() API's own timeout is enforced
            # tag-side). A silent default would be a bug; this is the
            # reviewed decision the socket-op-no-timeout rule asks for.
            conn.settimeout(None)
            thread = threading.Thread(target=self._recv_loop, args=(conn,),
                                      daemon=True,
                                      name=f"rsdl-transport-recv-{self.host_id}")
            thread.start()
            self._accept_threads.append(thread)

    def _recv_loop(self, conn: socket.socket) -> None:
        srcs_seen: set = set()
        try:
            while not self._closed.is_set():
                first = conn.recv(_HEADER.size)
                if not first:
                    return  # clean close at a message boundary
                header = (first if len(first) == _HEADER.size else
                          first + _recv_exact(conn,
                                              _HEADER.size - len(first)))
                (magic, src, incarnation, view, epoch, reducer,
                 file_index, length) = _HEADER.unpack(header)
                if magic != _MAGIC:
                    raise TransportError(
                        f"bad magic {magic:#x} from peer (protocol mismatch)")
                srcs_seen.add(src)
                payload = _recv_payload(conn, length)
                # Generation fence: frames from an older incarnation of
                # src (a zombie pre-kill process) or from a view below
                # the fence_view floor are dropped LOUDLY — they are
                # evidence of a process the world already moved past,
                # and letting them into the inbox would corrupt the
                # resized stream with stale data.
                with self._inbox_cv:
                    known = self._peer_incarnations.get(src, 0)
                    stale = incarnation < known or view < self._min_view
                    if not stale and incarnation > known:
                        self._peer_incarnations[src] = incarnation
                if stale:
                    from ray_shuffling_data_loader_tpu.runtime import (
                        metrics as rt_metrics)
                    rt_metrics.counter(
                        "rsdl_member_fenced_frames_total",
                        "frames rejected by the incarnation/view "
                        "fence").inc()
                    rt_telemetry.record(
                        "member_fenced_frame", epoch=epoch, task=reducer,
                        src=src, incarnation=incarnation, view=view)
                    logger.warning(
                        "host %d: FENCED stale frame from host %d "
                        "(incarnation %d < %d or view %d < %d); dropped",
                        self.host_id, src, incarnation,
                        self._peer_incarnations.get(src, 0), view,
                        self._min_view)
                    payload = None
                    continue
                if self._frame_observer is not None:
                    self._frame_observer(src, incarnation, view,
                                         epoch == _HEARTBEAT_EPOCH)
                if epoch == _HEARTBEAT_EPOCH:
                    # Control frame: detector food only, never inboxed.
                    payload = None
                    continue
                key = (src, (epoch, reducer, file_index))
                with self._inbox_cv:
                    if key in self._inbox:
                        # At-least-once delivery: a sender whose sendall
                        # errored after the frame was in fact delivered
                        # resends it on a fresh connection. Keep the first.
                        logger.warning(
                            "host %d: dropping duplicate message %s "
                            "(sender resend after reconnect)",
                            self.host_id, key)
                    else:
                        self._inbox[key] = payload
                    # A live message revives a src a previous connection
                    # declared dead (sender redialed).
                    self._dead_srcs.pop(src, None)
                    self._inbox_cv.notify_all()
                # Drop the frame's reference: otherwise this loop pins the
                # last payload's pool bytes while blocked on the next header.
                payload = None
        except (TransportError, OSError) as e:
            if not self._closed.is_set():
                # Fail pending/future recvs from these srcs fast (after the
                # reconnect grace) instead of sitting out the recv timeout.
                now = time.monotonic()
                with self._inbox_cv:
                    for src in srcs_seen:
                        self._dead_srcs.setdefault(src, (str(e), now))
                    self._inbox_cv.notify_all()
                logger.warning("host %d: peer connection died: %s",
                               self.host_id, e)
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def recv(self, src: int, tag: Tag, timeout_s: Optional[float] = None):
        """Block until the message with ``tag`` from host ``src`` arrives.

        Returns a bytes-like object: for remote messages a ``memoryview``
        over a pool-tracked recv buffer (content-compares equal to
        ``bytes``, supports the buffer protocol for ``pa.BufferReader`` /
        ``pa.py_buffer``, and keeps the pool bytes charged until every
        reference is gone), for self-sends whatever the sender passed.
        Callers needing an owned immutable copy should ``bytes(payload)``.

        Each message is consumed exactly once. Raises TransportTimeout after
        ``timeout_s`` (default: the transport-wide ``recv_timeout_s``) so a
        dead peer fails the trial loudly instead of hanging it.
        """
        if timeout_s is None:
            timeout_s = self._recv_timeout_s
        # Fault site: fires BEFORE the inbox pop, so the message is not
        # consumed — a caller-level retry of recv() is always safe.
        rt_faults.inject("transport_recv", epoch=tag[0], task=tag[1])
        key = (src, tag)
        start = time.monotonic()
        deadline = start + timeout_s
        with self._inbox_cv:
            while key not in self._inbox:
                if self._closed.is_set():
                    raise TransportError("transport closed while receiving")
                if src in self._dead_srcs:
                    reason, died_at = self._dead_srcs[src]
                    # Give a redialing sender reconnect_grace_s to revive
                    # the src before failing the trial.
                    if (time.monotonic() - died_at
                            >= self._reconnect_grace_s):
                        raise TransportError(
                            f"host {self.host_id}: connection from host "
                            f"{src} died before message {tag} arrived "
                            f"(no reconnect within "
                            f"{self._reconnect_grace_s:.0f}s): {reason}")
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TransportTimeout(
                        f"host {self.host_id}: no message {tag} from host "
                        f"{src} within {timeout_s:.0f}s")
                self._inbox_cv.wait(timeout=min(remaining, 1.0))
            payload = self._inbox.pop(key)
        rt_telemetry.record("transport_recv", epoch=tag[0], task=tag[1],
                            dur_s=time.monotonic() - start, src=src)
        return payload

    # -- send path -----------------------------------------------------------

    def send(self, dest: int, tag: Tag, payload) -> None:
        """Send ``payload`` (any buffer-protocol object, e.g. bytes or a
        ``pyarrow.Buffer``) to host ``dest`` tagged ``tag``. Thread-safe."""
        if dest == self.host_id:
            key = (self.host_id, tag)
            with self._inbox_cv:
                if key in self._inbox:
                    raise TransportError(f"duplicate message for {key}")
                self._inbox[key] = payload
                self._inbox_cv.notify_all()
            return
        sock = self._peers.get(dest)
        if sock is None:
            raise TransportError(
                f"host {self.host_id} has no connection to peer {dest} "
                "(connect() not called or peer unreachable)")
        epoch, reducer, file_index = tag
        # Chaos site: a partitioned link drops the frame silently — no
        # error reaches the sender, exactly like a blackholing switch.
        # The telemetry twin keeps the drop observable to the harness.
        try:
            rt_faults.inject("member_partition", epoch=epoch, task=dest)
        except rt_faults.InjectedFault:
            rt_telemetry.record("member_partition", epoch=epoch, task=dest,
                                src=self.host_id, fault="frame_dropped")
            return
        header = _HEADER.pack(_MAGIC, self.host_id, self.incarnation,
                              self.view_id, epoch, reducer, file_index,
                              memoryview(payload).nbytes)
        from ray_shuffling_data_loader_tpu import native

        def _send_frame(s: socket.socket) -> None:
            # Fault site fires inside the frame sender, so an injected
            # send fault exercises the SAME redial+resend path a real
            # socket error takes (and its per-key budget means the
            # resend on the fresh connection goes through).
            rt_faults.inject("transport_send", epoch=epoch, task=reducer)
            if (native.available()
                    and memoryview(payload).nbytes >= _NATIVE_PUMP_MIN_BYTES):
                # header + payload in one GIL-free writev stream: one GIL
                # transition per frame regardless of payload size.
                native.frame_send(s.fileno(), header, payload)
            else:
                s.sendall(header)
                s.sendall(payload)

        send_start = time.monotonic()
        with self._peer_locks[dest]:
            try:
                _send_frame(sock)
            except (OSError, rt_faults.InjectedFault) as first_err:
                # Elastic path: one redial + resend. The receiver discards
                # nothing on its side — a partial frame on the old
                # connection kills only that connection's recv loop, and
                # the resent frame arrives whole on the new one (the
                # receiver revives the src on first message).
                try:
                    sock.close()
                except OSError:
                    pass
                try:
                    new_sock = socket.create_connection(
                        self.addresses[dest], timeout=30)
                    new_sock.settimeout(None)
                    new_sock.setsockopt(socket.IPPROTO_TCP,
                                        socket.TCP_NODELAY, 1)
                    self._peers[dest] = new_sock
                    _send_frame(new_sock)
                    logger.warning(
                        "host %d: send to peer %d failed (%s); redialed and "
                        "resent %s", self.host_id, dest, first_err, tag)
                except OSError as e:
                    raise TransportError(
                        f"host {self.host_id} failed sending to peer {dest} "
                        f"(redial also failed: {e}): {first_err}")
        # The frame's (epoch, reducer, file) tag IS the cross-host trace
        # context; recording the send gives the merged trace both ends
        # of the hop (the receiver records transport_recv with the same
        # key — runtime/trace.py joins them).
        rt_telemetry.record("transport_send", epoch=epoch, task=reducer,
                            dur_s=time.monotonic() - send_start, dest=dest,
                            nbytes=memoryview(payload).nbytes)

    def send_heartbeat(self, dest: int) -> None:
        """Best-effort heartbeat control frame to ``dest`` — zero
        payload, epoch sentinel, never inboxed at the receiver (it feeds
        the failure detector through the frame observer). Socket errors
        are swallowed: a dead link is exactly what the detector's
        *silence* is for, and the prober must not die with it."""
        if dest == self.host_id:
            return
        try:
            rt_faults.inject("member_partition", task=dest)
        except rt_faults.InjectedFault:
            rt_telemetry.record("member_partition", task=dest,
                                src=self.host_id,
                                fault="heartbeat_dropped")
            return
        sock = self._peers.get(dest)
        if sock is None:
            return
        header = _HEADER.pack(_MAGIC, self.host_id, self.incarnation,
                              self.view_id, _HEARTBEAT_EPOCH, 0, 0, 0)
        lock = self._peer_locks.get(dest)
        if lock is None:
            return
        with lock:
            try:
                sock.sendall(header)
            except OSError:
                pass


def create_local_transports(world: int,
                            recv_timeout_s: float = 600.0,
                            incarnations: Optional[Sequence[int]] = None
                            ) -> List[TcpTransport]:
    """A fully-connected ``world`` of transports on localhost ephemeral
    ports — the single-machine stand-in for a TPU slice's host network,
    used by tests and the multi-host simulation example."""
    # Harness helper: the frozen walk over `world` here BUILDS the
    # address table membership layers liveness on top of.
    transports = [
        TcpTransport(h,
                     # rsdl-lint: disable=fixed-world-assumption
                     [("127.0.0.1", 0)] * world,
                     recv_timeout_s=recv_timeout_s,
                     incarnation=(0 if incarnations is None
                                  else int(incarnations[h])))
        # rsdl-lint: disable=fixed-world-assumption
        for h in range(world)
    ]
    for t in transports:
        t.start()
    addresses = [("127.0.0.1", t.bound_port()) for t in transports]
    for t in transports:
        t.addresses = addresses
        t.connect()
    return transports
