"""Multi-host distributed shuffle: per-host map/reduce + DCN all-to-all.

The reference scales across nodes by letting Ray place map/reduce tasks
anywhere on the cluster and shipping chunks through the plasma object store
(reference: shuffle.py:174-187, SURVEY.md §2.3). The TPU-native topology is
SPMD: one loader process per TPU-VM host (``jax.distributed``-style world),
each host mapping its contiguous shard of the global file list and owning a
contiguous shard of the global reducers. Only map->reduce chunks cross
hosts — an all-to-all over the host network / DCN carried by
``parallel.transport.TcpTransport``. Reducer ownership is aligned with the
reference's reducer->trainer routing (``np.array_split`` contiguous groups,
reference: shuffle.py:188-189), so reduce->trainer traffic is always
host-local.

Determinism contract: map and reduce PRNG streams are keyed by **global**
file and reducer indices (ops/partition.py), so for a given
``(seed, num_reducers, num_trainers)`` the batches global trainer ``t``
consumes are bit-identical whether the shuffle ran on one host or many —
the property test_distributed.py asserts, and what makes checkpoint/resume
topology-independent.
"""

from __future__ import annotations

import functools
import timeit
from typing import Dict, List, Optional, Sequence, Tuple

import pyarrow as pa

from ray_shuffling_data_loader_tpu import executor as ex
from ray_shuffling_data_loader_tpu import multiqueue as mq
# Not ``from ray_shuffling_data_loader_tpu import shuffle``: the package
# __init__ rebinds that attribute to the shuffle() function.
import importlib
sh = importlib.import_module("ray_shuffling_data_loader_tpu.shuffle")
from ray_shuffling_data_loader_tpu.dataset import batch_consumer as queue_batch_consumer
from ray_shuffling_data_loader_tpu.ops import partition as ops
from ray_shuffling_data_loader_tpu.parallel.transport import TcpTransport
from ray_shuffling_data_loader_tpu.utils.logger import setup_custom_logger

logger = setup_custom_logger(__name__)


def serialize_table(table: pa.Table) -> pa.Buffer:
    """Arrow IPC stream as a ``pa.Buffer`` (C++ writer; the buffer goes to
    the socket via the buffer protocol — no to_pybytes() memcpy on the
    shuffle's hottest cross-host path)."""
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, table.schema) as writer:
        writer.write_table(table)
    return sink.getvalue()


def deserialize_table(payload: bytes) -> pa.Table:
    with pa.ipc.open_stream(pa.BufferReader(payload)) as reader:
        return reader.read_all()


class ShardPlan:
    """Static partition of files, reducers, and trainers across hosts.

    - Global trainer ``t = host * trainers_per_host + local_rank``.
    - Reducer groups: ``contiguous_splits(range(num_reducers), num_trainers)``
      — exactly the reference's reducer->trainer routing
      (reference: shuffle.py:188-189) — and host ``h`` owns the union of its
      trainers' groups (a contiguous reducer range).
    - File shard: ``contiguous_splits(range(num_files), world)``.
    """

    def __init__(self, num_files: int, num_reducers: int, world: int,
                 trainers_per_host: int = 1):
        if world < 1 or trainers_per_host < 1:
            raise ValueError("world and trainers_per_host must be >= 1")
        self.world = world
        self.trainers_per_host = trainers_per_host
        # The STATIC shard plan is frozen at launch by contract (the
        # trainer count never changes under elasticity — membership/
        # resizes reducer placement, not trainer topology).
        # rsdl-lint: disable=fixed-world-assumption
        self.num_trainers = world * trainers_per_host
        self.num_files = num_files
        self.num_reducers = num_reducers
        self.file_shards: List[List[int]] = ops.contiguous_splits(
            list(range(num_files)), world)
        trainer_groups = ops.contiguous_splits(
            list(range(num_reducers)), self.num_trainers)
        self.trainer_reducers: List[List[int]] = trainer_groups
        # reducer -> owning host, via owning trainer.
        self._reducer_host = {}
        for t, group in enumerate(trainer_groups):
            for r in group:
                self._reducer_host[r] = t // trainers_per_host
        # file -> owning host, O(1) (resolved once per (file, reducer) pair
        # per epoch on the reduce hot path).
        self._file_host = [0] * num_files
        for h, shard in enumerate(self.file_shards):
            for f in shard:
                self._file_host[f] = h

    def file_host(self, file_index: int) -> int:
        if not 0 <= file_index < self.num_files:
            raise ValueError(f"file index {file_index} out of range")
        return self._file_host[file_index]

    def reducer_host(self, reducer_index: int) -> int:
        return self._reducer_host[reducer_index]

    def local_files(self, host: int) -> List[int]:
        return self.file_shards[host]

    def local_trainers(self, host: int) -> List[int]:
        base = host * self.trainers_per_host
        return list(range(base, base + self.trainers_per_host))

    def local_reducers(self, host: int) -> List[int]:
        out: List[int] = []
        for t in self.local_trainers(host):
            out.extend(self.trainer_reducers[t])
        return out


def _map_task(filename: str, global_file_index: int, num_reducers: int,
              seed: int, epoch: int, plan: ShardPlan,
              transport: TcpTransport, stats_collector,
              map_transform=None,
              file_cache=None) -> Dict[int, "sh.LazyChunk"]:
    """Map one local file, ship remote reducers' chunks, keep local ones.

    Remote chunks are materialized (gathered) only to cross the wire and
    leave immediately (sendall releases the GIL); host-local chunks stay
    lazy index arrays so the local reduce can run its single fused gather —
    the distributed analog of Ray's per-slice multi-return fetch
    (reference: shuffle.py:174-176).
    """
    shard = sh.shuffle_map(filename, num_reducers, seed, epoch,
                           global_file_index, stats_collector, map_transform,
                           file_cache)
    local: Dict[int, sh.LazyChunk] = {}
    for reducer_index, chunk in enumerate(shard):
        owner = plan.reducer_host(reducer_index)
        if owner == transport.host_id:
            local[reducer_index] = chunk
        else:
            # Fused-pipeline shards yield already-materialized tables;
            # legacy shards yield lazy chunks gathered here.
            payload = (chunk if isinstance(chunk, pa.Table)
                       else chunk.materialize())
            transport.send(owner, (epoch, reducer_index, global_file_index),
                           serialize_table(payload))
    return local


def _reduce_task(reducer_index: int, seed: int, epoch: int,
                 plan: ShardPlan, transport: TcpTransport,
                 local_map_refs: Dict[int, ex.TaskRef],
                 stats_collector, reduce_transform=None,
                 spill_manager=None, gather_threads=None) -> pa.Table:
    """Collect this reducer's chunk from every global file, then
    concat + seeded permute (global-index RNG => topology-independent)."""
    chunks: List = []  # LazyChunk (local) or pa.Table (remote)
    for file_index in range(plan.num_files):
        src = plan.file_host(file_index)
        if src == transport.host_id:
            chunks.append(local_map_refs[file_index].result()[reducer_index])
        else:
            payload = transport.recv(src, (epoch, reducer_index, file_index))
            chunks.append(deserialize_table(payload))
    shuffled = sh.shuffle_reduce(reducer_index, seed, epoch, chunks,
                                 stats_collector, reduce_transform,
                                 gather_threads)
    return sh.account_and_maybe_spill(shuffled, spill_manager,
                                      epoch=epoch, task=reducer_index,
                                      seed=seed)


def shuffle_epoch_distributed(epoch: int,
                              filenames: Sequence[str],
                              batch_consumer: sh.BatchConsumer,
                              plan: ShardPlan,
                              transport: TcpTransport,
                              pool: ex.Executor,
                              seed: int,
                              trial_start: float,
                              stats_collector=None,
                              map_transform=None,
                              file_cache=None,
                              reduce_transform=None,
                              spill_manager=None,
                              concurrent_epochs: int = 2) -> List[ex.TaskRef]:
    """One epoch on this host: map local files, reduce owned reducers,
    feed local trainers. Returns refs whose completion implies every
    cross-host send of this host's chunks has finished."""
    if stats_collector is not None:
        stats_collector.epoch_start(epoch)
    local_file_indices = plan.local_files(transport.host_id)
    map_refs: Dict[int, ex.TaskRef] = {
        fi: pool.submit(_map_task, filenames[fi], fi, plan.num_reducers,
                        seed, epoch, plan, transport, stats_collector,
                        map_transform, file_cache)
        for fi in local_file_indices
    }
    # submit_once: a reduce consumes transport messages exactly once, so a
    # retry would block on already-consumed tags until the recv timeout
    # and mask the original error. Maps MAY retry (duplicate sends are
    # dropped by the receiving transport).
    local_reducers = plan.local_reducers(transport.host_id)
    # Loopback worlds (tests, bench_distributed, single-machine emulation)
    # run every "host" on this one machine — split the cores; a real
    # deployment owns its cores per host. The driver's epoch throttle keeps
    # up to ``concurrent_epochs`` epochs' reducers in flight.
    loopback = all(host in ("127.0.0.1", "localhost")
                   for host, _ in transport.addresses)
    gather_threads = sh.derive_gather_threads(
        max(1, concurrent_epochs) * len(local_reducers), pool.num_workers,
        host_share=transport.world if loopback else 1)
    reduce_refs: Dict[int, ex.TaskRef] = {
        r: pool.submit_once(_reduce_task, r, seed, epoch, plan, transport,
                            map_refs, stats_collector, reduce_transform,
                            spill_manager, gather_threads)
        for r in local_reducers
    }
    for local_rank, trainer in enumerate(plan.local_trainers(transport.host_id)):
        refs = [reduce_refs[r] for r in plan.trainer_reducers[trainer]]
        sh.consume(local_rank, batch_consumer, trial_start, stats_collector,
                   epoch, refs)
        batch_consumer(local_rank, epoch, None)
    # Map refs are included so the epoch drain also guarantees this host's
    # outbound chunks were sent even for reducers it does not own.
    return list(reduce_refs.values()) + list(map_refs.values())


def shuffle_distributed(filenames: Sequence[str],
                        batch_consumer: sh.BatchConsumer,
                        num_epochs: int,
                        num_reducers: int,
                        transport: TcpTransport,
                        trainers_per_host: int = 1,
                        max_concurrent_epochs: int = 2,
                        seed: int = 0,
                        num_workers: Optional[int] = None,
                        pool: Optional[ex.Executor] = None,
                        start_epoch: int = 0,
                        map_transform=None,
                        file_cache="auto",
                        reduce_transform=None,
                        task_retries: int = 0,
                        collect_stats: bool = False,
                        max_inflight_bytes=None,
                        spill_dir=None):
    """Multi-epoch pipelined distributed shuffle driver for ONE host.

    Run with the same arguments on every host of the world (SPMD); hosts
    synchronize only through the chunk exchange itself. The per-host epoch
    throttle (``max_concurrent_epochs``) mirrors the reference driver's
    (reference: shuffle.py:103-140); a host cannot run ahead unboundedly
    because its reducers block on every peer's chunks for the oldest
    in-flight epoch. Returns wall-clock duration in seconds, or — with
    ``collect_stats`` — THIS host's ``TrialStats`` (its local maps/
    reduces/consumes; aggregate across hosts by summing the per-host CSVs,
    the analog of the reference's per-node stage spans).

    ``max_inflight_bytes`` / ``spill_dir`` carry the single-host driver's
    memory-budget semantics per host (see ``shuffle.shuffle``): without a
    spill dir the budget drains older epochs before launching; with one,
    over-budget reducer outputs spill to disk. ``batch_consumer`` then
    receives refs that may resolve to ``spill.SpilledTable`` handles —
    ``ShufflingDataset`` unwraps them automatically; custom consumers
    should call ``spill.unwrap``.
    """
    from ray_shuffling_data_loader_tpu import stats as stats_mod

    if not 0 <= start_epoch <= num_epochs:
        raise ValueError(
            f"start_epoch {start_epoch} out of range [0, {num_epochs}]")
    plan = ShardPlan(len(filenames), num_reducers, transport.world,
                     trainers_per_host)
    stats_collector = None
    if collect_stats:
        if start_epoch:
            raise ValueError(
                "collect_stats with start_epoch > 0 is unsupported (stats "
                "collectors assume all epochs run)")
        stats_collector = stats_mod.TrialStatsCollector(
            num_epochs,
            num_maps=len(plan.local_files(transport.host_id)),
            num_reduces=len(plan.local_reducers(transport.host_id)),
            num_consumes=trainers_per_host)
        stats_collector.trial_start()
    file_cache, owns_file_cache = sh.resolve_file_cache(
        file_cache, num_epochs - start_epoch)

    # Same budget semantics as the single-host driver, per host.
    from ray_shuffling_data_loader_tpu.spill import make_budget_state
    _over_budget, spill_manager = make_budget_state(
        file_cache, max_inflight_bytes, spill_dir)
    start = timeit.default_timer()
    owns_pool = pool is None
    if pool is None:
        pool = ex.Executor(num_workers=num_workers,
                           task_retries=task_retries)
    try:
        from ray_shuffling_data_loader_tpu.plan import ir as plan_ir
        in_progress: Dict[int, List[ex.TaskRef]] = {}
        # Epoch schedule comes from the plan layer (the static-epoch-
        # assumption contract): the multi-host driver iterates specs,
        # never the raw count.
        for spec in plan_ir.static_epoch_specs(filenames, num_epochs,
                                               start_epoch):
            epoch_idx = spec.epoch
            throttle_start = timeit.default_timer()
            # Budget pressure without a spill tier drains older epochs
            # before launching (single-host driver parity); with spilling
            # the launch proceeds and over-budget outputs go to disk. No
            # consumer-poll here: hosts must stay loosely in step, and a
            # long local stall would back-pressure every peer's reducers.
            while in_progress and (len(in_progress) >= max_concurrent_epochs
                                   or (spill_manager is None
                                       and _over_budget())):
                oldest = min(in_progress)
                refs = in_progress.pop(oldest)
                ex.wait(refs, num_returns=len(refs))
                for ref in refs:
                    ref.result()
            if stats_collector is not None:
                throttle_duration = timeit.default_timer() - throttle_start
                if throttle_duration > 1e-4:
                    stats_collector.throttle_done(epoch_idx,
                                                  throttle_duration)
            in_progress[epoch_idx] = shuffle_epoch_distributed(
                epoch_idx, filenames, batch_consumer, plan, transport, pool,
                seed, start, stats_collector=stats_collector,
                map_transform=map_transform,
                file_cache=file_cache, reduce_transform=reduce_transform,
                spill_manager=spill_manager,
                concurrent_epochs=min(max_concurrent_epochs,
                                      num_epochs - start_epoch))
        for epoch_idx in sorted(in_progress):
            refs = in_progress.pop(epoch_idx)
            ex.wait(refs, num_returns=len(refs))
            for ref in refs:
                ref.result()
    finally:
        if owns_pool:
            pool.shutdown()
        if owns_file_cache:
            # Same release point as the single-host driver: reducer
            # outputs are gathered copies, so drained refs mean the
            # decoded-cache scratch files have no remaining readers.
            file_cache.close()
        if spill_manager is not None:
            spill_manager.report()
        if owns_pool:
            # End-of-trial hygiene (same gating as the single-host
            # driver): release the pool's recycled recv buffers to the OS.
            from ray_shuffling_data_loader_tpu import native
            native.trim_freelist()
    if stats_collector is not None:
        stats_collector.trial_done()
        return stats_collector.get_stats()
    return timeit.default_timer() - start


def create_distributed_batch_queue_and_shuffle(
        filenames: Sequence[str],
        num_epochs: int,
        num_reducers: int,
        transport: TcpTransport,
        trainers_per_host: int = 1,
        max_concurrent_epochs: int = 2,
        max_batch_queue_size: int = 0,
        seed: int = 0,
        num_workers: Optional[int] = None,
        queue_name: Optional[str] = None,
        start_epoch: int = 0,
        map_transform=None,
        reduce_transform=None,
        task_retries: int = 0,
        file_cache="auto",
        max_inflight_bytes: Optional[int] = None,
        spill_dir: Optional[str] = None
) -> Tuple[mq.MultiQueue, ex.TaskRef]:
    """Host-local queue + background distributed shuffle driver.

    The returned ``(batch_queue, shuffle_result)`` plug straight into
    ``ShufflingDataset(batch_queue=..., shuffle_result=...)`` /
    ``JaxShufflingDataset`` with ``rank`` = local rank in
    ``[0, trainers_per_host)`` and ``num_trainers = trainers_per_host`` —
    the consumer-only pattern of the reference's distributed example
    (reference: dataset.py:17-51, ray_torch_shuffle.py:316-322).
    """
    from ray_shuffling_data_loader_tpu.dataset import make_failure_broadcaster
    batch_queue = mq.MultiQueue(num_epochs * trainers_per_host,
                                max_batch_queue_size, name=queue_name)
    consumer = functools.partial(queue_batch_consumer, batch_queue,
                                 trainers_per_host)
    on_failure = make_failure_broadcaster(batch_queue,
                                          num_epochs * trainers_per_host)
    driver_pool = ex.Executor(num_workers=1,
                              thread_name_prefix="rsdl-dist-driver")

    def _run():
        try:
            return shuffle_distributed(
                filenames, consumer, num_epochs, num_reducers, transport,
                trainers_per_host=trainers_per_host,
                max_concurrent_epochs=max_concurrent_epochs, seed=seed,
                num_workers=num_workers, start_epoch=start_epoch,
                map_transform=map_transform,
                reduce_transform=reduce_transform,
                task_retries=task_retries, file_cache=file_cache,
                max_inflight_bytes=max_inflight_bytes, spill_dir=spill_dir)
        except BaseException as e:  # noqa: BLE001 - forwarded to consumers
            on_failure(e)
            raise
        finally:
            driver_pool.shutdown(wait_for_tasks=False)

    return batch_queue, driver_pool.submit(_run)
