"""SPMD trainer: jitted train step over a device mesh.

Replaces the reference's Horovod training harness (reference:
examples/horovod/ray_torch_shuffle.py:126-243): instead of
``hvd.DistributedOptimizer`` wrapping a torch optimizer with NCCL allreduce
hooks (:173-177) and explicit parameter broadcast (:165-166), the whole
train step — forward, backward, optimizer update — is one ``jax.jit``
program over a ``Mesh``. Gradient synchronization is not written anywhere:
batches arrive sharded along the "data" axis, params are replicated (or TP-
sharded along "model"), and XLA inserts the ``psum``/``all_gather``
collectives over ICI that the sharding layout implies. fp16 compression /
Adasum knobs (:80-87) map to bf16 compute in the models and optax
transforms here.

The trainer owns sharded params + optimizer state and exposes
``train_step(batch) -> loss``; donation keeps params/opt-state in place in
HBM across steps (no host round-trips).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_shuffling_data_loader_tpu.parallel.mesh import DATA_AXIS
from ray_shuffling_data_loader_tpu.utils import tracing
from ray_shuffling_data_loader_tpu.utils.logger import setup_custom_logger

logger = setup_custom_logger(__name__)


def make_train_step(loss_fn: Callable,
                    optimizer: optax.GradientTransformation) -> Callable:
    """Pure train-step function: (params, opt_state, *batch) ->
    (params, opt_state, loss)."""

    def train_step(params, opt_state, *batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, *batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return train_step


class SpmdTrainer:
    """Owns mesh-sharded training state and the compiled step.

    Args:
        mesh: the device mesh ("data" [, "model"]).
        loss_fn: ``loss_fn(params, *batch) -> scalar``.
        params: initial parameter pytree (host or device).
        param_specs: pytree of ``PartitionSpec`` matching ``params``
            (e.g. ``models.dlrm.param_specs(cfg)``); ``None`` = replicate
            everything (pure DP).
        optimizer: an optax ``GradientTransformation``.
    """

    def __init__(self,
                 mesh: Mesh,
                 loss_fn: Callable,
                 params: Any,
                 optimizer: optax.GradientTransformation,
                 param_specs: Optional[Any] = None,
                 donate: bool = True):
        self.mesh = mesh
        if param_specs is None:
            param_specs = jax.tree.map(lambda _: P(), params)
        self._param_shardings = jax.tree.map(
            lambda spec: NamedSharding(mesh, spec), param_specs,
            is_leaf=lambda x: isinstance(x, P))
        self.params = jax.device_put(params, self._param_shardings)
        # Optimizer state sharding is inferred by XLA from the param
        # shardings (mu/nu mirror params; scalars replicate).
        self.opt_state = jax.jit(optimizer.init)(self.params)
        step = make_train_step(loss_fn, optimizer)
        self._step = jax.jit(
            step, donate_argnums=(0, 1) if donate else ())
        self._step_count = 0

    def train_step(self, *batch) -> jax.Array:
        """One optimizer step; returns the (lazy) scalar loss."""
        with tracing.step_span(self._step_count):
            self.params, self.opt_state, loss = self._step(
                self.params, self.opt_state, *batch)
        self._step_count += 1
        return loss

    def block_until_ready(self) -> None:
        jax.block_until_ready((self.params, self.opt_state))


def batch_shardings(mesh: Mesh, batch_example: Tuple,
                    data_axis: str = DATA_AXIS):
    """NamedShardings for a batch pytree: leading axis over ``data_axis``."""
    return jax.tree.map(
        lambda a: NamedSharding(
            mesh, P(data_axis, *([None] * (a.ndim - 1)))),
        batch_example)
