"""Multi-queue batch transport.

Capability parity with the reference's ``MultiQueue`` — N FIFO queues behind
one named endpoint with sync/async, blocking/non-blocking, and batched
put/get, named discovery with exponential backoff, and graceful shutdown
(reference: multiqueue.py:24-307,310-390).

TPU-native design difference: the reference needs a Ray *actor* because its
trainer processes are spawned by Horovod with no handle to driver state —
the queue is their rendezvous point (SURVEY.md §1). On a TPU slice, one
process per host drives all local devices (SPMD), so queues are host-local
and shared between the shuffle service threads and the training thread in
the same process. The named registry (process-local) keeps the reference's
connect-by-name contract so consumer code is identical in both topologies;
cross-host consumers are not needed because each host shuffles and consumes
its own shard of the data (deterministic shard routing, SURVEY.md §2.3).

Queue-id contract (unchanged from the reference, dataset.py:173):
``queue_id = epoch * num_trainers + rank``.
"""

from __future__ import annotations

import collections
import concurrent.futures as cf
import threading
import time
from typing import Any, List, Optional

from ray_shuffling_data_loader_tpu.runtime import faults as rt_faults
from ray_shuffling_data_loader_tpu.runtime import metrics as rt_metrics
from ray_shuffling_data_loader_tpu.runtime import telemetry as rt_telemetry
from ray_shuffling_data_loader_tpu.utils.logger import setup_custom_logger

logger = setup_custom_logger(__name__)


class Empty(Exception):
    """Raised by non-blocking gets on an empty queue (reference: multiqueue.py:13-14)."""


class Full(Exception):
    """Raised by non-blocking puts on a full queue (reference: multiqueue.py:17-18)."""


class ShutdownError(RuntimeError):
    """Raised to callers blocked in ``get``/``put`` when the queue shuts down.

    The reference's actor kill made blocked consumers fail loudly with a
    RayActorError (reference: multiqueue.py:285-307); this is the in-process
    equivalent so a stray consumer thread can't be silently stranded."""


class BoundedFifo:
    """Bounded FIFO with atomic all-or-nothing batch operations.

    Owned implementation (deque + two Conditions on one lock) rather than
    ``queue.Queue`` so the batch ops don't have to reach into stdlib
    internals. ``maxsize=0`` means unbounded. Raises this module's
    :class:`Empty`/:class:`Full`.
    """

    __slots__ = ("_maxsize", "_items", "_mutex", "_not_empty", "_not_full",
                 "_closed")

    def __init__(self, maxsize: int = 0):
        self._maxsize = maxsize
        self._items: collections.deque = collections.deque()
        self._mutex = threading.Lock()
        self._not_empty = threading.Condition(self._mutex)
        self._not_full = threading.Condition(self._mutex)
        self._closed = False

    def close(self) -> None:
        """Wake every blocked ``put``/``get`` waiter with :class:`ShutdownError`.

        Items already enqueued remain readable via non-waiting gets."""
        with self._mutex:
            self._closed = True
            self._not_empty.notify_all()
            self._not_full.notify_all()

    def qsize(self) -> int:
        with self._mutex:
            return len(self._items)

    def _has_room(self, n: int = 1) -> bool:
        return not self._maxsize or len(self._items) + n <= self._maxsize

    def put(self, item: Any, block: bool = True,
            timeout: Optional[float] = None) -> None:
        with self._not_full:
            if not self._has_room():
                if not block:
                    raise Full("queue is full")
                deadline = (None if timeout is None
                            else time.monotonic() + timeout)
                while not self._has_room():
                    if self._closed:
                        raise ShutdownError("queue shut down while put blocked")
                    remaining = (None if deadline is None
                                 else deadline - time.monotonic())
                    if remaining is not None and remaining <= 0:
                        raise Full("queue is full")
                    self._not_full.wait(remaining)
            self._items.append(item)
            self._not_empty.notify()

    def get(self, block: bool = True,
            timeout: Optional[float] = None) -> Any:
        with self._not_empty:
            if not self._items:
                if not block:
                    raise Empty("queue is empty")
                deadline = (None if timeout is None
                            else time.monotonic() + timeout)
                while not self._items:
                    if self._closed:
                        raise ShutdownError("queue shut down while get blocked")
                    remaining = (None if deadline is None
                                 else deadline - time.monotonic())
                    if remaining is not None and remaining <= 0:
                        raise Empty("queue is empty")
                    self._not_empty.wait(remaining)
            item = self._items.popleft()
            self._not_full.notify()
            return item

    def put_batch_atomic(self, items: List[Any]) -> None:
        """Enqueue all of ``items`` or none (non-blocking)."""
        with self._mutex:
            if not self._has_room(len(items)):
                raise Full(
                    f"cannot accept {len(items)} items "
                    f"(capacity {self._maxsize}, size {len(self._items)})")
            self._items.extend(items)
            self._not_empty.notify_all()

    def get_batch_atomic(self, num_items: int) -> List[Any]:
        """Dequeue exactly ``num_items`` or nothing (non-blocking)."""
        with self._mutex:
            if len(self._items) < num_items:
                raise Empty(
                    f"queue has {len(self._items)} items, need {num_items}")
            out = [self._items.popleft() for _ in range(num_items)]
            self._not_full.notify_all()
            return out


# Process-local named-queue registry (stands in for Ray's named actors).
_REGISTRY: dict = {}
_REGISTRY_LOCK = threading.Lock()

# Default connect/backoff schedule (reference: multiqueue.py:310-332).
CONNECT_RETRIES = 5
CONNECT_INITIAL_BACKOFF_S = 1.0


class MultiQueue:
    """N bounded FIFO queues behind one (optionally named) endpoint.

    ``maxsize=0`` means unbounded — the reference's default in practice
    (reference: dataset.py:86). ``connect=True`` attaches to an existing
    named queue instead of creating one.
    """

    def __init__(self,
                 num_queues: int,
                 maxsize: int = 0,
                 name: Optional[str] = None,
                 connect: bool = False,
                 connect_retries: int = CONNECT_RETRIES):
        if connect:
            if name is None:
                raise ValueError("connect=True requires a name")
            peer = connect_queue(name, retries=connect_retries)
            # Share the peer's underlying queues.
            self._queues = peer._queues
            self._num_queues = peer._num_queues
            self._maxsize = peer._maxsize
            self._name = name
            self._shutdown_event = peer._shutdown_event
            self._async_pool = peer._async_pool
            self._inflight_async = peer._inflight_async
            self._inflight_lock = peer._inflight_lock
            self._depth_gauges = peer._depth_gauges
            return
        if num_queues < 1:
            raise ValueError(f"num_queues must be >= 1, got {num_queues}")
        self._num_queues = num_queues
        self._maxsize = maxsize
        self._queues: List[BoundedFifo] = [
            BoundedFifo(maxsize=maxsize) for _ in range(num_queues)
        ]
        self._name = name
        self._shutdown_event = threading.Event()
        self._async_pool = cf.ThreadPoolExecutor(
            max_workers=2, thread_name_prefix="rsdl-queue-async")
        self._inflight_async: set = set()
        self._inflight_lock = threading.Lock()
        self._depth_gauges: dict = {}
        if name is not None:
            with _REGISTRY_LOCK:
                if name in _REGISTRY:
                    raise ValueError(f"queue name already registered: {name}")
                _REGISTRY[name] = self

    # -- introspection ------------------------------------------------------

    @property
    def num_queues(self) -> int:
        return self._num_queues

    def size(self, queue_index: int) -> int:
        """Approximate number of items in queue ``queue_index``.

        Also the liveness probe: the reference blocks on ``.size(0)`` to
        wait for the actor to come up (reference: dataset.py:106).
        """
        return self._queues[queue_index].qsize()

    def sizes(self, indices: Optional[List[int]] = None) -> List[int]:
        """Approximate depths of several queues in one pass (all of
        them when ``indices`` is None) — the serving plane's per-shard
        depth gauge reads its owned queues through this instead of N
        lock round trips through :meth:`size`."""
        queues = (self._queues if indices is None
                  else [self._queues[i] for i in indices])
        return [q.qsize() for q in queues]

    def _check_open(self) -> None:
        if self._shutdown_event.is_set():
            raise RuntimeError(f"MultiQueue {self._name!r} is shut down")

    def _note_depth(self, queue_index: int) -> None:
        """Refresh the per-queue depth gauge (the health plane's
        ``queue_saturation`` detector judges this series). Callers gate
        on a truthy ``stamp()`` so the hard-off telemetry path pays
        nothing extra."""
        gauge = self._depth_gauges.get(queue_index)
        if gauge is None:
            gauge = self._depth_gauges[queue_index] = rt_metrics.gauge(
                "rsdl_queue_depth", "items resident per queue",
                queue=str(queue_index))
        gauge.set(self._queues[queue_index].qsize())

    # -- puts ---------------------------------------------------------------

    def put(self, queue_index: int, item: Any, block: bool = True,
            timeout: Optional[float] = None) -> None:
        """Put one item (reference: multiqueue.py:98-125)."""
        # Fault site: fires before the enqueue, so an injected put fault
        # never half-delivers an item (chaos keyed by queue index).
        rt_faults.inject("queue_put", task=queue_index)
        self._check_open()
        # stamp() rebinds to a no-op with telemetry hard-off, so the
        # per-item clock reads vanish along with the record call
        # (hot-path audit, ISSUE 7).
        start = rt_telemetry.stamp()
        try:
            self._queues[queue_index].put(item, block=block, timeout=timeout)
        except Full:
            raise Full(f"queue {queue_index} is full")
        # Producer-side backpressure evidence: a long put means the
        # consumer (or a bounded queue) is the slow side.
        rt_telemetry.record("queue_put", task=queue_index,
                            dur_s=rt_telemetry.stamp() - start)
        if start:  # stamp() is 0.0 exactly when telemetry is hard-off
            self._note_depth(queue_index)

    def put_nowait(self, queue_index: int, item: Any) -> None:
        self.put(queue_index, item, block=False)

    def put_batch(self, queue_index: int, items: List[Any],
                  block: bool = True, timeout: Optional[float] = None) -> None:
        """Put many items FIFO (reference: multiqueue.py:127-154)."""
        self._check_open()
        for item in items:
            self.put(queue_index, item, block=block, timeout=timeout)

    def put_nowait_batch(self, queue_index: int, items: List[Any]) -> None:
        """All-or-nothing non-blocking batch put, atomic under concurrent
        producers (reference: multiqueue.py:374-381)."""
        self._check_open()
        try:
            self._queues[queue_index].put_batch_atomic(items)
        except Full as e:
            raise Full(f"queue {queue_index}: {e}")
        if rt_telemetry.stamp():
            self._note_depth(queue_index)

    def _submit_async(self, fn, *args) -> cf.Future:
        fut = self._async_pool.submit(fn, *args)
        with self._inflight_lock:
            self._inflight_async.add(fut)

        def _discard(f: cf.Future) -> None:
            # Done callbacks run on pool worker threads; an unlocked
            # discard here can race close()'s locked snapshot of the
            # set and blow up its list() copy mid-iteration.
            with self._inflight_lock:
                self._inflight_async.discard(f)

        fut.add_done_callback(_discard)
        return fut

    def put_async(self, queue_index: int, item: Any) -> cf.Future:
        """Async put; resolves when enqueued (reference: multiqueue.py's *_async)."""
        self._check_open()
        return self._submit_async(self.put, queue_index, item)

    # -- gets ---------------------------------------------------------------

    def get(self, queue_index: int, block: bool = True,
            timeout: Optional[float] = None) -> Any:
        """Pop one item (reference: multiqueue.py:185-214)."""
        # Fault site: fires before the dequeue — no item is consumed, so
        # the caller may retry (or crash, for checkpoint-resume chaos).
        rt_faults.inject("queue_get", task=queue_index)
        start = rt_telemetry.stamp()  # no-op clock read when hard-off
        try:
            item = self._queues[queue_index].get(block=block,
                                                 timeout=timeout)
        except Empty:
            raise Empty(f"queue {queue_index} is empty")
        rt_telemetry.record("queue_get", task=queue_index,
                            dur_s=rt_telemetry.stamp() - start)
        if start:
            self._note_depth(queue_index)
        return item

    def get_nowait(self, queue_index: int) -> Any:
        return self.get(queue_index, block=False)

    def get_nowait_batch(self, queue_index: int, num_items: int) -> List[Any]:
        """Pop exactly ``num_items`` without blocking or raise Empty
        (all-or-nothing, atomic under concurrent consumers,
        reference: multiqueue.py:270-283,383-390)."""
        try:
            items = self._queues[queue_index].get_batch_atomic(num_items)
        except Empty as e:
            raise Empty(f"queue {queue_index}: {e}")
        if rt_telemetry.stamp():
            self._note_depth(queue_index)
        return items

    def get_async(self, queue_index: int) -> cf.Future:
        """Async blocking get; resolves with the item."""
        return self._submit_async(self.get, queue_index)

    # -- lifecycle ----------------------------------------------------------

    def shutdown(self, force: bool = False, grace_period_s: float = 5.0) -> None:
        """Stop accepting puts, drop the name, release async workers.

        The graceful-then-forceful contract of the reference's actor kill
        (reference: multiqueue.py:285-307) maps to: refuse new puts
        immediately, wait up to ``grace_period_s`` for in-flight async ops,
        then cancel whatever remains. Items already enqueued stay readable;
        consumers *blocked* in ``get()`` (and producers blocked in ``put()``)
        are woken with :class:`ShutdownError` so no thread is stranded.
        """
        self._shutdown_event.set()
        for q in self._queues:
            q.close()
        if self._name is not None:
            with _REGISTRY_LOCK:
                _REGISTRY.pop(self._name, None)
        if not force:
            with self._inflight_lock:
                inflight = list(self._inflight_async)
            if inflight:
                cf.wait(inflight, timeout=grace_period_s)
        self._async_pool.shutdown(wait=False, cancel_futures=True)


def connect_queue(name: str,
                  retries: int = CONNECT_RETRIES,
                  initial_backoff_s: float = CONNECT_INITIAL_BACKOFF_S
                  ) -> "MultiQueue":
    """Look up a named queue with retry + doubling backoff
    (reference: multiqueue.py:310-332)."""
    backoff = initial_backoff_s
    for attempt in range(retries + 1):
        with _REGISTRY_LOCK:
            q = _REGISTRY.get(name)
        if q is not None:
            return q
        if attempt == retries:
            break
        logger.info("queue %r not found, retrying in %.1fs", name, backoff)
        time.sleep(backoff)
        backoff *= 2
    raise TimeoutError(
        f"could not connect to queue {name!r} after {retries} retries")
