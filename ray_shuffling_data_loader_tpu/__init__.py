"""TPU-native shuffling data loader.

A from-scratch JAX/TPU framework with the capabilities of
``vvksh/ray_shuffling_data_loader`` (see SURVEY.md): per-epoch map/reduce
shuffle over Parquet with epoch pipelining, multi-queue batch transport,
rank-aware iterable datasets, and an accelerator binding that lands batches
as sharded ``jax.Array``s in HBM — plus a seeded-PRNG determinism story,
loader checkpoint/resume, stats, and a benchmark harness.

Public exports mirror the reference's (reference: __init__.py:1-11).
"""

__version__ = "0.1.0"
