"""TPU-native shuffling data loader.

A from-scratch JAX/TPU framework with the capabilities of
``vvksh/ray_shuffling_data_loader`` (see SURVEY.md): per-epoch map/reduce
shuffle over Parquet with epoch pipelining, multi-queue batch transport,
rank-aware iterable datasets, and an accelerator binding that lands batches
as sharded ``jax.Array``s in HBM — plus a seeded-PRNG determinism story,
loader checkpoint/resume, stats, and a benchmark harness.

Public exports mirror the reference's (reference: __init__.py:1-11).
"""

__version__ = "0.1.0"

from ray_shuffling_data_loader_tpu.checkpoint import (  # noqa: E402,F401
    LoaderCheckpoint, TrainStateCheckpointer, resume_iterator)
from ray_shuffling_data_loader_tpu.dataset import (  # noqa: E402,F401
    ShufflingDataset, create_batch_queue_and_shuffle)
from ray_shuffling_data_loader_tpu.jax_dataset import (  # noqa: E402,F401
    JaxShufflingDataset)
from ray_shuffling_data_loader_tpu.multiqueue import (  # noqa: E402,F401
    Empty, Full, MultiQueue, ShutdownError)
from ray_shuffling_data_loader_tpu.multiqueue_service import (  # noqa: E402,F401
    RemoteQueue, serve_queue)
from ray_shuffling_data_loader_tpu.shuffle import (  # noqa: E402,F401
    shuffle, shuffle_with_stats, shuffle_no_stats)

# "TorchShufflingDataset" is importable by name via module __getattr__ but
# intentionally not in __all__: star-import must not require (or eagerly
# import) the optional torch dependency.
__all__ = [
    "ShufflingDataset",
    "JaxShufflingDataset",
    "MultiQueue",
    "Empty",
    "Full",
    "ShutdownError",
    "RemoteQueue",
    "serve_queue",
    "shuffle",
    "shuffle_with_stats",
    "shuffle_no_stats",
    "create_batch_queue_and_shuffle",
    "LoaderCheckpoint",
    "TrainStateCheckpointer",
    "resume_iterator",
    "__version__",
]


def __getattr__(name):
    # Lazy: importing torch costs seconds and most TPU users never need the
    # migration-compat binding (the reference exports it eagerly,
    # reference: __init__.py:1-11).
    if name == "TorchShufflingDataset":
        from ray_shuffling_data_loader_tpu.torch_dataset import (
            TorchShufflingDataset)
        return TorchShufflingDataset
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
