"""Tests for the cross-process queue service (multiqueue_service.py):
loopback protocol, drop-in dataset consumption, failure propagation, a
real separate-process trainer rendezvous, and the v3 serving plane
(shm-handle delivery, frame compression, plan-routed shards)."""

import subprocess
import sys
import threading

import pyarrow as pa
import pytest

from ray_shuffling_data_loader_tpu import data_generation as dg
from ray_shuffling_data_loader_tpu import multiqueue as mq
from ray_shuffling_data_loader_tpu import multiqueue_service as svc
from ray_shuffling_data_loader_tpu import stats as rsdl_stats
from ray_shuffling_data_loader_tpu.dataset import (ShuffleFailure,
                                                   ShufflingDataset,
                                                   connect_remote_queue,
                                                   create_batch_queue_and_shuffle)
from ray_shuffling_data_loader_tpu.plan import ir as plan_ir


def test_roundtrip_table_sentinel_failure():
    queue = mq.MultiQueue(2, name=None)
    table = pa.table({"x": [1, 2, 3]})
    queue.put(0, table)  # service accepts bare tables too
    queue.put(0, None)
    queue.put(1, ShuffleFailure(ValueError("boom")))
    with svc.serve_queue(queue) as server:
        with svc.RemoteQueue(server.address) as remote:
            got = remote.get(0)
            assert got.equals(table)
            assert remote.get(0) is None
            failure = remote.get(1)
            assert isinstance(failure, ShuffleFailure)
            assert "boom" in str(failure.error)


def test_remote_queue_rejects_nonblocking():
    queue = mq.MultiQueue(1, name=None)
    with svc.serve_queue(queue) as server:
        with svc.RemoteQueue(server.address) as remote:
            with pytest.raises(ValueError, match="blocking"):
                remote.get(0, block=False)


def test_connect_retry_fails_loudly():
    with pytest.raises(ConnectionError, match="could not reach"):
        svc.RemoteQueue(("127.0.0.1", 1), retries=1,
                        initial_backoff_s=0.01)


def test_remote_dataset_consumes_full_epochs(tmp_parquet_dir):
    """A ShufflingDataset fed by RemoteQueue sees every key exactly once
    per epoch — identical consumer code to the in-process path."""
    filenames, _ = dg.generate_data_local(200, 2, 1, 0.0, tmp_parquet_dir)
    num_epochs = 2
    queue, shuffle_result = create_batch_queue_and_shuffle(
        filenames, num_epochs, num_trainers=1, batch_size=40,
        max_concurrent_epochs=2, num_reducers=2, seed=7,
        queue_name="svc-test")
    with svc.serve_queue(queue) as server:
        remote = svc.RemoteQueue(server.address)
        ds = ShufflingDataset(filenames, num_epochs, num_trainers=1,
                              batch_size=40, rank=0, num_reducers=2,
                              batch_queue=remote, shuffle_result=None,
                              seed=7)
        for epoch in range(num_epochs):
            ds.set_epoch(epoch)
            keys = []
            for batch in ds:
                keys.extend(batch.column(dg.KEY_COLUMN).to_pylist())
            assert sorted(keys) == list(range(200))
        remote.close()
    shuffle_result.result()
    queue.shutdown()


def test_separate_process_trainer_rendezvous(tmp_parquet_dir):
    """The reference's signature topology: a trainer PROCESS with no
    handle to driver state attaches to the pipeline over the wire."""
    filenames, _ = dg.generate_data_local(120, 2, 1, 0.0, tmp_parquet_dir)
    queue, shuffle_result = create_batch_queue_and_shuffle(
        filenames, 1, num_trainers=1, batch_size=30,
        max_concurrent_epochs=1, num_reducers=2, seed=3,
        queue_name="svc-proc-test")
    with svc.serve_queue(queue) as server:
        host, port = server.address
        consumer = (
            "import sys\n"
            "from ray_shuffling_data_loader_tpu import multiqueue_service as svc\n"
            "from ray_shuffling_data_loader_tpu.dataset import ShufflingDataset\n"
            f"remote = svc.RemoteQueue(('{host}', {port}))\n"
            "ds = ShufflingDataset([], 1, num_trainers=1, batch_size=30,\n"
            "                      rank=0, num_reducers=2, batch_queue=remote,\n"
            "                      shuffle_result=None)\n"
            "ds.set_epoch(0)\n"
            "keys = []\n"
            "for batch in ds:\n"
            "    keys.extend(batch.column('key').to_pylist())\n"
            "print('ROWS', len(keys), 'UNIQUE', len(set(keys)))\n")
        proc = subprocess.run([sys.executable, "-c", consumer],
                              capture_output=True, text=True, timeout=120,
                              cwd="/root/repo")
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert "ROWS 120 UNIQUE 120" in proc.stdout, proc.stdout
    shuffle_result.result()
    queue.shutdown()


def test_concurrent_same_index_getters_preserve_fifo():
    """Two threads blocked on the SAME queue index must share one
    in-flight request: each consumer's observed sequence stays strictly
    increasing (global per-index FIFO), never inverted by a second
    racing round trip ingesting out of request order."""
    queue = mq.MultiQueue(1, name=None)
    n = 60
    for i in range(n):
        queue.put(0, pa.table({"seq": [i]}))
    queue.put(0, None)  # one sentinel per consumer thread
    queue.put(0, None)
    got: dict = {0: [], 1: []}
    errors: list = []
    with svc.serve_queue(queue) as server:
        # max_batch=1 keeps the client buffer empty after every pop, so
        # both threads are constantly in the blocked-on-fetch path the
        # fix serializes.
        with svc.RemoteQueue(server.address, max_batch=1) as remote:

            def consume(tid: int) -> None:
                try:
                    while True:
                        item = remote.get(0)
                        if item is None:
                            return
                        got[tid].append(item.column("seq")[0].as_py())
                except RuntimeError as e:
                    # Only the other thread draining the epoch sentinel is
                    # benign; any other RuntimeError must fail the test.
                    if "already yielded" not in str(e):
                        errors.append(e)
                except BaseException as e:  # noqa: BLE001 - surfaced below
                    errors.append(e)

            threads = [threading.Thread(target=consume, args=(t,),
                                        daemon=True) for t in range(2)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
                assert not t.is_alive(), "concurrent getter hung"
    if errors:
        raise errors[0]
    for tid in (0, 1):
        seq = got[tid]
        assert seq == sorted(seq), f"thread {tid} saw inverted order: {seq}"
    assert sorted(got[0] + got[1]) == list(range(n))


def test_failed_ref_crosses_wire_as_failure_frame():
    """A queued ref whose task failed reaches the remote consumer as a
    KIND_FAILURE frame carrying the real cause, not a dead socket."""
    from ray_shuffling_data_loader_tpu import executor as ex

    queue = mq.MultiQueue(1, name=None)
    with ex.Executor(num_workers=1) as pool:
        def boom():
            raise ValueError("real cause")
        ref = pool.submit(boom)
        with pytest.raises(ValueError):
            ref.result()
        queue.put(0, ref)
        with svc.serve_queue(queue) as server:
            with svc.RemoteQueue(server.address) as remote:
                failure = remote.get(0)
                assert isinstance(failure, ShuffleFailure)
                assert "real cause" in str(failure.error)


def test_jax_dataset_over_remote_queue(tmp_parquet_dir):
    """Full remote-trainer topology: RemoteQueue -> JaxShufflingDataset ->
    device-resident batches (the reference's Horovod-worker pattern)."""
    import numpy as np

    from ray_shuffling_data_loader_tpu.jax_dataset import JaxShufflingDataset

    filenames, _ = dg.generate_data_local(160, 2, 1, 0.0, tmp_parquet_dir)
    queue, shuffle_result = create_batch_queue_and_shuffle(
        filenames, 1, num_trainers=1, batch_size=40,
        max_concurrent_epochs=1, num_reducers=2, seed=11,
        queue_name="svc-jax-test")
    with svc.serve_queue(queue) as server:
        remote = svc.RemoteQueue(server.address)
        ds = JaxShufflingDataset(
            filenames, num_epochs=1, num_trainers=1, batch_size=40, rank=0,
            num_reducers=2, batch_queue=remote, shuffle_result=None,
            feature_columns=list(dg.FEATURE_COLUMNS),
            feature_types=[np.int32] * len(dg.FEATURE_COLUMNS),
            label_column=dg.LABEL_COLUMN, drop_last=True)
        ds.set_epoch(0)
        rows = 0
        for features, label in ds:
            assert features[0].shape == (40, 1)
            rows += label.shape[0]
        assert rows == 160
        remote.close()
    shuffle_result.result()
    queue.shutdown()


def test_jax_dataset_over_remote_queue_device_rebatch(tmp_parquet_dir):
    """Remote-trainer topology with device re-batching forced on: tables
    materialized over the wire flow through the bulk-chunk producer and
    yield the same batch stream as the per-batch path."""
    import numpy as np

    from ray_shuffling_data_loader_tpu.jax_dataset import JaxShufflingDataset

    filenames, _ = dg.generate_data_local(160, 2, 1, 0.0, tmp_parquet_dir)

    def run(device_rebatch, qname):
        queue, shuffle_result = create_batch_queue_and_shuffle(
            filenames, 1, num_trainers=1, batch_size=40,
            max_concurrent_epochs=1, num_reducers=2, seed=11,
            queue_name=qname)
        with svc.serve_queue(queue) as server:
            remote = svc.RemoteQueue(server.address)
            ds = JaxShufflingDataset(
                filenames, num_epochs=1, num_trainers=1, batch_size=40,
                rank=0, num_reducers=2, batch_queue=remote,
                shuffle_result=None,
                feature_columns=list(dg.FEATURE_COLUMNS),
                feature_types=[np.int32] * len(dg.FEATURE_COLUMNS),
                label_column=dg.LABEL_COLUMN, drop_last=True,
                device_rebatch=device_rebatch)
            ds.set_epoch(0)
            out = [(tuple(np.asarray(f) for f in feats), np.asarray(lb))
                   for feats, lb in ds]
            remote.close()
        shuffle_result.result()
        queue.shutdown()
        return out

    host = run(False, "svc-jax-drb-host")
    dev = run(True, "svc-jax-drb-dev")
    assert len(host) == len(dev) == 4
    for (fa, la), (fb, lb) in zip(host, dev):
        for x, y in zip(fa, fb):
            np.testing.assert_array_equal(x, y)
        np.testing.assert_array_equal(la, lb)


def test_two_remote_trainer_ranks_drain_their_own_queues(tmp_parquet_dir):
    """The reference's multi-GPU topology over the wire: two trainer
    ranks, each with its OWN RemoteQueue connection, drain their own
    per-rank queues of one shuffle concurrently — every key exactly once
    across the pair, none crossing ranks (queue id contract
    epoch*num_trainers+rank, reference: dataset.py:173)."""
    filenames, _ = dg.generate_data_local(300, 3, 1, 0.0, tmp_parquet_dir)
    num_epochs = 2
    queue, shuffle_result = create_batch_queue_and_shuffle(
        filenames, num_epochs, num_trainers=2, batch_size=25,
        max_concurrent_epochs=2, num_reducers=4, seed=13,
        queue_name="svc-two-ranks")
    per_rank: dict = {}
    errors: list = []
    with svc.serve_queue(queue) as server:

        def consume(rank: int) -> None:
            try:
                with svc.RemoteQueue(server.address, max_batch=3) as remote:
                    ds = ShufflingDataset(
                        filenames, num_epochs, num_trainers=2,
                        batch_size=25, rank=rank, batch_queue=remote,
                        shuffle_result=None, seed=13)
                    for epoch in range(num_epochs):
                        ds.set_epoch(epoch)
                        keys = []
                        for batch in ds:
                            keys.extend(
                                batch.column(dg.KEY_COLUMN).to_pylist())
                        per_rank[(rank, epoch)] = keys
            except BaseException as e:  # noqa: BLE001 - surfaced below
                errors.append(e)

        # daemon=True + shutdown in finally: a genuinely hung rank must
        # fail the test, not strand a non-daemon thread blocked in
        # socket recv that keeps pytest alive forever at exit.
        threads = [threading.Thread(target=consume, args=(r,), daemon=True)
                   for r in range(2)]
        try:
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
                assert not t.is_alive(), "remote rank hung"
        finally:
            queue.shutdown()
    if errors:
        raise errors[0]
    for epoch in range(num_epochs):
        union = sorted(per_rank[(0, epoch)] + per_rank[(1, epoch)])
        assert union == list(range(300)), f"epoch {epoch} coverage broken"
        assert per_rank[(0, epoch)] and per_rank[(1, epoch)]
    shuffle_result.result()


# ---------------------------------------------------------------------------
# v3 serving plane: shm-handle delivery, compression, shards
# ---------------------------------------------------------------------------


def test_handle_delivery_cuts_wire_bytes_10x():
    """Same-host consumers get segment handles, not table bytes: the
    payload bytes on the wire collapse by >= 10x vs the logical table
    flow (the acceptance-criteria ratio, asserted at the unit level)."""
    queue = mq.MultiQueue(1, name=None)
    table = pa.table({"x": list(range(40_000))})
    queue.put(0, table)
    queue.put(0, None)
    before = rsdl_stats.queue_serve_totals()
    with svc.serve_queue(queue) as server:
        with svc.RemoteQueue(server.address) as remote:
            got = remote.get(0)
            assert got.equals(table)
            assert remote.get(0) is None
    after = rsdl_stats.queue_serve_totals()
    payload = after["queue_payload_bytes"] - before["queue_payload_bytes"]
    wire = after["queue_bytes_on_wire"] - before["queue_bytes_on_wire"]
    hits = after["queue_handle_hits"] - before["queue_handle_hits"]
    assert hits == 1
    assert payload > 0 and wire * 10 <= payload, (payload, wire)


def test_stream_delivery_forced_still_bit_identical():
    queue = mq.MultiQueue(1, name=None)
    table = pa.table({"x": list(range(10_000))})
    queue.put(0, table)
    queue.put(0, None)
    before = rsdl_stats.queue_serve_totals()
    with svc.serve_queue(queue) as server:
        with svc.RemoteQueue(server.address, delivery="stream") as remote:
            assert remote.get(0).equals(table)
            assert remote.get(0) is None
    after = rsdl_stats.queue_serve_totals()
    assert after["queue_handle_hits"] == before["queue_handle_hits"]
    assert (after["queue_handle_misses"]
            > before["queue_handle_misses"])
    # Streamed: every payload byte rides the socket.
    wire = after["queue_bytes_on_wire"] - before["queue_bytes_on_wire"]
    payload = after["queue_payload_bytes"] - before["queue_payload_bytes"]
    assert wire == payload > 0


def test_compression_round_trip_and_ratio(monkeypatch):
    """zlib frame compression (stream delivery): CRC is computed
    pre-compression, the stream decodes bit-identical, and the saved
    bytes land in the per-shard counter."""
    monkeypatch.setenv("RSDL_QUEUE_COMPRESSION", "zlib")
    monkeypatch.setenv("RSDL_QUEUE_COMPRESSION_MIN_BYTES", "64")
    queue = mq.MultiQueue(1, name=None)
    table = pa.table({"x": [42] * 50_000})  # compresses hard
    queue.put(0, table)
    queue.put(0, None)
    before = rsdl_stats.queue_serve_totals()
    with svc.serve_queue(queue) as server:
        with svc.RemoteQueue(server.address, delivery="stream") as remote:
            assert remote.get(0).equals(table)
            assert remote.get(0) is None
    after = rsdl_stats.queue_serve_totals()
    saved = (after["queue_compression_saved_bytes"]
             - before["queue_compression_saved_bytes"])
    wire = after["queue_bytes_on_wire"] - before["queue_bytes_on_wire"]
    payload = after["queue_payload_bytes"] - before["queue_payload_bytes"]
    assert saved > 0 and wire < payload
    assert wire + saved == payload


def test_compression_with_chaos_corruption_recovers(monkeypatch):
    """A corrupted COMPRESSED frame is NACK'd and replayed exactly-once:
    pre-compression CRC keeps the v2 corruption matrix intact."""
    from ray_shuffling_data_loader_tpu.runtime import faults as rt_faults
    monkeypatch.setenv("RSDL_QUEUE_COMPRESSION", "zlib")
    monkeypatch.setenv("RSDL_QUEUE_COMPRESSION_MIN_BYTES", "64")
    queue = mq.MultiQueue(1, name=None)
    for i in range(6):
        queue.put(0, pa.table({"seq": [i] * 500}))
    queue.put(0, None)
    rt_faults.install("frame_corrupt:task0:after2", seed=0)
    try:
        with svc.serve_queue(queue) as server:
            with svc.RemoteQueue(server.address, delivery="stream",
                                 max_batch=2) as remote:
                seen = []
                while True:
                    item = remote.get(0)
                    if item is None:
                        break
                    seen.append(item.column("seq")[0].as_py())
        assert seen == list(range(6))
    finally:
        rt_faults.clear()


def test_handle_downgrade_on_unusable_segment(monkeypatch):
    """A consumer that cannot map the server's segments NACKs with
    NACK_NO_HANDLE; the server downgrades the queue to streamed bytes
    and replays the same frames — delivery degrades, exactly-once does
    not."""
    real_read = svc.pp.read_segment_buffer
    calls = {"n": 0}

    def flaky_read(path):
        calls["n"] += 1
        if calls["n"] == 1:  # the CLIENT's first handle open
            raise OSError("simulated foreign-host segment path")
        return real_read(path)

    monkeypatch.setattr(svc.pp, "read_segment_buffer", flaky_read)
    queue = mq.MultiQueue(1, name=None)
    tables = [pa.table({"seq": [i] * 100}) for i in range(4)]
    for t in tables:
        queue.put(0, t)
    queue.put(0, None)
    with svc.serve_queue(queue) as server:
        with svc.RemoteQueue(server.address, delivery="handle",
                             max_batch=2) as remote:
            seen = []
            while True:
                item = remote.get(0)
                if item is None:
                    break
                seen.append(item.column("seq")[0].as_py())
    assert seen == [0, 1, 2, 3]
    assert calls["n"] >= 2  # the server's downgrade re-read happened


def test_sharded_server_routes_by_plan_and_rejects_foreign_queues():
    num_trainers, num_epochs = 2, 2
    queue = mq.MultiQueue(num_trainers * num_epochs, name=None)
    for epoch in range(num_epochs):
        for rank in range(num_trainers):
            qi = plan_ir.queue_index(epoch, rank, num_trainers)
            queue.put(qi, pa.table({"v": [qi]}))
            queue.put(qi, None)
    with svc.serve_queue_sharded(queue, num_shards=2,
                                 num_trainers=num_trainers) as sharded:
        assert sharded.shard_map.num_shards == 2
        # JSON round trip: what the supervisor hands a trainer process.
        remote = svc.ShardedRemoteQueue(sharded.shard_map.to_json())
        for epoch in range(num_epochs):
            for rank in range(num_trainers):
                qi = plan_ir.queue_index(epoch, rank, num_trainers)
                assert remote.get(qi).column("v")[0].as_py() == qi
                assert remote.get(qi) is None
        remote.close()
        # A GET for a queue the shard does not own fails loudly.
        wrong = svc.RemoteQueue(tuple(sharded.shard_map.addresses[0]))
        foreign = plan_ir.queue_index(0, 1, num_trainers)  # rank 1
        got = wrong.get(foreign)
        assert isinstance(got, ShuffleFailure)
        assert "not served by shard" in str(got.error)
        wrong.close()


def test_sharded_dataset_consumes_both_ranks(tmp_parquet_dir):
    """End to end: one shuffle, two trainer ranks, two serving shards —
    each rank's ShufflingDataset drains through a ShardedRemoteQueue
    (via connect_remote_queue) and coverage holds per epoch."""
    filenames, _ = dg.generate_data_local(200, 2, 1, 0.0, tmp_parquet_dir)
    num_epochs = 2
    queue, shuffle_result = create_batch_queue_and_shuffle(
        filenames, num_epochs, num_trainers=2, batch_size=25,
        max_concurrent_epochs=2, num_reducers=4, seed=21,
        queue_name="svc-sharded-ds")
    per_rank: dict = {}
    errors: list = []
    with svc.serve_queue_sharded(queue, num_shards=2,
                                 num_trainers=2) as sharded:

        def consume(rank: int) -> None:
            try:
                with connect_remote_queue(sharded.shard_map,
                                          max_batch=3) as remote:
                    ds = ShufflingDataset(
                        filenames, num_epochs, num_trainers=2,
                        batch_size=25, rank=rank, batch_queue=remote,
                        shuffle_result=None, seed=21)
                    for epoch in range(num_epochs):
                        ds.set_epoch(epoch)
                        keys = []
                        for batch in ds:
                            keys.extend(
                                batch.column(dg.KEY_COLUMN).to_pylist())
                        per_rank[(rank, epoch)] = keys
            except BaseException as e:  # noqa: BLE001 - surfaced below
                errors.append(e)

        threads = [threading.Thread(target=consume, args=(r,),
                                    daemon=True) for r in range(2)]
        try:
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
                assert not t.is_alive(), "sharded rank hung"
        finally:
            queue.shutdown()
    if errors:
        raise errors[0]
    for epoch in range(num_epochs):
        union = sorted(per_rank[(0, epoch)] + per_rank[(1, epoch)])
        assert union == list(range(200)), f"epoch {epoch} coverage broken"
    shuffle_result.result()
